"""Benchmark: elastic resize latency on a localhost CPU cluster.

Prints ONE JSON line:
  {"metric": "elastic_resize_latency", "value": N, "unit": "ms", ...}

Parity: the reference's resize-latency harness ("resize %d -> %d took %s",
benchmarks/adaptation/adaptive_trainer.py:98-103 + the ResizeProfiler in
experimental/hook/elastic.py) — BASELINE.md's second north-star metric.
Latency = wall time of one propose->consensus->respawn->rejoin->barrier
cycle as observed by a surviving worker (from calling resize to the new
session's first completed collective).

vs_baseline: the reference publishes no number; we report the measured
value with vs_baseline=1.0 as the self-referenced anchor for tracking
regressions round over round.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.abspath(__file__))

AGENT = r'''
import os, sys, time
_t_start = time.monotonic()
_t_act = float(os.environ.get("KF_ACTIVATED_TS", 0) or 0)
import numpy as np
from kungfu_tpu import api
from kungfu_tpu.elastic.state import ElasticState
_t_imports = time.monotonic()
if _t_act:
    print(f"JOINER wakeup={((_t_start-_t_act)*1e3):.1f} ms"
          f" imports={((_t_imports-_t_start)*1e3):.1f} ms", flush=True)

SIZES = [2, 3, 4, 2, 3, 4, 2]
es = ElasticState(max_progress=len(SIZES) * 10)
_su = api.trace_summary()
if _su.get("worker.startup"):
    print(
        f"JOINSTART {_su['worker.startup']:.1f} ms"
        f" parse={_su.get('worker.parse_config', 0):.1f}"
        f" init={_su.get('worker.peer_init', 0):.1f}"
        f" server={_su.get('worker.start.server', 0):.1f}"
        f" update={_su.get('worker.start.update', 0):.1f}",
        flush=True,
    )
t_resize = None
while not es.stopped():
    with es.scope():
        rank, size = api.current_rank(), api.cluster_size()
        step = es.progress
        if step % 10 == 0 and rank == 0:
            target = SIZES[(step // 10) % len(SIZES)]
            if target != size:
                api.propose_new_size(target)
        time.sleep(0.4)  # stand-in for a real train step: preemption-driven
        # resizes are minutes apart in the BASELINE scenario, so warm
        # spares have warmed by the time a join needs one
        t0 = time.perf_counter()
        before = size
        es.end(1)
        # es.end ran resize(); if membership changed, the new session's
        # barrier already completed inside _update_to -> this is the full
        # resize cost as seen by a survivor
        if not es.stopped() and api.cluster_size() != before:
            dt = (time.perf_counter() - t0) * 1000
            import json as _json
            phases = _json.dumps(api.last_resize_phases())
            print(f"RESIZE {before} -> {api.cluster_size()} took {dt:.1f} ms"
                  f" phases={phases}", flush=True)
print(f"done rank={api.current_rank()} reason={es.stop_reason}", flush=True)
'''


def main() -> None:
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(AGENT)
        agent_path = f.name
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "kungfu_tpu.runner.cli",
                "-np", "2",
                "-H", "127.0.0.1:4",
                "-w",
                "-warm-spares", "2",
                "-builtin-config-port", "0",
                "--", sys.executable, agent_path,
            ],
            env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
        )
    finally:
        os.unlink(agent_path)
    lat = [float(m) for m in re.findall(r"took ([0-9.]+) ms", r.stdout)]
    # per-phase medians (wait_config / consensus / notify / update)
    phase_samples: dict = {}
    for m in re.findall(r"phases=(\{[^}]*\})", r.stdout):
        for k, v in json.loads(m).items():
            phase_samples.setdefault(k, []).append(float(v))
    phase_medians = {
        k: sorted(v)[len(v) // 2] for k, v in sorted(phase_samples.items())
    }
    if r.returncode != 0 or not lat:
        print(json.dumps({
            "metric": "elastic_resize_latency",
            "value": -1,
            "unit": "ms",
            "vs_baseline": 0,
            "error": (r.stdout + r.stderr)[-400:],
        }))
        sys.exit(1)
    lat.sort()
    median = lat[len(lat) // 2]
    print(json.dumps({
        "metric": "elastic_resize_latency",
        "value": round(median, 1),
        "unit": "ms",
        "vs_baseline": 1.0,
        "n_resizes": len(lat),
        "min_ms": round(lat[0], 1),
        "max_ms": round(lat[-1], 1),
        "phase_median_ms": phase_medians,
    }))


if __name__ == "__main__":
    main()
