"""Benchmark: ResNet-50 training throughput (images/sec/chip) on TPU,
running through the framework's own training path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The step is built the way users build it: a `jax.sharding.Mesh` over all
chips, `shard_map` SPMD, and the `synchronous_sgd` optimizer wrapper whose
traced `pmean` is the framework's gradient AllReduce (one chip degenerates
to an identity reduce, but the compiled program is the real S-SGD path).
Cross-replica batch-norm stats are pmean-synced like the gradients.

Execution shape (round 5): the host loop dispatches ONE jit call that
`lax.scan`s over INNER distinct pre-staged batches — the standard TPU
train-loop pattern (amortizes per-dispatch latency, which is ~5-7 ms
through this host's device tunnel). Batches are distinct per scan step so
XLA cannot hoist per-batch input transforms out of the loop; inputs are
fed bfloat16.

Profile note (round-5 trace, jax.profiler on the real chip): the device
step is bandwidth-bound, not compute-bound. Per 47 ms device step at
batch 128: conv fusions ~21 ms running at ~65% sustained MXU efficiency
(the chip's measured large-matmul ceiling), batch-norm statistic
reductions (convert_reduce fusions) ~22 ms, maxpool backward
(select_and_scatter) ~0.7 ms. The norm reductions are HBM-limited: a
GroupNorm variant times identically, and neither MXU-dot-based stats nor
layout changes move it — XLA's cost model puts the step's arithmetic
intensity at ~70 FLOP/byte, below the v5e compute/bandwidth ratio of 240,
so the roofline is memory bandwidth.

MFU convention: FLOPs = multiplies + adds (2 FLOPs per MAC), the standard
MFU accounting (PaLM appendix / scaling-book). ResNet-50 forward at
224x224 is 4.1 GMACs = 8.2 GFLOPs/img; training ~= 3x forward = 24.6
GFLOPs/img. This matches XLA's own cost analysis of the compiled step
(3.06e12 flops / 128 imgs = 23.9 GFLOPs/img), which we use when
available. (Rounds 1-4 divided by peak using MAC counts — i.e. reported
half the standard-convention MFU.) `mfu_macs` preserves the old
accounting for cross-round comparability.

Baseline: the reference's headline workload is ResNet-50 synchronous SGD
(README "Benchmark", 16x V100). Published-era per-GPU throughput for
TF ResNet-50 fp32 on V100 is ~350 images/sec (the regime of the
reference's charts, benchmarks/system/result/sync-scalability.svg);
vs_baseline = our images/sec/chip / 350.

Second metric (resize latency, BASELINE.md north star #2): bench_resize.py.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from kungfu_tpu.parallel._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

BASELINE_IMG_PER_SEC = 350.0  # TF ResNet-50 fp32 on V100, reference era
INNER = 16  # scanned train steps per dispatch


def main() -> None:
    from kungfu_tpu.models.resnet import init_resnet, resnet50, resnet_loss
    from kungfu_tpu.optimizers import synchronous_sgd
    from kungfu_tpu.parallel import make_mesh

    n_chips = jax.device_count()
    per_chip_batch = 128
    batch = per_chip_batch * n_chips
    image_size = 224
    model = resnet50(num_classes=1000)
    key = jax.random.PRNGKey(0)
    params, batch_stats = init_resnet(key, model, image_size, batch=2)

    mesh = make_mesh({"dp": n_chips})
    opt = synchronous_sgd(optax.sgd(0.1, momentum=0.9), axis_name="dp")
    opt_state = opt.init(params)

    def local_loop(params, batch_stats, opt_state, images, labels):
        """INNER training steps over distinct batches, one dispatch."""

        def one(carry, batch_data):
            params, batch_stats, opt_state = carry

            def loss_fn(p):
                return resnet_loss(model, p, batch_stats, batch_data)

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            # synchronous_sgd's update pmeans the grads over dp (the AllReduce)
            updates, opt_state2 = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # cross-replica BN stats, like the gradient sync
            new_stats = jax.tree.map(lambda x: lax.pmean(x, "dp"), new_stats)
            return (params, new_stats, opt_state2), lax.pmean(loss, "dp")

        (params, batch_stats, opt_state), losses = lax.scan(
            one, (params, batch_stats, opt_state), (images, labels)
        )
        return params, batch_stats, opt_state, losses[-1]

    step = jax.jit(
        shard_map(
            local_loop,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(None, "dp"), P(None, "dp")),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1, 2),
    )

    sharded = NamedSharding(mesh, P(None, "dp"))
    # INNER distinct bf16 batches, staged on device once (synthetic data,
    # like the reference's benchmark harness)
    images = jax.device_put(
        jax.random.normal(
            key, (INNER, batch, image_size, image_size, 3), jnp.bfloat16
        ),
        sharded,
    )
    labels = jax.device_put(
        jnp.zeros((INNER, batch), jnp.int32), sharded
    )

    # FLOPs of the compiled step from XLA's cost model (per-image), with
    # the standard-convention constant as fallback
    train_flops_per_img = 24.6e9
    try:
        ca = step.lower(
            params, batch_stats, opt_state, images, labels
        ).compile().cost_analysis()
        ca0 = ca if isinstance(ca, dict) else ca[0]
        xla_flops = float(ca0.get("flops", 0.0))
        # XLA's cost model counts the scan (while-loop) body ONCE, not per
        # trip, so the per-image figure divides by batch only. Sanity-clamp
        # to the analytic constant in case that convention changes.
        cand = xla_flops / batch
        if 0.5 * train_flops_per_img <= cand <= 2.0 * train_flops_per_img:
            train_flops_per_img = cand
    except Exception:
        pass

    # warmup/compile; device_get forces real completion (block_until_ready
    # does not block on the axon tunnel backend)
    for _ in range(2):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    float(jax.device_get(loss))

    # best-of-windows: the minimum over several dispatches rejects
    # interference from other tenants of the host (timeit-min methodology)
    best_dt = float("inf")
    for _ in range(6):
        t0 = time.perf_counter()
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
        float(jax.device_get(loss))
        best_dt = min(best_dt, (time.perf_counter() - t0) / INNER)

    per_chip = per_chip_batch / best_dt
    peaks = {"v2": 46e12, "v3": 123e12, "v4": 275e12, "v5 lite": 197e12,
             "v5e": 197e12, "v5p": 459e12, "v6": 918e12}
    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in peaks.items() if k in kind), 197e12)
    mfu = per_chip * train_flops_per_img / peak
    print(
        json.dumps(
            {
                "metric": "resnet50_ssgd_train_throughput_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC, 3),
                "step_ms": round(best_dt * 1e3, 2),
                "mfu": round(mfu, 4),
                "mfu_macs": round(mfu / 2.0, 4),
                "flops_per_img": round(train_flops_per_img / 1e9, 1),
                "device": jax.devices()[0].device_kind,
            }
        )
    )


if __name__ == "__main__":
    main()
