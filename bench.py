"""Benchmark: ResNet-50 training throughput (images/sec/chip) on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference's headline workload is ResNet-50 synchronous SGD
(README "Benchmark", 16x V100). Published-era per-GPU throughput for
TF ResNet-50 fp32 on V100 is ~350 images/sec (the regime of the
reference's charts, benchmarks/system/result/sync-scalability.svg);
vs_baseline = our images/sec/chip / 350.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

BASELINE_IMG_PER_SEC = 350.0  # TF ResNet-50 fp32 on V100, reference era


def main() -> None:
    from kungfu_tpu.models.resnet import init_resnet, resnet50, resnet_loss

    batch = 128
    image_size = 224
    model = resnet50(num_classes=1000)
    key = jax.random.PRNGKey(0)
    params, batch_stats = init_resnet(key, model, image_size, batch=2)

    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)

    images = jax.random.normal(key, (batch, image_size, image_size, 3), jnp.float32)
    labels = jnp.zeros((batch,), jnp.int32)

    @jax.jit
    def step(params, batch_stats, opt_state, batch_data):
        def loss_fn(p):
            return resnet_loss(model, p, batch_stats, batch_data)

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state2, loss

    # warmup/compile; device_get forces real completion (block_until_ready
    # does not block on the axon tunnel backend)
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, (images, labels)
        )
    float(jax.device_get(loss))

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, (images, labels)
        )
    float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    img_per_sec = batch * iters / dt
    n_chips = jax.device_count()
    per_chip = img_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "resnet50_train_throughput_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
