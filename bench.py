"""Benchmark: ResNet-50 training throughput (images/sec/chip) on TPU,
running through the framework's own training path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The step is built the way users build it: a `jax.sharding.Mesh` over all
chips, `shard_map` SPMD, and the `synchronous_sgd` optimizer wrapper whose
traced `pmean` is the framework's gradient AllReduce (one chip degenerates
to an identity reduce, but the compiled program is the real S-SGD path).
Cross-replica batch-norm stats are pmean-synced like the gradients.

Baseline: the reference's headline workload is ResNet-50 synchronous SGD
(README "Benchmark", 16x V100). Published-era per-GPU throughput for
TF ResNet-50 fp32 on V100 is ~350 images/sec (the regime of the
reference's charts, benchmarks/system/result/sync-scalability.svg);
vs_baseline = our images/sec/chip / 350. Both runs here are fp32
parameters (matmuls ride the MXU in bf16 via XLA's default precision,
the TPU-native equivalent of the V100's tensor-core fp16 accumulate).

Second metric (resize latency, BASELINE.md north star #2): bench_resize.py.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

BASELINE_IMG_PER_SEC = 350.0  # TF ResNet-50 fp32 on V100, reference era


def main() -> None:
    from kungfu_tpu.models.resnet import init_resnet, resnet50, resnet_loss
    from kungfu_tpu.optimizers import synchronous_sgd
    from kungfu_tpu.parallel import make_mesh

    n_chips = jax.device_count()
    per_chip_batch = 128
    batch = per_chip_batch * n_chips
    image_size = 224
    model = resnet50(num_classes=1000)
    key = jax.random.PRNGKey(0)
    params, batch_stats = init_resnet(key, model, image_size, batch=2)

    mesh = make_mesh({"dp": n_chips})
    opt = synchronous_sgd(optax.sgd(0.1, momentum=0.9), axis_name="dp")
    opt_state = opt.init(params)

    def local_step(params, batch_stats, opt_state, batch_data):
        def loss_fn(p):
            return resnet_loss(model, p, batch_stats, batch_data)

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # synchronous_sgd's update pmeans the grads over dp (the AllReduce)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # cross-replica BN stats, like the gradient sync
        new_stats = jax.tree.map(lambda x: lax.pmean(x, "dp"), new_stats)
        return params, new_stats, opt_state2, lax.pmean(loss, "dp")

    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(), P("dp")),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1, 2),
    )

    sharded = NamedSharding(mesh, P("dp"))
    images = jax.device_put(
        jax.random.normal(key, (batch, image_size, image_size, 3), jnp.float32),
        sharded,
    )
    labels = jax.device_put(jnp.zeros((batch,), jnp.int32), sharded)

    # warmup/compile; device_get forces real completion (block_until_ready
    # does not block on the axon tunnel backend)
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, (images, labels)
        )
    float(jax.device_get(loss))

    # best-of-windows: the minimum over several short windows rejects
    # interference from other tenants of the host (timeit-min methodology)
    best_dt = float("inf")
    for _ in range(8):
        iters = 8
        t0 = time.perf_counter()
        for _ in range(iters):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, (images, labels)
            )
        float(jax.device_get(loss))
        best_dt = min(best_dt, (time.perf_counter() - t0) / iters)

    per_chip = per_chip_batch / best_dt
    # MFU: ResNet-50 training ~= 3x forward FLOPs; forward ~= 4.1 GFLOP/img
    # at 224x224 -> ~12.3 GFLOP/img. Peak bf16 FLOP/s by chip generation.
    train_flops_per_img = 12.3e9
    peaks = {"v2": 46e12, "v3": 123e12, "v4": 275e12, "v5 lite": 197e12,
             "v5e": 197e12, "v5p": 459e12, "v6": 918e12}
    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in peaks.items() if k in kind), 197e12)
    mfu = per_chip * train_flops_per_img / peak
    print(
        json.dumps(
            {
                "metric": "resnet50_ssgd_train_throughput_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC, 3),
                "step_ms": round(best_dt * 1e3, 2),
                "mfu": round(mfu, 4),
                "device": jax.devices()[0].device_kind,
            }
        )
    )


if __name__ == "__main__":
    main()
