"""Benchmark: block-scaled int8/int4 wire codec with error feedback
(BENCH_HOST_r17, ISSUE 20).

Three in-process experiments, one JSON line each:

1. ``k8_wire_precision_ab`` — k=8 across 2 virtual hosts (contiguous
   rank->host), per-edge DCN shape on cross-host edges (lat:2,
   bw:2MiB) plus one shared 32 MiB/s uplink bucket per host. Blocks of
   timed lockstep allreduce rounds cycle bf16 -> int8 -> int4 three
   times so box drift cancels from the ratios; every precision flip
   goes through the production lockstep ``check_precision`` majority
   vote (digest-checked, residual-flushing — the same path the
   precision policy drives). Wire bytes per codec are read off the
   ``kungfu_collective_wire_bytes_total{codec=...}`` counters and
   divided by the raw 2(k-1)N payload a segmented allreduce moves, so
   the compression ratio is MEASURED, not derived. Acceptance:
   int8 >= 1.3x over bf16 round time; int8 and int4 wire bytes
   <= 0.45x raw payload; every round's result bit-identical across all
   8 peers (each segment is quantized ONCE by its owner).

2. ``k8_zero_weight_ab`` — same shape; the ZeRO-1 sharded-update leg.
   Each peer drives a real ``ShardedUpdateSession`` step (pack ->
   reduce-scatter -> shard update -> weight all-gather -> scatter)
   over a 1 MiB parameter set; both the gradient reduce-scatter and
   the weight all-gather ride the quantized codec, with per-shard
   error-feedback residuals (``_Bucket.wres``) telescoping the weight
   quantization error across steps. Blocks alternate bf16/int8/int4
   via the same lockstep vote; params must stay bit-identical across
   peers after every block.

3. ``k8_precision_vote_ledger`` — the full voted-knob lifecycle, driven
   by the per-peer ``PrecisionPolicy`` stack end-to-end: a high
   measured noise scale (B_noise >> B) makes every peer's policy
   propose int8, the lockstep vote flips the cluster, and the decision
   ledger's ``precision_switch`` record grades the flip from measured
   step times (expect ``delivered`` — the shaped path got faster).
   Then the harness turns the noise signal down, the policies vote the
   wire back UP to bf16, and on this bandwidth-starved path that
   upshift genuinely regresses throughput: the ledger closes the
   record ``regressed``, ``decision/regressed`` surfaces it, and the
   policy votes straight back to int8 (trigger=regression_rollback),
   then HOLDS the bf16 target through the cooldown window instead of
   thrashing.

All legs run real Peer transports (sockets + the shaping layer) in one
process; per-message Python overhead serializes on the GIL for every
leg of each A/B alike, and the shaped-bandwidth term each codec pays is
proportional to its wire bytes — exactly the term the quantized codec
shrinks on a real DCN path. Not a pytest module: run directly
(`python bench_wire_q.py`).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

os.environ["KF_CONFIG_SHM"] = "0"       # sockets, so shaping applies
os.environ["KF_DECISION_WINDOW"] = "4"  # ledger measurement window
os.environ["KF_DECISION_SETTLE"] = "1"
os.environ["KF_CONFIG_WIRE"] = "bf16"   # baseline codec at session start
os.environ["KF_TELEMETRY"] = "metrics"  # wire-byte counters are the point

import numpy as np

from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.cmd import _reserve_ports
from kungfu_tpu.collective.host_session import HostSession
from kungfu_tpu.peer import Peer
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.runner.env import WorkerConfig
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu.transport import shaping

HostSession.SEGMENT_MIN_BYTES = 0
HostSession.WIRE_MIN_BYTES = 0
# Tight pacing for the bench (same rationale as bench_hier.py): the
# default 20ms burst credit refills between rounds and would let small
# payloads ride the burst without ever paying the shaped bandwidth.
shaping.BURST_SECONDS = 0.002
shaping.BURST_MIN_BYTES = 4 << 10

K = 8
HOSTS = 2
N = 256 * 1024          # 1 MiB f32 payload
MODES = ("bf16", "int8", "int4")
# loose per-mode value tolerance for a CONSTANT input vector: bf16 is
# exact on small integers; one quantized round-trip per hop errs at
# most half a scale step (scale = pow2(absmax/Qmax)), compounded over
# the 2(k-1) segmented hops — the tight drift bound lives in
# tests/test_wire_codec.py, this bound just catches gross breakage
TOL_REL = {"bf16": 1e-6, "int8": 0.05, "int4": 0.35}


def _run_on_all(fns, join=300):
    errs = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join)
        assert not t.is_alive(), "collective hung"
    if errs:
        raise errs[0]


def _mk_cluster():
    """k=8 over 2 virtual hosts with shaped cross-host edges and shared
    per-host uplink buckets; returns (cluster, sessions, labels)."""
    host_of = lambda r: r // 4  # noqa: E731 - contiguous: 2 hosts x 4
    tdir = tempfile.mkdtemp(prefix="kf-bench-wireq-")
    os.environ["KF_TELEMETRY_DIR"] = tdir
    ports = _reserve_ports(K)
    ids = [PeerID("127.0.0.1", p) for p in ports]
    labels = [str(i) for i in ids]
    groups = {}
    for r, lab in enumerate(labels):
        groups.setdefault(host_of(r), []).append(lab)
    entries = [
        f"{labels[i]}>{labels[j]}=lat:2,bw:2MiB"
        for i in range(K) for j in range(K)
        if i != j and host_of(i) != host_of(j)
    ]
    entries += [
        f"uplink:{'|'.join(groups[h])}=bw:32MiB" for h in sorted(groups)
    ]
    os.environ["KF_SHAPE_LINKS"] = ";".join(entries)

    peers = PeerList(ids)
    cluster = [
        Peer(WorkerConfig(
            self_id=me, peers=peers, runners=PeerList(), parent=None,
            cluster_version=0, strategy=Strategy.STAR, config_server="",
            elastic_mode="", init_progress=0,
        ))
        for me in ids
    ]
    _run_on_all([p.start for p in cluster], join=300)
    sessions = [
        HostSession(Strategy.RING_SEGMENTED, p.self_id, peers,
                    p.client, p.collective, timeout=240.0)
        for p in cluster
    ]
    return cluster, sessions, labels


def _teardown(cluster):
    for p in cluster:
        p.stop()
    os.environ.pop("KF_SHAPE_LINKS", None)


def _flip(sessions, mode, trigger="bench_ab"):
    """Lockstep production precision vote: every peer proposes `mode`,
    the majority flips the active candidate's codec on all of them."""
    if sessions[0].active_wire_mode() == mode:
        return
    res = {}
    _run_on_all([
        lambda r=r, s=s: res.__setitem__(
            r, s.check_precision(mode, trigger=trigger))
        for r, s in enumerate(sessions)
    ])
    assert all(res[r] == mode for r in res), res
    assert all(s.active_wire_mode() == mode for s in sessions)


def _timed_block_q(sessions, tag, rounds, n, tol_rel):
    """`rounds` lockstep allreduces under the active codec. The
    workspace NAME is held constant across rounds — the training-loop
    pattern the error-feedback store keys on, so round i's residual
    corrects round i+1. Asserts the result is bit-identical on every
    peer (each segment quantized once by its owner) and within the
    codec's value tolerance. Round time = barrier-to-barrier max,
    recorded by rank 0."""
    k = len(sessions)
    bar = threading.Barrier(k)
    times = []
    outs = [None] * k
    want = float(sum(j + 1 for j in range(k)))

    def run(r, s):
        for i in range(rounds):
            bar.wait()
            t0 = time.perf_counter()
            x = np.full(n, np.float32(r + 1))
            out = np.empty_like(x)
            s.all_reduce(Workspace(
                send=x, recv=out, op=ReduceOp.SUM, name=f"grad:{tag}",
            ))
            bar.wait()
            outs[r] = out
            assert abs(float(out[0]) - want) <= tol_rel * want, \
                (tag, i, float(out[0]), want)
            bar.wait()
            if r == 0:
                times.append(time.perf_counter() - t0)
                ref = outs[0].tobytes()
                assert all(o.tobytes() == ref for o in outs[1:]), \
                    f"{tag}:{i} result not bit-identical across peers"

    _run_on_all([lambda r=r, s=s: run(r, s) for r, s in enumerate(sessions)])
    return times


def _wire_children():
    """Per-codec children of the wire-byte counters (process-global —
    in-process peers sum into the same registry, which is exactly the
    cluster-total accounting the ratios need)."""
    ctr = tmetrics.counter(
        "kungfu_collective_wire_bytes_total",
        "Host-plane collective payload bytes sent by this peer",
        ("collective", "strategy", "codec"),
    )
    saved = tmetrics.counter(
        "kungfu_collective_wire_saved_bytes_total",
        "Wire bytes saved by the collective codec on this peer",
        ("collective", "codec"),
    )
    return (
        {m: ctr.labels("all_reduce", "RING_SEGMENTED", m) for m in MODES},
        {m: saved.labels("all_reduce", m) for m in MODES},
    )


# ---------------------------------------------------------------------------
# experiment 1: gradient-ring A/B, measured payload ratios
# ---------------------------------------------------------------------------

def k8_wire_precision_ab():
    cluster, sessions, _ = _mk_cluster()
    try:
        assert all(s.active_wire_mode() == "bf16" for s in sessions)
        wire_c, saved_c = _wire_children()
        _timed_block_q(sessions, "warmup", 2, N, TOL_REL["bf16"])

        rounds, blocks = 5, 3
        times = {m: [] for m in MODES}
        wire_bytes = {m: 0 for m in MODES}
        saved_bytes = {m: 0 for m in MODES}
        for blk in range(blocks):
            for mode in MODES:
                _flip(sessions, mode)
                w0, s0 = wire_c[mode].value, saved_c[mode].value
                times[mode] += _timed_block_q(
                    sessions, f"ab{blk}:{mode}", rounds, N, TOL_REL[mode])
                wire_bytes[mode] += wire_c[mode].value - w0
                saved_bytes[mode] += saved_c[mode].value - s0

        # a segmented allreduce moves 2(k-1)/k * N per peer = 2(k-1)*N
        # across the cluster, every round, whatever the codec
        raw = blocks * rounds * 2 * (K - 1) * N * 4
        med = lambda xs: float(np.median(xs))  # noqa: E731
        ratio = {m: wire_bytes[m] / raw for m in MODES}
        out = {
            "experiment": "k8_wire_precision_ab",
            "k": K,
            "hosts": HOSTS,
            "payload_bytes": N * 4,
            "rounds_per_block": rounds,
            "blocks": blocks,
            "round_ms": {m: round(med(times[m]) * 1e3, 1) for m in MODES},
            "speedup_int8_vs_bf16": round(
                med(times["bf16"]) / med(times["int8"]), 2),
            "speedup_int4_vs_bf16": round(
                med(times["bf16"]) / med(times["int4"]), 2),
            "wire_payload_ratio": {m: round(ratio[m], 4) for m in MODES},
            "saved_matches_wire": {
                m: bool(saved_bytes[m] == raw - wire_bytes[m])
                for m in MODES
            },
        }
        print(json.dumps(out), flush=True)
        assert out["speedup_int8_vs_bf16"] >= 1.3, out
        assert ratio["int8"] <= 0.45, ratio
        assert ratio["int4"] <= 0.45, ratio
        # block=16 framing: 1/4 payload + 4B scale per 64B block = 0.3125,
        # 1/8 payload + scale = 0.1875 (partial tail blocks round up)
        assert abs(ratio["int8"] - 0.3125) < 0.01, ratio
        assert abs(ratio["int4"] - 0.1875) < 0.01, ratio
        assert abs(ratio["bf16"] - 0.5) < 0.01, ratio
        assert all(out["saved_matches_wire"].values()), out
        return out
    finally:
        _teardown(cluster)


# ---------------------------------------------------------------------------
# experiment 2: ZeRO-1 weight leg (reduce-scatter + weight all-gather)
# ---------------------------------------------------------------------------

def k8_zero_weight_ab():
    from kungfu_tpu.collective.zero import ShardedSGD, ShardedUpdateSession

    cluster, sessions, _ = _mk_cluster()
    try:
        n = 256 * 1024  # 1 MiB of parameters
        params = [np.ones(n, np.float32) for _ in range(K)]
        zss = [
            ShardedUpdateSession([params[r]], ShardedSGD(0.01),
                                 name="benchz", session=sessions[r])
            for r in range(K)
        ]
        grads = [np.full(n, np.float32(0.001 * (r + 1))) for r in range(K)]
        bar = threading.Barrier(K)

        def zstep(tag, rounds):
            times = []

            def run(r):
                for i in range(rounds):
                    bar.wait()
                    t0 = time.perf_counter()
                    zss[r].step([grads[r].copy()])
                    bar.wait()
                    if r == 0:
                        times.append(time.perf_counter() - t0)

            _run_on_all([lambda r=r: run(r) for r in range(K)])
            ref = params[0].tobytes()
            assert all(p.tobytes() == ref for p in params[1:]), \
                f"{tag}: gathered weights not bit-identical across peers"
            return times

        zstep("warmup", 1)
        rounds, blocks = 4, 3
        times = {m: [] for m in MODES}
        for blk in range(blocks):
            for mode in MODES:
                _flip(sessions, mode)
                times[mode] += zstep(f"zero{blk}:{mode}", rounds)

        med = lambda xs: float(np.median(xs))  # noqa: E731
        out = {
            "experiment": "k8_zero_weight_ab",
            "k": K,
            "param_bytes": n * 4,
            "rounds_per_block": rounds,
            "blocks": blocks,
            "step_ms": {m: round(med(times[m]) * 1e3, 1) for m in MODES},
            "speedup_int8_vs_bf16": round(
                med(times["bf16"]) / med(times["int8"]), 2),
            "speedup_int4_vs_bf16": round(
                med(times["bf16"]) / med(times["int4"]), 2),
            "params_converged_finite": bool(
                np.isfinite(params[0]).all()),
        }
        print(json.dumps(out), flush=True)
        assert out["speedup_int8_vs_bf16"] >= 1.1, out
        assert out["params_converged_finite"], out
        return out
    finally:
        _teardown(cluster)


# ---------------------------------------------------------------------------
# experiment 3: policy-voted flip -> delivered; hostile upshift ->
# regressed -> rollback -> cooldown hold
# ---------------------------------------------------------------------------

def k8_precision_vote_ledger():
    from kungfu_tpu.policy import PolicyContext, PrecisionPolicy
    from kungfu_tpu.telemetry import decisions as tdecisions

    tdecisions.reset_ledger()  # experiments 1/2 left ungraded vote records
    cluster, sessions, _ = _mk_cluster()
    try:
        ledger = tdecisions.get_ledger()
        window = ledger.window
        batch = 64
        policies = [
            # int4_ratio effectively off: this leg exercises one clean
            # downshift + the rollback contract, not the full ladder
            PrecisionPolicy(interval_steps=window, patience=1,
                            int8_ratio=8.0, int4_ratio=1e9,
                            cooldown_intervals=8,
                            session_supplier=lambda s=s: s)
            for s in sessions
        ]
        ctxs = [PolicyContext(batch_size=batch) for _ in sessions]

        step_ms = []
        events = {}

        def one_step(step, noise_ratio):
            t0 = time.perf_counter()
            _timed_block_q(sessions, f"step{step}", 1, N, TOL_REL["int4"])
            dt = time.perf_counter() - t0
            tdecisions.note_step(dt)
            mode = sessions[0].active_wire_mode()
            step_ms.append((step, round(dt * 1e3, 1), mode))
            if step % window == 0:
                sig = ledger.signals()
                for ctx in ctxs:
                    ctx.step = step
                    ctx.metrics.update(sig)
                    ctx.metrics["monitor/noise_scale"] = noise_ratio * batch
                _run_on_all([
                    lambda p=p, c=c: p.after_step(c)
                    for p, c in zip(policies, ctxs)
                ])

        def recs():
            return [r for r in ledger.records()
                    if r.kind == "precision_switch"]

        # phase A: noisy gradients (B_noise >> B) -> policies vote int8
        step = 0
        while sessions[0].active_wire_mode() != "int8":
            step += 1
            assert step <= 6 * window, "policies never voted int8"
            one_step(step, noise_ratio=16.0)
        events["downshift_step"] = step

        # phase B: the ledger grades the downshift from measured steps
        while any(r.verdict is None for r in recs()):
            step += 1
            assert step <= events["downshift_step"] + 6 * window, \
                "downshift never graded"
            one_step(step, noise_ratio=16.0)
        events["downshift_verdicts"] = sorted(
            {r.verdict for r in recs()})
        events["downshift_verdict_step"] = step

        # phase C: noise collapses -> policies vote bf16 back; on this
        # bandwidth-starved path the upshift is throughput-hostile, the
        # ledger closes it regressed, and the rollback votes int8 back
        upshift_seen = False
        while True:
            step += 1
            assert step <= events["downshift_verdict_step"] + 12 * window, \
                "hostile upshift never rolled back"
            one_step(step, noise_ratio=1.0)
            mode = sessions[0].active_wire_mode()
            if mode == "bf16" and not upshift_seen:
                upshift_seen = True
                events["upshift_step"] = step
            if upshift_seen and mode == "int8":
                events["rollback_step"] = step
                break
        assert upshift_seen, "policies never proposed the upshift"
        rb = [r for r in recs() if r.trigger == "regression_rollback"]
        assert rb, "rollback flip did not open its own ledger record"
        events["regressed_recorded"] = any(
            r.verdict == "regressed" for r in recs())

        # phase D: cooldown — the bf16 target persists but the policy
        # holds instead of thrashing straight back into the regression
        hold_windows = 3
        for _ in range(hold_windows * window):
            step += 1
            one_step(step, noise_ratio=1.0)
        events["cooldown_held"] = sessions[0].active_wire_mode() == "int8"
        events["cooldown_withheld_votes"] = max(
            int(c.metrics.get("precision/vote_withheld_cooldown", 0))
            for c in ctxs
        )

        bf16_ms = [ms for _, ms, m in step_ms if m == "bf16"]
        int8_ms = [ms for _, ms, m in step_ms if m == "int8"]
        out = {
            "experiment": "k8_precision_vote_ledger",
            "k": K,
            "ledger_window": window,
            "policy_patience": 1,
            "bf16_round_ms": float(np.median(bf16_ms)),
            "int8_round_ms": float(np.median(int8_ms)),
            **events,
        }
        print(json.dumps(out), flush=True)
        assert out["downshift_verdicts"] == ["delivered"], out
        assert out["regressed_recorded"], out
        assert out["cooldown_held"], out
        assert out["cooldown_withheld_votes"] >= 1, out
        return out
    finally:
        _teardown(cluster)


def main():
    k8_wire_precision_ab()
    k8_zero_weight_ab()
    k8_precision_vote_ledger()


if __name__ == "__main__":
    main()
