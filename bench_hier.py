"""Benchmark: hierarchical two-level ring vs flat measured ring under a
SHARED-uplink shape (BENCH_HOST_r16, ISSUE 19).

Three in-process experiments, one JSON line each:

1. ``k64_shared_uplink_ab`` — k=64 across 4 virtual hosts (interleaved
   rank->host assignment), per-edge DCN shape on cross-host edges
   (lat:1ms, bw:16MiB) plus ONE shared token bucket per host uplink
   (64MiB across all 16 senders). Both plans are derived from the SAME
   probe-measured matrix through the production derivation
   (``derive_plan`` / ``derive_hier_plan``) and adopted through the
   production lockstep ``adopt_replan`` digest bracket; blocks of timed
   allreduce rounds alternate flat/hier three times so box drift
   cancels from the ratio. A naive rank-order block is timed for
   context. Acceptance: hier >= 1.5x over the flat MEASURED ring.

2. ``k256_lockstep_adoption`` — 256 live peers (16 virtual hosts x 16)
   with measured link rows injected into each peer's passive link
   table (a full k^2 probe mesh is not what this leg is about: the
   k=64 leg and the k=32 tier-1 smoke probe for real), shared-uplink
   shaping active. One lockstep ``check_replan`` round must carry the
   vote, exchange 256 rows, derive the identical two-level plan on
   every peer, and adopt it — wall-clock recorded against the sweep
   budget — followed by one exact two-level walk under the shape.

3. ``k8_live_demotion`` — 2 hosts x 4; rank 5's outgoing edges are
   persistently shaped (lat:25ms on every send, so its phase-1 star
   contribution drags each round). The per-peer ``ReplanPolicy`` stack
   runs the production path: patience windows close against the
   decision ledger's measurement window, the lockstep ``check_demote``
   vote flips rank 5 into the demoted role, the ledger's
   ``peer_demoted`` record measures the demotion (expect `delivered`),
   then the shape is removed live and the recovery counter promotes
   rank 5 back within the patience window.

All legs run real Peer transports (sockets + the shaping layer) in one
process; sleep-based shaping overlaps across threads, while per-message
Python overhead serializes on the GIL for BOTH legs of each A/B — the
per-step sync overhead it adds scales with step count exactly like the
real per-hop latency the two-level plan removes (2(k-1) flat hops vs
2(H-1)+2 phases), so it compresses nothing in hier's favor vs a real
deployment. Not a pytest module: run directly (`python bench_hier.py`).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

os.environ["KF_CONFIG_SHM"] = "0"       # sockets, so shaping applies
os.environ["KF_DECISION_WINDOW"] = "4"  # ledger measurement window
os.environ["KF_DECISION_SETTLE"] = "1"

import numpy as np

from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.cmd import _reserve_ports
from kungfu_tpu.collective.host_session import HostSession
from kungfu_tpu.peer import Peer
from kungfu_tpu.plan import replan as rp
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.runner.env import WorkerConfig
from kungfu_tpu.telemetry import link as tlink
from kungfu_tpu.transport import shaping
from kungfu_tpu.transport.message import ConnType

HostSession.SEGMENT_MIN_BYTES = 0
# Tight pacing for the bench: the default 20ms burst credit refills
# between ~50ms-spaced rounds, which would let every small per-round
# payload ride the burst and never pay the shaped bandwidth — the
# passive link table would then measure latency-only rates and the
# bimodal intra/cross gap the clustering keys on would wash out.
shaping.BURST_SECONDS = 0.002
shaping.BURST_MIN_BYTES = 4 << 10


def _run_on_all(fns, join=600):
    errs = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join)
        assert not t.is_alive(), "collective hung"
    if errs:
        raise errs[0]


def join_budget(k):
    return 600 if k >= 128 else 300


def _probe(cluster, ids, r, frames=2, nbytes=16 << 10):
    me = cluster[r]
    k = len(ids)
    payload = bytes(nbytes)
    for j in range(k):
        if j == r:
            continue
        for t in range(frames):
            me.client.send(ids[j], f"bprobe:{r}:{j}:{t}", payload,
                           ConnType.COLLECTIVE)
    for j in range(k):
        if j == r:
            continue
        for t in range(frames):
            msg = me.collective.recv(ids[j], f"bprobe:{j}:{r}:{t}", 120.0)
            if msg.release is not None:
                msg.release()


def _timed_block(sessions, tag, rounds, n):
    """`rounds` lockstep allreduces; per-round wall time = barrier-to-
    barrier (the max across peers), recorded by rank 0."""
    k = len(sessions)
    bar = threading.Barrier(k)
    times = []

    def run(r, s):
        for i in range(rounds):
            bar.wait()
            # a demoted peer's contribution is zero-weighted out of the
            # reduction (it still receives the result via broadcast)
            want = sum(j + 1 for j in range(k) if j not in s.demoted_peers())
            t0 = time.perf_counter()
            x = np.full(n, np.float32(r + 1))
            out = np.empty_like(x)
            s.all_reduce(Workspace(
                send=x, recv=out, op=ReduceOp.SUM, name=f"{tag}:{i}",
            ))
            assert out[0] == want, "walk result wrong"
            bar.wait()
            if r == 0:
                times.append(time.perf_counter() - t0)

    _run_on_all([lambda r=r, s=s: run(r, s) for r, s in enumerate(sessions)],
                join=join_budget(k))
    return times


def _lockstep_adopt(sessions, plans):
    _run_on_all([
        lambda s=s, p=p: s.adopt_replan(p)
        for s, p in zip(sessions, plans)
    ], join=join_budget(len(sessions)))


# ---------------------------------------------------------------------------
# experiment 1: k=64 flat-measured vs two-level A/B under shared uplinks
# ---------------------------------------------------------------------------

def k64_shared_uplink_ab():
    k, hosts = 64, 4
    host_of = lambda r: r % hosts  # noqa: E731 - interleaved: naive worst case
    tdir = tempfile.mkdtemp(prefix="kf-bench-hier-")
    os.environ["KF_TELEMETRY_DIR"] = tdir

    # the shape is built against the label set, so reserve first
    ports = _reserve_ports(k)
    ids = [PeerID("127.0.0.1", p) for p in ports]
    labels = [str(i) for i in ids]
    groups = {}
    for r, lab in enumerate(labels):
        groups.setdefault(host_of(r), []).append(lab)
    entries = [
        f"{labels[i]}>{labels[j]}=lat:2,bw:4MiB"
        for i in range(k) for j in range(k)
        if i != j and host_of(i) != host_of(j)
    ]
    entries += [
        f"uplink:{'|'.join(groups[h])}=bw:64MiB" for h in sorted(groups)
    ]
    os.environ["KF_SHAPE_LINKS"] = ";".join(entries)

    peers = PeerList(ids)
    cluster = [
        Peer(WorkerConfig(
            self_id=me, peers=peers, runners=PeerList(), parent=None,
            cluster_version=0, strategy=Strategy.STAR, config_server="",
            elastic_mode="", init_progress=0,
        ))
        for me in ids
    ]
    try:
        _run_on_all([p.start for p in cluster], join=300)
        tables = [
            tlink.LinkTable(registry=None, bw_min_bytes=1024)
            for _ in range(k)
        ]
        for p, t in zip(cluster, tables):
            p.client._links = t
        sessions = [
            HostSession(Strategy.RING_SEGMENTED, p.self_id, peers,
                        p.client, p.collective, timeout=240.0)
            for p in cluster
        ]
        for s, t in zip(sessions, tables):
            s._links = t
            s.replan_mode = "hier"

        n = 64 * 1024  # 256 KiB f32 payload
        _timed_block(sessions, "warmup", 2, n)
        _run_on_all([
            lambda r=r: _probe(cluster, ids, r, frames=3, nbytes=64 << 10)
            for r in range(k)
        ], join=300)

        # ONE measured matrix; both plans derived from the same bytes
        # through the production pure-function derivations
        flat_plans = [None] * k
        hier_plans = [None] * k

        def derive(r, s):
            m = s.measured_matrix()
            cf = s.measured_compute_frac()
            flat_plans[r] = rp.derive_plan(m, mode="auto", compute_frac=cf)
            hier_plans[r] = rp.derive_hier_plan(
                m, hosts=s._static_hosts(), mode="hier", compute_frac=cf,
            )

        _run_on_all([lambda r=r, s=s: derive(r, s)
                     for r, s in enumerate(sessions)], join=300)
        assert all(p is not None for p in flat_plans)
        assert all(h is not None for h in hier_plans)
        h = hier_plans[0]
        assert len(h.groups) == hosts, f"clustering found {len(h.groups)}"
        assert sorted(sorted(g) for g in h.groups) == [
            sorted(r for r in range(k) if host_of(r) == hh)
            for hh in range(hosts)
        ], "measured clustering did not recover the shaped hosts"

        naive = _timed_block(sessions, "naive", 3, n)
        flat_ms, hier_ms = [], []
        rounds = 5
        for blk in range(3):
            _lockstep_adopt(sessions, flat_plans)
            flat_ms += _timed_block(sessions, f"flat{blk}", rounds, n)
            _lockstep_adopt(sessions, hier_plans)
            hier_ms += _timed_block(sessions, f"hier{blk}", rounds, n)

        med = lambda xs: float(np.median(xs))  # noqa: E731
        out = {
            "experiment": "k64_shared_uplink_ab",
            "k": k,
            "hosts": hosts,
            "payload_bytes": n * 4,
            "naive_round_ms": round(med(naive) * 1e3, 1),
            "flat_measured_round_ms": round(med(flat_ms) * 1e3, 1),
            "hier_round_ms": round(med(hier_ms) * 1e3, 1),
            "speedup_hier_vs_flat": round(med(flat_ms) / med(hier_ms), 2),
            "speedup_hier_vs_naive": round(med(naive) / med(hier_ms), 2),
            "flat_order_crossings": sum(
                1 for a, b in zip(
                    flat_plans[0].order,
                    flat_plans[0].order[1:] + flat_plans[0].order[:1],
                )
                if host_of(a) != host_of(b)
            ),
            "hier_heads": list(h.heads),
            "rounds_per_block": rounds,
            "blocks": 3,
        }
        print(json.dumps(out), flush=True)
        return out
    finally:
        for p in cluster:
            p.stop()
        os.environ.pop("KF_SHAPE_LINKS", None)


# ---------------------------------------------------------------------------
# experiment 2: k=256 lockstep two-level adoption within budget
# ---------------------------------------------------------------------------

def k256_lockstep_adoption(budget_s=300.0):
    k, hosts = 256, 16
    host_of = lambda r: r % hosts  # noqa: E731
    tdir = tempfile.mkdtemp(prefix="kf-bench-hier-")
    os.environ["KF_TELEMETRY_DIR"] = tdir

    ports = _reserve_ports(k)
    ids = [PeerID("127.0.0.1", p) for p in ports]
    labels = [str(i) for i in ids]
    groups = {}
    for r, lab in enumerate(labels):
        groups.setdefault(host_of(r), []).append(lab)
    # uplink-only shape: 16 shared buckets, no per-edge entries (the
    # measured rows are injected below; probing a 65k-edge mesh is the
    # k=64 leg's job)
    os.environ["KF_SHAPE_LINKS"] = ";".join(
        f"uplink:{'|'.join(groups[h])}=bw:256MiB" for h in sorted(groups)
    )

    peers = PeerList(ids)
    cluster = [
        Peer(WorkerConfig(
            self_id=me, peers=peers, runners=PeerList(), parent=None,
            cluster_version=0, strategy=Strategy.STAR, config_server="",
            elastic_mode="", init_progress=0,
        ))
        for me in ids
    ]
    try:
        t_start = time.perf_counter()
        _run_on_all([p.start for p in cluster], join=600)
        start_s = time.perf_counter() - t_start
        tables = [
            tlink.LinkTable(registry=None, bw_min_bytes=1024)
            for _ in range(k)
        ]
        for p, t in zip(cluster, tables):
            p.client._links = t
        sessions = [
            HostSession(Strategy.RING_SEGMENTED, p.self_id, peers,
                        p.client, p.collective, timeout=600.0)
            for p in cluster
        ]
        for s, t in zip(sessions, tables):
            s._links = t
            s.replan_mode = "hier"

        # inject each peer's measured row: loopback-fast intra, DCN-slow
        # cross with deterministic per-edge variation
        nb = 1 << 20
        for r, t in enumerate(tables):
            for j, pid in enumerate(ids):
                if j == r:
                    continue
                if host_of(r) == host_of(j):
                    bw = 1e9 + 1e5 * ((r * 7 + j * 3) % 50)
                else:
                    bw = 5e6 + 1e3 * ((r * 31 + j * 17) % 100)
                t.observe_send(pid, nb, nb / bw)

        results = {}
        t0 = time.perf_counter()
        _run_on_all([
            lambda r=r, s=s: results.__setitem__(
                r, s.check_replan(want=True, min_gain=1.0)
            )
            for r, s in enumerate(sessions)
        ], join=600)
        adopt_s = time.perf_counter() - t0
        assert all(results[r] is not None for r in range(k)), \
            "k=256 hier re-plan did not fire"
        hiers = [s.hier_plan() for s in sessions]
        assert all(h is not None for h in hiers)
        assert len({h.to_bytes() for h in hiers}) == 1, "divergent plans"
        h = hiers[0]
        assert len(h.groups) == hosts
        assert sorted(sorted(g) for g in h.groups) == [
            sorted(r for r in range(k) if host_of(r) == hh)
            for hh in range(hosts)
        ]

        t0 = time.perf_counter()
        walk = _timed_block(sessions, "post-hier", 1, 16 * 1024)
        walk_s = time.perf_counter() - t0
        out = {
            "experiment": "k256_lockstep_adoption",
            "k": k,
            "hosts": hosts,
            "peer_start_s": round(start_s, 1),
            "lockstep_adopt_s": round(adopt_s, 1),
            "hier_walk_round_s": round(walk[0], 2),
            "walk_harness_s": round(walk_s, 1),
            "groups": len(h.groups),
            "within_budget": adopt_s <= budget_s,
            "budget_s": budget_s,
        }
        print(json.dumps(out), flush=True)
        assert out["within_budget"], f"adoption blew the budget: {adopt_s}"
        return out
    finally:
        for p in cluster:
            p.stop()
        os.environ.pop("KF_SHAPE_LINKS", None)


# ---------------------------------------------------------------------------
# experiment 3: live demotion -> ledger verdict -> recovery promotion
# ---------------------------------------------------------------------------

def k8_live_demotion():
    from kungfu_tpu.policy import PolicyContext, ReplanPolicy
    from kungfu_tpu.telemetry import decisions as tdecisions

    k, hosts = 8, 2
    host_of = lambda r: r // 4  # noqa: E731 - contiguous: 2 hosts x 4
    straggler = 5
    tdir = tempfile.mkdtemp(prefix="kf-bench-hier-")
    os.environ["KF_TELEMETRY_DIR"] = tdir

    ports = _reserve_ports(k)
    ids = [PeerID("127.0.0.1", p) for p in ports]
    labels = [str(i) for i in ids]
    groups = {}
    for r, lab in enumerate(labels):
        groups.setdefault(host_of(r), []).append(lab)
    # Cross-host DCN: lat:2,bw:8MiB, except the 0<->4 pair which is
    # deliberately faster (lat:1.5,bw:12MiB) so head election is
    # deterministic (ranks 0 and 4 measure the best uplinks). The
    # persistent straggler is rank 5: EVERY send it makes pays 40ms —
    # its phase-1 star contribution holds the whole round hostage —
    # while its inbound stays clean (symmetrized clustering still puts
    # it in its host; demotion, not exclusion, is the remedy).
    entries = []
    for i in range(k):
        for j in range(k):
            if i == j:
                continue
            if i == straggler:
                entries.append(f"{labels[i]}>{labels[j]}=lat:40")
            elif host_of(i) != host_of(j):
                if {i, j} == {0, 4}:
                    entries.append(
                        f"{labels[i]}>{labels[j]}=lat:1.5,bw:12MiB")
                else:
                    entries.append(f"{labels[i]}>{labels[j]}=lat:2,bw:8MiB")
    entries += [
        f"uplink:{'|'.join(groups[h])}=bw:64MiB" for h in sorted(groups)
    ]
    os.environ["KF_SHAPE_LINKS"] = ";".join(entries)

    peers = PeerList(ids)
    cluster = [
        Peer(WorkerConfig(
            self_id=me, peers=peers, runners=PeerList(), parent=None,
            cluster_version=0, strategy=Strategy.STAR, config_server="",
            elastic_mode="", init_progress=0,
        ))
        for me in ids
    ]
    try:
        _run_on_all([p.start for p in cluster], join=300)
        tables = [
            tlink.LinkTable(registry=None, bw_min_bytes=1024)
            for _ in range(k)
        ]
        for p, t in zip(cluster, tables):
            p.client._links = t
        sessions = [
            HostSession(Strategy.RING_SEGMENTED, p.self_id, peers,
                        p.client, p.collective, timeout=240.0)
            for p in cluster
        ]
        for s, t in zip(sessions, tables):
            s._links = t
            s.replan_mode = "hier"

        n = 64 * 1024
        _timed_block(sessions, "warmup", 1, n)
        _run_on_all([lambda r=r: _probe(cluster, ids, r) for r in range(k)],
                    join=300)
        results = {}
        _run_on_all([
            lambda r=r, s=s: results.__setitem__(
                r, s.check_replan(want=True, min_gain=1.0)
            )
            for r, s in enumerate(sessions)
        ], join=300)
        assert all(results[r] is not None for r in range(k))
        h = sessions[0].hier_plan()
        assert h is not None and len(h.groups) == hosts
        assert straggler not in h.heads, "shaped peer won head election?!"

        ledger = tdecisions.get_ledger()
        window = ledger.window
        patience = 2
        policies = [
            ReplanPolicy(interval_steps=window, patience=99, min_gain=9.9,
                         demote_patience=patience,
                         session_supplier=lambda s=s: s)
            for s in sessions
        ]
        ctxs = [PolicyContext(batch_size=1) for _ in sessions]
        lab5 = labels[straggler]

        def signals(step, shaped):
            sig = {"cluster/updated_at": float(step)}
            if shaped:
                sig.update({
                    "step/critical_peer": lab5,
                    "cluster/stragglers": [lab5],
                    "cluster/straggler_causes": {lab5: "compute"},
                })
            else:
                sig.update({
                    "step/critical_peer": None,
                    "cluster/stragglers": [],
                    "cluster/straggler_causes": {},
                })
            return sig

        step_ms = []
        events = {}

        def one_step(step, shaped):
            t0 = time.perf_counter()
            _timed_block(sessions, f"step{step}", 1, n)
            dt = time.perf_counter() - t0
            tdecisions.note_step(dt)
            step_ms.append((step, round(dt * 1e3, 1), shaped))
            if step % window == 0:
                for ctx in ctxs:
                    ctx.step = step
                    ctx.metrics.update(signals(step, shaped))
                _run_on_all([
                    lambda p=p, c=c: p.after_step(c)
                    for p, c in zip(policies, ctxs)
                ], join=300)

        # phase A: shaped straggler -> lockstep demotion
        step = 0
        while sessions[0].demoted_peers() != (straggler,):
            step += 1
            assert step <= 4 * window * (patience + 2), "never demoted"
            one_step(step, shaped=True)
        events["demote_step"] = step
        events["demoted"] = list(sessions[0].demoted_peers())

        # phase B: the ledger measures the demotion
        def demote_recs():
            return [r for r in tdecisions.get_ledger().records()
                    if r.kind == "peer_demoted"]

        while any(r.verdict is None for r in demote_recs()):
            step += 1
            assert step <= events["demote_step"] + 6 * window, "never graded"
            one_step(step, shaped=True)
        events["verdicts"] = sorted({r.verdict for r in demote_recs()})
        events["verdict_step"] = step

        # phase C: un-shape rank 5 LIVE and feed clean signals
        cluster[straggler].client._shaper = None
        unshape_step = step
        events["unshape_step"] = unshape_step
        while sessions[0].demoted_peers() == (straggler,):
            step += 1
            assert step <= unshape_step + 2 * window * (patience + 2), \
                "never promoted back"
            one_step(step, shaped=False)
        events["promote_step"] = step
        events["promoted_within_windows"] = (
            (step - unshape_step + window - 1) // window
        )

        shaped_ms = [ms for st, ms, sh in step_ms
                     if sh and st <= events["demote_step"]]
        demoted_ms = [ms for st, ms, sh in step_ms
                      if sh and st > events["demote_step"]]
        out = {
            "experiment": "k8_live_demotion",
            "k": k,
            "straggler_rank": straggler,
            "ledger_window": window,
            "demote_patience": patience,
            "shaped_round_ms": float(np.median(shaped_ms)),
            "demoted_round_ms": float(np.median(demoted_ms)),
            **events,
        }
        print(json.dumps(out), flush=True)
        assert out["verdicts"] == ["delivered"], out["verdicts"]
        assert out["promoted_within_windows"] <= patience + 1
        return out
    finally:
        for p in cluster:
            p.stop()
        os.environ.pop("KF_SHAPE_LINKS", None)


def main():
    k64_shared_uplink_ab()
    k256_lockstep_adoption()
    k8_live_demotion()


if __name__ == "__main__":
    main()
