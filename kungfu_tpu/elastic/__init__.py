from kungfu_tpu.elastic.configserver import ConfigServer
from kungfu_tpu.elastic.dataset import ElasticDataset
from kungfu_tpu.elastic.schedule import (
    StepBasedSchedule,
    parse_schedule,
    schedule_target,
)
from kungfu_tpu.elastic.state import ElasticState

__all__ = [
    "ConfigServer",
    "ElasticDataset",
    "ElasticState",
    "StepBasedSchedule",
    "parse_schedule",
    "schedule_target",
]
