"""Checkpoint/resume for elastic and failure-recovered training.

The reference has no general checkpoint subsystem (SURVEY §5.4): resume
relies on live state broadcast across survivors plus user-managed Keras
checkpoints reloaded on ``--restart 1``. The TPU-native build keeps the
live-broadcast path (elastic/state.py) for in-flight membership changes
and adds a real checkpointer for the cases live state cannot cover — a
full-cluster restart (kfrun -auto-recover relaunch, preemption of every
host) — built on orbax, the JAX-ecosystem checkpoint library.

Also provides ``dump_final_variables`` (parity: hooks/elastic.py:80-87,
the ad-hoc ``variables-final.npz`` dump), dtype-faithful for bf16 via
base/serialize.

Usage with the auto-recover contract::

    ckpt = Checkpointer(logdir)            # every rank; saves on rank 0
    state, start = ckpt.restore_or((params, opt_state))
    for epoch in range(start, n_epochs):
        ...
        state = (params, opt_state)
        ckpt.save(epoch + 1, state)        # after the epoch completes
        cmd.monitor_epoch_end()

On relaunch, KF_RECOVER_EPOCH (set by the monitored runner from the
heartbeat min-epoch) caps the restore step: a checkpoint AHEAD of the
cluster-wide safe epoch is skipped so every worker resumes from the same
step.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

from kungfu_tpu.runner.monitored import RECOVER_EPOCH_ENV


class Checkpointer:
    """Orbax-backed (step, pytree) checkpoints with a bounded window.

    Saving is rank-0-only by default (synchronous data parallelism keeps
    state replicated); every rank restores from the same directory —
    colocated workers share the local FS, multi-host clusters need a
    shared path (e.g. GCS, which orbax speaks natively)."""

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_rank: Optional[int] = 0,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.save_rank = save_rank
        self.mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def _my_rank(self) -> int:
        try:
            from kungfu_tpu import api

            return api.current_rank()
        # kfcheck: disable=KF400 — checkpointing is usable without a
        # cluster; no api/peer means single-process rank 0 by contract
        except Exception:  # noqa: BLE001
            return 0

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save `state` at `step`; returns True if written (rank-gated)."""
        if self.save_rank is not None and self._my_rank() != self.save_rank:
            return False
        self.mgr.save(step, args=self._ocp.args.StandardSave(state), force=force)
        self.mgr.wait_until_finished()
        return True

    def latest_step(self) -> Optional[int]:
        """Newest step not beyond the cluster-wide safe resume epoch
        (KF_RECOVER_EPOCH, when the monitored runner provides one)."""
        steps = sorted(self.mgr.all_steps())
        from kungfu_tpu import knobs

        cap = knobs.raw(RECOVER_EPOCH_ENV)
        if cap:
            steps = [s for s in steps if s <= int(cap)]
        return steps[-1] if steps else None

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Any:
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        return self.mgr.restore(
            step, args=self._ocp.args.StandardRestore(abstract_state)
        )

    def restore_or(self, default_state: Any) -> Tuple[Any, int]:
        """(state, start_step): the newest safe checkpoint, or the given
        initial state at step 0."""
        step = self.latest_step()
        if step is None:
            return default_state, 0
        return self.restore(default_state, step), step

    def close(self) -> None:
        self.mgr.close()


def dump_final_variables(path: str, tree: Any) -> None:
    """Dump a pytree's leaves to one file at end of training (parity:
    variables-final.npz, hooks/elastic.py:80-87). Uses the dtype-faithful
    pack format — np.savez cannot round-trip bf16."""
    import jax

    from kungfu_tpu.base.serialize import pack_leaves

    leaves = jax.tree.leaves(jax.device_get(tree))
    with open(path, "wb") as f:
        f.write(pack_leaves(leaves))


def load_final_variables(path: str, like: Any) -> Any:
    """Inverse of dump_final_variables, re-shaped onto `like`'s treedef."""
    import jax

    from kungfu_tpu.base.serialize import unpack_leaves

    leaves, treedef = jax.tree.flatten(like)
    with open(path, "rb") as f:
        out = unpack_leaves(f.read(), len(leaves))
    return jax.tree.unflatten(treedef, out)
