"""Elastic dataset adaptor: shard/offset-aware batches across resizes.

Capability parity: srcs/python/kungfu/tensorflow/v1/datasets/adaptor.py —
the dataset must (a) shard batches across the CURRENT cluster and (b)
resume from the global progress offset after a resize, so no sample is
double-trained or skipped when workers join/leave (modulo the in-flight
batch).

TPU-native design: a deterministic global sample order (seeded per-epoch
permutation) indexed by the cluster-max progress that ElasticState already
syncs. Any worker at (progress, rank, size) can compute its batch without
coordination — the progress IS the dataset iterator state, which is what
makes elastic restart (and reload mode) trivially correct.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


class ElasticDataset:
    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int, seed: int = 0):
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("all arrays must share the leading dimension")
        self.arrays = [np.asarray(a) for a in arrays]
        self.n = n
        self.batch_size = batch_size
        self.seed = seed
        self._perm_epoch = -1
        self._perm: np.ndarray = np.empty(0, np.int64)

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        if epoch != self._perm_epoch:
            rng = np.random.default_rng(self.seed + epoch)
            self._perm = rng.permutation(self.n)
            self._perm_epoch = epoch
        return self._perm

    def batch_at(self, progress: int, rank: int, size: int) -> Tuple[np.ndarray, ...]:
        """The batch worker `rank` of `size` trains at global progress
        `progress` (measured in SAMPLES, like ElasticState). The global
        order is a per-epoch permutation; batches wrap across epochs."""
        start = progress + rank * self.batch_size
        idx = np.arange(start, start + self.batch_size)
        epoch = idx // self.n
        pos = idx % self.n
        if (epoch == epoch[0]).all():
            sel = self._epoch_perm(int(epoch[0]))[pos]
        else:  # batch straddles an epoch boundary
            sel = np.array(
                [self._epoch_perm(int(e))[p] for e, p in zip(epoch, pos)]
            )
        return tuple(a[sel] for a in self.arrays)

    def cluster_delta(self, size: int) -> int:
        """Progress consumed by one cluster-wide step (for es.end)."""
        return self.batch_size * size
