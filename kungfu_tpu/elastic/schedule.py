"""Step-based elastic schedules: "np:steps,np:steps,..." driving resizes.

Capability parity: KungfuStepBasedSchedule (ops/cpu/elastic.cpp:16-81) +
KungFuElasticTrainHook (hooks/elastic.py:14-88) — a declarative schedule
of cluster sizes by global step; rank 0 publishes the target size to the
config server at each boundary and every worker resizes via consensus.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from kungfu_tpu import api


def parse_schedule(spec: str) -> List[Tuple[int, int]]:
    """"2:10,4:20,1:5" -> [(2,10), (4,20), (1,5)]: np for a span of steps."""
    out: List[Tuple[int, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        np_s, _, steps_s = part.partition(":")
        n, steps = int(np_s), int(steps_s)
        if n <= 0 or steps <= 0:
            raise ValueError(f"bad schedule entry {part!r}: sizes/spans must be > 0")
        out.append((n, steps))
    if not out:
        raise ValueError(f"empty schedule: {spec!r}")
    return out


def schedule_target(schedule: List[Tuple[int, int]], step: int) -> Optional[int]:
    """Desired cluster size at `step`; None once the schedule is exhausted
    (training continues at the last size)."""
    off = 0
    for n, steps in schedule:
        if step < off + steps:
            return n
        off += steps
    return None


class StepBasedSchedule:
    """Drives propose_new_size from a schedule inside the elastic loop:

        sched = StepBasedSchedule("2:10,4:20,1:5")
        while not es.stopped():
            with es.scope():
                sched.maybe_propose(es.progress)
                ...
                es.end(1)

    Only rank 0 publishes; the resize itself still flows through the config
    server + consensus like any other elastic event.
    """

    REPROPOSE_AFTER = 10.0  # seconds before a non-landed proposal is resent

    def __init__(self, spec: str):
        self.schedule = parse_schedule(spec)
        self._last_proposed: Optional[int] = None
        self._proposed_at = 0.0

    def total_steps(self) -> int:
        return sum(steps for _, steps in self.schedule)

    def maybe_propose(self, step: int) -> Optional[int]:
        """Publish the scheduled size if the cluster isn't there yet;
        returns the size proposed (or None).

        _last_proposed is only recorded after propose_new_size SUCCEEDS on
        the acting rank 0: if the PUT fails or rank 0 detaches at the
        boundary, the next acting rank 0 re-proposes instead of the
        schedule silently skipping the resize. A proposal that was accepted
        but then lost (config-server restart) is also covered: while the
        observed cluster size stays off-target, the proposal is re-sent
        every REPROPOSE_AFTER seconds (rate-limited so the steady
        propose→consensus window doesn't spam the server).

        GROW proposals consult the memory plane first (ISSUE 17): a
        bigger cluster re-replicates state across peers that may
        already be near their limit, so while the acting rank 0's
        MEASURED headroom sits at/below the pressure line the proposal
        is deferred (re-checked every REPROPOSE_AFTER via the existing
        rate limit). An unmeasured plane never defers — headroom that
        was never observed must not block the schedule — and shrink
        proposals always pass: shedding peers is how pressure gets
        RELIEVED. The gate is rank-0-local by design: only the single
        acting proposer decides, so divergent per-peer RSS can never
        split an engine-knob consensus."""
        target = schedule_target(self.schedule, step)
        if target is None:
            return None
        if target == api.cluster_size():
            self._last_proposed = target  # landed; don't re-propose
            return None
        if api.current_rank() != 0:
            return None
        if (
            target == self._last_proposed
            and time.monotonic() - self._proposed_at < self.REPROPOSE_AFTER
        ):
            # proposed recently: the resize flows through the config-server
            # consensus in es.end(); give it time to land
            return None
        if target > api.cluster_size():
            try:
                from kungfu_tpu.telemetry import memory as tmem

                ok, why = tmem.get_plane().grow_ok()
            # kfcheck: disable=KF400 — a broken memory plane must
            # never block a resize; fail open
            except Exception:  # noqa: BLE001
                ok, why = True, "plane unavailable"
            if not ok:
                from kungfu_tpu.telemetry import log, metrics

                metrics.counter(
                    "kungfu_memory_grow_deferrals_total",
                    "Scheduled grow proposals deferred because the "
                    "acting rank 0's measured memory headroom sat at "
                    "or below the pressure line",
                ).inc()
                log.warn(
                    "schedule: deferring grow to %d at progress %d: %s",
                    target, step, why,
                )
                # rate-limit the re-check like a sent proposal so a
                # pressured rank 0 logs once per window, not per step
                self._last_proposed = target
                self._proposed_at = time.monotonic()
                return None
        try:
            api.propose_new_size(target)
        except OSError as e:
            # transient config-server blip: _last_proposed stays unset so
            # the very next maybe_propose call retries the PUT; warn so a
            # PERSISTENT failure is distinguishable from a spent schedule
            from kungfu_tpu.telemetry import log

            log.warn("propose_new_size(%d) failed (%s); will retry", target, e)
            return None
        from kungfu_tpu.telemetry import log, metrics

        metrics.counter(
            "kungfu_schedule_proposals_total",
            "Cluster sizes proposed by the step-based schedule",
        ).inc()
        log.info("schedule proposed cluster size %d at progress %d", target, step)
        self._last_proposed = target
        self._proposed_at = time.monotonic()
        return target
