"""ElasticState: progress-based elastic training loop driver.

Capability parity: srcs/python/kungfu/python/elastic_state.py:4-79 +
KungFuElasticTrainHook's state re-sync (hooks/elastic.py:46-57) —
  es = ElasticState(max_progress)
  es.register_state(get_state, set_state)   # joiner weight re-sync
  while not es.stopped():
      with es.scope():          # begin(): sync progress + state after resize
          train_one_batch()
          es.end(batch_size)    # progress += n, maybe resize
                                # (es.advance is an alias for es.end)
Stop reasons: 'finished' | 'detached' | 'reload'.

After every membership change begin() (a) adopts the cluster-max progress
via an int-max allreduce and (b) if state callbacks are registered,
broadcasts rank-0's training state over the host plane so joining workers
inherit live weights instead of fresh-initialized ones (the reference
re-broadcasts variables + re-syncs progress in its elastic hook).
"""

from __future__ import annotations

import contextlib
from typing import Callable, Optional

import numpy as np

from kungfu_tpu import api
from kungfu_tpu.base.serialize import pack_leaves as _pack_leaves
from kungfu_tpu.base.serialize import unpack_leaves as _unpack_leaves


class ElasticState:
    def __init__(self, max_progress: Optional[int] = None, reload_mode: bool = False):
        from kungfu_tpu.peer import get_default_peer

        self.max_progress = max_progress
        self.reload_mode = reload_mode
        self._peer = get_default_peer()
        self.progress = self._peer.config.init_progress
        self._synced = False
        self._stop_reason: Optional[str] = None
        self._get_state: Optional[Callable] = None
        self._set_state: Optional[Callable] = None
        # last checkpoint version this driver saved/restored (stamped
        # onto resize audit records); None until note_checkpoint()
        self._checkpoint_version: Optional[int] = None

    def note_checkpoint(self, version: int) -> None:
        """Tell the elastic driver which checkpoint version now covers
        `progress` — recorded on the next resize's audit entry."""
        self._checkpoint_version = int(version)

    def register_state(self, get_state: Callable, set_state: Callable) -> None:
        """Register training-state callbacks for joiner re-sync.

        get_state() -> pytree of arrays (params + optimizer state);
        set_state(pytree) installs the received values. Called only after
        membership changes, never in the steady-state step path.
        """
        self._get_state = get_state
        self._set_state = set_state

    def _sync_state(self) -> None:
        if self._get_state is None:
            return
        from kungfu_tpu.utils import trace

        with trace.span("elastic.sync_state"):
            self._sync_state_traced()

    def _sync_state_traced(self) -> None:
        import jax

        from kungfu_tpu.base.ops import ReduceOp
        from kungfu_tpu.base.workspace import Workspace

        sess = self._peer.current_session()
        if sess.size == 1:
            return
        # Pick a provably SURVIVING broadcast root: the new cluster's order
        # comes verbatim from the user's config PUT, so rank 0 may be a
        # fresh joiner whose state must never overwrite the survivors'.
        # Each peer votes (its rank if it lived through a previous epoch);
        # the min survivor rank becomes the root. Two more scalars ride the
        # same vote: the joiner count (a pure shrink has none -> skip the
        # broadcast entirely) gated by the MIN below.
        big = np.int64(1 << 30)
        survivor = self._peer.epoch_count > 1
        v = f"v{self._peer.cluster_version}"
        root_in = np.array([sess.rank if survivor else big], np.int64)
        root_out = np.zeros(1, np.int64)
        sess.all_reduce(
            Workspace(root_in, root_out, ReduceOp.MIN, f"kungfu::syncroot:{v}")
        )
        fresh_in = np.array([0 if survivor else 1], np.int64)
        fresh_out = np.zeros(1, np.int64)
        sess.all_reduce(
            Workspace(fresh_in, fresh_out, ReduceOp.SUM, f"kungfu::syncfresh:{v}")
        )
        n_fresh = int(fresh_out[0])
        if n_fresh == 0:
            return  # pure shrink: survivors are already in sync
        # fresh world (startup / reload): root 0 = initializer broadcast
        root = int(root_out[0]) if root_out[0] < big else 0
        tree = self._get_state()
        leaves, treedef = jax.tree.flatten(tree)
        blob = _pack_leaves(leaves) if sess.rank == root else b""
        got = sess.broadcast_bytes(blob, f"kungfu::statesync:{v}", root=root)
        if sess.rank != root and self._set_state is not None:
            new_leaves = _unpack_leaves(got, len(leaves))
            new_leaves = [
                np.asarray(nl).astype(np.asarray(ol).dtype).reshape(np.shape(ol))
                for nl, ol in zip(new_leaves, leaves)
            ]
            self._set_state(jax.tree.unflatten(treedef, new_leaves))

    def begin(self) -> None:
        if not self._synced:
            # after a membership change, everyone adopts the max progress
            # and rank-0's live training state
            self.progress = api.all_reduce_int_max(self.progress)
            self._sync_state()
            self._synced = True

    def end(self, delta: int = 1) -> None:
        self.progress += delta
        if self.max_progress is not None and self.progress >= self.max_progress:
            self._stop_reason = "finished"
            return
        if self.reload_mode:
            changed, _ = api.change_cluster(self.progress)
            if changed:
                self._stop_reason = "reload"
            return
        changed, detached = api.resize()
        if changed:
            # the resize audit record was written deep in the peer
            # protocol; only the elastic driver knows the training
            # progress (and checkpoint version) it happened at
            from kungfu_tpu.telemetry import audit

            audit.annotate_last(
                peer=str(self._peer.self_id),
                progress=self.progress,
                checkpoint_version=self._checkpoint_version,
            )
        if detached:
            self._stop_reason = "detached"
        elif changed:
            self._synced = False

    advance = end  # documented alias

    @contextlib.contextmanager
    def scope(self):
        self.begin()
        yield

    def stopped(self) -> bool:
        return self._stop_reason is not None

    @property
    def stop_reason(self) -> Optional[str]:
        return self._stop_reason
