"""ElasticState: progress-based elastic training loop driver.

Capability parity: srcs/python/kungfu/python/elastic_state.py:4-79 —
  es = ElasticState(max_progress)
  while not es.stopped():
      with es.scope():          # begin(): sync progress after resize
          train_one_batch()
          es.end(batch_size)    # progress += n, maybe resize
                                # (es.advance is an alias for es.end)
Stop reasons: 'finished' | 'detached' | 'reload'.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from kungfu_tpu import api


class ElasticState:
    def __init__(self, max_progress: Optional[int] = None, reload_mode: bool = False):
        from kungfu_tpu.peer import get_default_peer

        self.max_progress = max_progress
        self.reload_mode = reload_mode
        self._peer = get_default_peer()
        self.progress = self._peer.config.init_progress
        self._synced = False
        self._stop_reason: Optional[str] = None

    def begin(self) -> None:
        if not self._synced:
            # after a membership change, everyone adopts the max progress
            self.progress = api.all_reduce_int_max(self.progress)
            self._synced = True

    def end(self, delta: int = 1) -> None:
        self.progress += delta
        if self.max_progress is not None and self.progress >= self.max_progress:
            self._stop_reason = "finished"
            return
        if self.reload_mode:
            changed, _ = api.change_cluster(self.progress)
            if changed:
                self._stop_reason = "reload"
            return
        changed, detached = api.resize()
        if detached:
            self._stop_reason = "detached"
        elif changed:
            self._synced = False

    advance = end  # documented alias

    @contextlib.contextmanager
    def scope(self):
        self.begin()
        yield

    def stopped(self) -> bool:
        return self._stop_reason is not None

    @property
    def stop_reason(self) -> Optional[str]:
        return self._stop_reason
