"""HTTP config server: the desired-membership oracle for elastic training.

Capability parity: srcs/go/kungfu/elastic/configserver/configserver.go —
GET returns the current Cluster JSON, PUT installs a validated new cluster
(version++), POST resets, DELETE clears, /stop shuts down. Also embeddable
in kfrun (-builtin-config-port; parity: builtin-config-server.go).
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kungfu_tpu.plan.cluster import Cluster, ClusterError


class ConfigState:
    def __init__(self, initial: Optional[Cluster] = None):
        self._lock = threading.Lock()
        self._cluster = initial
        self._version = 0

    def get(self):
        with self._lock:
            return self._cluster, self._version

    def put(self, cluster: Cluster) -> int:
        cluster.validate()
        with self._lock:
            self._cluster = cluster
            self._version += 1
            return self._version

    def reset(self, cluster: Optional[Cluster]) -> None:
        with self._lock:
            self._cluster = cluster
            self._version = 0


class _Handler(BaseHTTPRequestHandler):
    state: ConfigState = None  # set by serve()
    stop_event: threading.Event = None

    def log_message(self, *args):  # quiet
        pass

    def _reply(self, code: int, body: bytes = b"", ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.rstrip("/") == "/stop":
            self._reply(200, b"{}")
            self.stop_event.set()
            return
        cluster, version = self.state.get()
        if cluster is None:
            self._reply(404, b'{"error": "no config"}')
            return
        body = json.dumps({**cluster.to_json(), "Version": version}).encode()
        self._reply(200, body)

    def do_PUT(self):
        n = int(self.headers.get("Content-Length", 0))
        try:
            cluster = Cluster.loads(self.rfile.read(n).decode())
            version = self.state.put(cluster)
        except (ValueError, ClusterError, json.JSONDecodeError) as e:
            self._reply(400, json.dumps({"error": str(e)}).encode())
            return
        from kungfu_tpu.telemetry import audit

        audit.record_event(
            "config_put",
            trigger="http",
            version=version,
            size=len(cluster.workers),
        )
        self._reply(200, json.dumps({"Version": version}).encode())

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n).decode()
        cluster = Cluster.loads(body) if body.strip() else None
        self.state.reset(cluster)
        self._reply(200, b"{}")

    def do_DELETE(self):
        self.state.reset(None)
        self._reply(200, b"{}")


class ConfigServer:
    """Embeddable threaded config server."""

    def __init__(self, port: int, initial: Optional[Cluster] = None, host: str = "0.0.0.0"):
        self.state = ConfigState(initial)
        self.stop_event = threading.Event()
        handler = type("Handler", (_Handler,), {"state": self.state, "stop_event": self.stop_event})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        threading.Thread(target=self._watch_stop, daemon=True).start()

    def _watch_stop(self) -> None:
        # kfcheck: disable=KF301 — this daemon thread waits ON the abort
        # signal itself; stop() sets it, and process exit reaps the thread
        self.stop_event.wait()
        self.httpd.shutdown()

    def stop(self) -> None:
        self.stop_event.set()
        self.httpd.shutdown()


def main(argv=None) -> None:
    p = argparse.ArgumentParser("kf-config-server")
    p.add_argument("-port", type=int, default=9100)
    p.add_argument("-init", type=str, default="", help="initial cluster JSON file")
    args = p.parse_args(argv)
    initial = None
    if args.init:
        with open(args.init) as f:
            initial = Cluster.loads(f.read())
    srv = ConfigServer(args.port, initial)
    srv.start()
    from kungfu_tpu.telemetry import log

    log.echo(f"config server on :{srv.port}")
    # kfcheck: disable=KF301 — serving forever IS the program; the main
    # thread waits on the abort signal and Ctrl-C interrupts the wait
    srv.stop_event.wait()


if __name__ == "__main__":
    main()
