"""kungfu_tpu — a TPU-native adaptive distributed-training framework.

Provides the capabilities of KungFu (OSDI'20: adaptive/elastic decentralized
data-parallel training) re-designed for TPU hardware:

- The collective data plane is XLA: ``psum``/``pmean``/``all_gather`` inside
  jitted programs over a ``jax.sharding.Mesh`` (ICI), replacing the
  reference's NCCL + TCP graph-walk collectives.
- A host-side control plane (runner CLI, config server, heartbeat monitor,
  TCP message channels) supervises worker processes and drives elastic
  membership, replacing the reference's Go runtime.
- Optimizers (SynchronousSGD, SynchronousAveraging, PairAveraging,
  AdaptiveSGD, gradient-noise-scale monitoring) wrap optax gradient
  transformations.

Reference capability map: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from kungfu_tpu import knobs as _knobs

# Debug-mode lock-order detector (ISSUE 7): installed FIRST, before any
# kungfu module creates a lock, so every threading.Lock/RLock below this
# line is instrumented. Unset/falsy knob = lockwatch never imported,
# threading untouched, zero overhead (asserted by tests/test_lockwatch).
if _knobs.get("KF_DEBUG_LOCKS"):
    from kungfu_tpu.devtools import lockwatch as _lockwatch

    _lockwatch.install()

from kungfu_tpu.base.dtype import DType
from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.strategy import Strategy

__all__ = [
    "DType",
    "ReduceOp",
    "Strategy",
    "telemetry",
    "__version__",
]


def __getattr__(name):
    # lazy (PEP 562): kungfu_tpu.telemetry without paying for it on
    # import paths that never touch it
    if name == "telemetry":
        import kungfu_tpu.telemetry as telemetry

        return telemetry
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
