"""Topology generators: broadcast/reduce graph pairs over a PeerList.

Capability parity: srcs/go/plan/topology.go:17-160 and
srcs/go/plan/subgraph/subgraph.go. Each generator returns broadcast graphs
(edges flow root -> leaves); the matching reduce graph is the reversal with
self-loops on every node (gen_default_reduce_graph, topology.go:33-40).

Host-locality-aware shapes (tree/star within a host, another shape across
host masters) map DCN topology: intra-host edges are loopback, inter-host
edges cross the network — on TPU pods this is the DCN between VM hosts.
"""

from __future__ import annotations

from typing import List, Tuple

from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerList


def gen_default_reduce_graph(bcast: Graph) -> Graph:
    """Reverse the broadcast graph and self-loop every node (accumulate)."""
    g = bcast.reverse()
    for i in range(g.n):
        g.add_edge(i, i)
    return g


def gen_star_bcast_graph(k: int, root: int = 0) -> Graph:
    g = Graph(k)
    for i in range(k):
        if i != root:
            g.add_edge(root, i)
    return g


def gen_binary_tree(k: int, root_offset: int = 0) -> Graph:
    """Heap-layout binary tree over ranks (i -> 2i+1, 2i+2), rotated by offset."""
    g = Graph(k)
    idx = lambda i: (i + root_offset) % k
    for i in range(k):
        for j in (2 * i + 1, 2 * i + 2):
            if j < k:
                g.add_edge(idx(i), idx(j))
    return g


def gen_tree(peers: PeerList) -> Graph:
    """Two-level tree: host masters star out to local peers; master[0] to other masters."""
    g = Graph(len(peers))
    masters, master_of = peers.partition_by_host()
    for rank in range(len(peers)):
        if master_of[rank] != rank:
            g.add_edge(master_of[rank], rank)
    for m in masters[1:]:
        g.add_edge(masters[0], m)
    return g


def gen_multi_star(peers: PeerList, root_idx: int = 0) -> Graph:
    """Intra-host stars + star over masters centered at masters[root_idx]."""
    g = Graph(len(peers))
    masters, master_of = peers.partition_by_host()
    for rank in range(len(peers)):
        if master_of[rank] != rank:
            g.add_edge(master_of[rank], rank)
    if len(masters) > 1:
        for i, m in enumerate(masters):
            if i != root_idx:
                g.add_edge(masters[root_idx], m)
    return g


def gen_multi_stars(peers: PeerList) -> List[Graph]:
    masters, _ = peers.partition_by_host()
    return [gen_multi_star(peers, i) for i in range(len(masters))]


def gen_binary_tree_star(peers: PeerList, offset: int = 0) -> Graph:
    """Intra-host stars + binary tree over host masters (rotated by offset)."""
    g = Graph(len(peers))
    masters, master_of = peers.partition_by_host()
    for rank in range(len(peers)):
        if master_of[rank] != rank:
            g.add_edge(master_of[rank], rank)
    k = len(masters)
    if k > 1:
        idx = lambda i: (i + offset) % k
        for i in range(k):
            for j in (2 * i + 1, 2 * i + 2):
                if j < k:
                    g.add_edge(masters[idx(i)], masters[idx(j)])
    return g


def gen_multi_binary_tree_star(peers: PeerList) -> List[Graph]:
    masters, _ = peers.partition_by_host()
    return [gen_binary_tree_star(peers, i) for i in range(len(masters))]


def gen_circular_graph_pair(k: int, r: int) -> Tuple[Graph, Graph]:
    """Ring (reduce, bcast) pair rooted at rank r.

    Reduce: chain (r+1) -> (r+2) -> ... -> r with self-loops everywhere
    (each hop accumulates). Bcast: chain r -> (r+1) -> ... -> (r+k-1).
    Used with chunking: chunk c uses root (c % k), giving a pipelined,
    bandwidth-optimal ring like the classic ring-allreduce.
    """
    reduce_g = Graph(k)
    bcast_g = Graph(k)
    for i in range(k):
        reduce_g.add_edge(i, i)
    for i in range(1, k):
        reduce_g.add_edge((r + i) % k, (r + i + 1) % k)
        bcast_g.add_edge((r + i - 1) % k, (r + i) % k)
    return reduce_g, bcast_g


def gen_subset_circular_graph_pair(n: int, ranks: List[int], r: int) -> Tuple[Graph, Graph]:
    """Ring pair over a subset of ranks (e.g. host masters), for cross-host
    allreduce. Mirrors subgraph.GenCircularGraphPair."""
    k = len(ranks)
    reduce_g = Graph(n)
    bcast_g = Graph(n)
    for i in ranks:
        reduce_g.add_edge(i, i)
    for i in range(1, k):
        reduce_g.add_edge(ranks[(r + i) % k], ranks[(r + i + 1) % k])
        bcast_g.add_edge(ranks[(r + i - 1) % k], ranks[(r + i) % k])
    return reduce_g, bcast_g


def gen_subset_binary_tree(n: int, ranks: List[int]) -> Graph:
    """Binary tree over a subset of ranks embedded in an n-rank graph."""
    g = Graph(n)
    k = len(ranks)
    for i in range(k):
        for j in (2 * i + 1, 2 * i + 2):
            if j < k:
                g.add_edge(ranks[i], ranks[j])
    return g
