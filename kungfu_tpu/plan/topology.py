"""Topology generators: broadcast/reduce graph pairs over a PeerList.

Capability parity: srcs/go/plan/topology.go:17-160 and
srcs/go/plan/subgraph/subgraph.go. Each generator returns broadcast graphs
(edges flow root -> leaves); the matching reduce graph is the reversal with
self-loops on every node (gen_default_reduce_graph, topology.go:33-40).

Host-locality-aware shapes (tree/star within a host, another shape across
host masters) map DCN topology: intra-host edges are loopback, inter-host
edges cross the network — on TPU pods this is the DCN between VM hosts.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerList


def gen_default_reduce_graph(bcast: Graph) -> Graph:
    """Reverse the broadcast graph and self-loop every node (accumulate)."""
    g = bcast.reverse()
    for i in range(g.n):
        g.add_edge(i, i)
    return g


def gen_star_bcast_graph(k: int, root: int = 0) -> Graph:
    g = Graph(k)
    for i in range(k):
        if i != root:
            g.add_edge(root, i)
    return g


def gen_binary_tree(k: int, root_offset: int = 0) -> Graph:
    """Heap-layout binary tree over ranks (i -> 2i+1, 2i+2), rotated by offset."""
    g = Graph(k)
    idx = lambda i: (i + root_offset) % k
    for i in range(k):
        for j in (2 * i + 1, 2 * i + 2):
            if j < k:
                g.add_edge(idx(i), idx(j))
    return g


def gen_tree(peers: PeerList) -> Graph:
    """Two-level tree: host masters star out to local peers; master[0] to other masters."""
    g = Graph(len(peers))
    masters, master_of = peers.partition_by_host()
    for rank in range(len(peers)):
        if master_of[rank] != rank:
            g.add_edge(master_of[rank], rank)
    for m in masters[1:]:
        g.add_edge(masters[0], m)
    return g


def gen_multi_star(peers: PeerList, root_idx: int = 0) -> Graph:
    """Intra-host stars + star over masters centered at masters[root_idx]."""
    g = Graph(len(peers))
    masters, master_of = peers.partition_by_host()
    for rank in range(len(peers)):
        if master_of[rank] != rank:
            g.add_edge(master_of[rank], rank)
    if len(masters) > 1:
        for i, m in enumerate(masters):
            if i != root_idx:
                g.add_edge(masters[root_idx], m)
    return g


def gen_multi_stars(peers: PeerList) -> List[Graph]:
    masters, _ = peers.partition_by_host()
    return [gen_multi_star(peers, i) for i in range(len(masters))]


def gen_binary_tree_star(peers: PeerList, offset: int = 0) -> Graph:
    """Intra-host stars + binary tree over host masters (rotated by offset)."""
    g = Graph(len(peers))
    masters, master_of = peers.partition_by_host()
    for rank in range(len(peers)):
        if master_of[rank] != rank:
            g.add_edge(master_of[rank], rank)
    k = len(masters)
    if k > 1:
        idx = lambda i: (i + offset) % k
        for i in range(k):
            for j in (2 * i + 1, 2 * i + 2):
                if j < k:
                    g.add_edge(masters[idx(i)], masters[idx(j)])
    return g


def gen_multi_binary_tree_star(peers: PeerList) -> List[Graph]:
    masters, _ = peers.partition_by_host()
    return [gen_binary_tree_star(peers, i) for i in range(len(masters))]


def gen_circular_graph_pair(k: int, r: int) -> Tuple[Graph, Graph]:
    """Ring (reduce, bcast) pair rooted at rank r.

    Reduce: chain (r+1) -> (r+2) -> ... -> r with self-loops everywhere
    (each hop accumulates). Bcast: chain r -> (r+1) -> ... -> (r+k-1).
    Used with chunking: chunk c uses root (c % k), giving a pipelined,
    bandwidth-optimal ring like the classic ring-allreduce.
    """
    reduce_g = Graph(k)
    bcast_g = Graph(k)
    for i in range(k):
        reduce_g.add_edge(i, i)
    for i in range(1, k):
        reduce_g.add_edge((r + i) % k, (r + i + 1) % k)
        bcast_g.add_edge((r + i - 1) % k, (r + i) % k)
    return reduce_g, bcast_g


def gen_subset_circular_graph_pair(n: int, ranks: List[int], r: int) -> Tuple[Graph, Graph]:
    """Ring pair over a subset of ranks (e.g. host masters), for cross-host
    allreduce. Mirrors subgraph.GenCircularGraphPair."""
    k = len(ranks)
    reduce_g = Graph(n)
    bcast_g = Graph(n)
    for i in ranks:
        reduce_g.add_edge(i, i)
    for i in range(1, k):
        reduce_g.add_edge(ranks[(r + i) % k], ranks[(r + i + 1) % k])
        bcast_g.add_edge(ranks[(r + i - 1) % k], ranks[(r + i) % k])
    return reduce_g, bcast_g


@dataclasses.dataclass(frozen=True)
class SegmentedSchedule:
    """Per-rank plan for a segmented ring allreduce over ``ranks``.

    The payload is split into ``k = len(ranks)`` contiguous segments.
    Phase 1 (reduce-scatter) runs k-1 steps; at each step every member
    sends one partially-reduced segment to its ring successor and
    accumulates the segment arriving from its predecessor. After it,
    member i holds the fully reduced segment ``(i+1) % k``. Phase 2
    (all-gather) runs k-1 more steps relaying reduced segments around the
    same ring. Every member therefore moves exactly
    ``2 * (sum of all segments except one)`` ≈ ``2*(k-1)/k * N`` bytes
    each way — the bandwidth-optimal schedule (arXiv:1810.11112 §3).

    ``rs_steps``/``ag_steps`` are (send_segment, recv_segment) pairs; the
    send/recv peers are fixed for the whole walk (ring successor and
    predecessor in ``ranks`` order).
    """

    ranks: Tuple[int, ...]  # participating global ranks in ring order
    index: int  # this member's position within ranks
    rs_steps: Tuple[Tuple[int, int], ...]
    ag_steps: Tuple[Tuple[int, int], ...]

    @property
    def k(self) -> int:
        return len(self.ranks)

    @property
    def send_peer(self) -> int:
        """Global rank of the ring successor (all sends go here)."""
        return self.ranks[(self.index + 1) % self.k]

    @property
    def recv_peer(self) -> int:
        """Global rank of the ring predecessor (all receives come from here)."""
        return self.ranks[(self.index - 1) % self.k]

    @property
    def owned_segment(self) -> int:
        """Segment this member holds fully reduced after reduce-scatter."""
        return (self.index + 1) % self.k


def segment_bounds(
    count: int, k: int, weights: Optional[Sequence[float]] = None
) -> List[Tuple[int, int]]:
    """THE segment partition of a k-ring payload: equal contiguous
    segments, or throughput-proportional ones when a measured plan
    supplies ``weights`` (ISSUE 14). Single-sourced so the walk engine,
    the owned-shard layout and every test derive identical bounds."""
    from kungfu_tpu.base.workspace import even_partition

    if weights is None:
        return even_partition(count, k)
    from kungfu_tpu.plan.replan import weighted_partition

    if len(weights) != k:
        raise ValueError(f"{len(weights)} weights for a ring of {k}")
    return weighted_partition(count, weights)


def owned_segment_bounds(
    count: int,
    k: int,
    index: int,
    order: Optional[Sequence[int]] = None,
    weights: Optional[Sequence[float]] = None,
) -> Tuple[int, int]:
    """(begin, end) element bounds of the segment rank ``index`` owns
    fully reduced after the reduce-scatter phase — THE shard layout
    of the ZeRO-1 sharded update (ISSUE 11). Single-sourced here so the
    walk engine's segment math and the sharded optimizer's shard views
    can never disagree: both call this, both get
    ``segment_bounds(count, k, weights)[owned_segment]``.

    With a measured-topology plan (ISSUE 14) pass its ring ``order``
    (ranks in ring order) and optional per-segment ``weights``: the
    owned segment follows the rank's POSITION in the reordered ring and
    the weighted partition, exactly as the reordered walk computes it —
    a plan change re-shards through this one function. Without a plan,
    ``index`` doubles as the ring position (the naive rank-order ring).
    k == 1 owns everything."""
    if k <= 1:
        return (0, count)
    members = list(order) if order is not None else list(range(k))
    sched = gen_segmented_schedule(members, members.index(index))
    return segment_bounds(count, k, weights)[sched.owned_segment]


def gen_segmented_schedule(ranks: List[int], index: int) -> SegmentedSchedule:
    """Segmented ring schedule for member ``index`` of ``ranks``.

    Every member computes its own table from the same (ranks, k) inputs,
    so the tables pair up cluster-wide without negotiation: the segment
    member i sends at step s is exactly the segment member i+1 expects to
    receive at step s (both phases).
    """
    k = len(ranks)
    if not 0 <= index < k:
        raise ValueError(f"index {index} outside ring of {k}")
    i = index
    rs = tuple(((i - s) % k, (i - s - 1) % k) for s in range(k - 1))
    ag = tuple(((i + 1 - s) % k, (i - s) % k) for s in range(k - 1))
    return SegmentedSchedule(ranks=tuple(ranks), index=i, rs_steps=rs, ag_steps=ag)


def gen_subset_binary_tree(n: int, ranks: List[int]) -> Graph:
    """Binary tree over a subset of ranks embedded in an n-rank graph."""
    g = Graph(n)
    k = len(ranks)
    for i in range(k):
        for j in (2 * i + 1, 2 * i + 2):
            if j < k:
                g.add_edge(ranks[i], ranks[j])
    return g
