"""Measured-topology re-planning (ROADMAP item 2 / ISSUE 14 tentpole).

PR 6 gave every worker a measured k×k bandwidth/latency matrix and PR 11
named the blocking (peer, edge) per training step; this module closes
the loop: pure functions that turn the MEASURED matrix into a better
ring plan — the source paper's "adapt the communication strategy to the
monitored network" applied to the segmented ring engine
(arXiv:1909.09756 motivates topology-matched collective shapes).

Everything here is a **pure, deterministic function of its inputs**:
every peer that feeds the same matrix in derives the byte-identical
:class:`RingPlan` out. That is the cluster-safety contract — the plan
digest is asserted on the knob-independent consensus walk at adoption
(``HostSession.adopt_replan``), so a peer whose derivation diverged
gets a named error, never a rendezvous hang.

Two levers:

- :func:`ring_order` — a ring permutation placing each peer next to its
  fastest measured links: greedy max-min-edge construction refined by
  2-opt (segment reversal, asymmetric-aware: candidate orders are
  re-scored, not mirrored). The objective is lexicographic
  ``(min edge bandwidth, total edge bandwidth)`` — a ring walk
  serializes on its slowest edge, so the minimum edge is what step
  wall-clock sees. Rank 0 stays first (rings are rotation-invariant;
  pinning the start keeps plans canonical and diffs readable).
- :func:`weighted_partition` — contiguous throughput-proportional
  segments. The owned segment sizes the per-peer work that does NOT
  rotate around the ring: the ZeRO-1 shard update (optimizer FLOPs +
  state ∝ owned size), the all-gather seed encode, and the one segment
  a peer never sends. A slow peer gets a smaller owned segment, so the
  update tail stops straggling on it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

# weights are clamped to [mean/CLAMP, mean*CLAMP] before normalizing: a
# wildly mis-measured peer must shift segment sizes, not collapse its
# segment to zero (an empty owned segment would drop that peer's update
# work entirely and concentrate it elsewhere)
WEIGHT_CLAMP = 4.0
# 2-opt refinement passes are capped for bounded runtime at k=64 (the
# scan is deterministic first-improvement, so the cap never introduces
# cross-peer divergence — every peer stops at the same pass)
MAX_2OPT_PASSES = 64


def weighted_partition(
    count: int, weights: Sequence[float]
) -> List[Tuple[int, int]]:
    """Split [0, count) into ``len(weights)`` contiguous intervals with
    sizes proportional to ``weights``.

    Boundaries are cumulative-rounded (``floor(count·cum + 0.5)``), which
    gives three properties the shard layout depends on (property-tested):

    - **contiguous + lossless**: intervals tile [0, count) exactly;
    - **monotone**: growing one weight (others fixed) never shrinks its
      interval — boundaries left of it stay put, boundaries right of it
      only move right;
    - **degenerate-safe**: an all-zero weight vector falls back to
      :func:`~kungfu_tpu.base.workspace.even_partition`; ``count < k``
      produces empty intervals exactly like the even split.

    Negative weights are a caller bug and raise."""
    k = len(weights)
    if k <= 0:
        raise ValueError("weighted_partition needs at least one weight")
    w = [float(x) for x in weights]
    if any(x < 0 for x in w):
        raise ValueError(f"weights must be non-negative, got {w}")
    total = sum(w)
    if total <= 0.0:
        from kungfu_tpu.base.workspace import even_partition

        return even_partition(count, k)
    bounds: List[Tuple[int, int]] = []
    cum = 0.0
    prev = 0
    for i in range(k):
        cum += w[i]
        end = count if i == k - 1 else min(count, int(count * (cum / total) + 0.5))
        end = max(end, prev)
        bounds.append((prev, end))
        prev = end
    return bounds


@dataclasses.dataclass(frozen=True)
class RingPlan:
    """A measured-topology plan for the global segmented ring.

    ``order`` is the ranks in ring order (a permutation of
    ``range(k)``, ``order[0] == 0``); ``weights`` — when present — are
    per-SEGMENT weights (segment ``s`` is owned by the member at ring
    position ``(s - 1) % k``, i.e. rank ``order[(s - 1) % k]``), summing
    to ~1. ``gain`` is the optimizer's predicted step-throughput ratio
    vs the plan it replaces (min-ring-edge bandwidth ratio — the edge a
    ring walk serializes on).

    Byte serialization is canonical (sorted keys, fixed float rounding
    upstream), so equality of derivations is equality of bytes — what
    the adoption digest asserts."""

    order: Tuple[int, ...]
    weights: Optional[Tuple[float, ...]] = None
    gain: float = 1.0

    def __post_init__(self):
        k = len(self.order)
        if sorted(self.order) != list(range(k)):
            raise ValueError(f"order must be a permutation of 0..{k - 1}: "
                             f"{self.order}")
        if self.weights is not None and len(self.weights) != k:
            raise ValueError(
                f"{len(self.weights)} weights for a ring of {k}"
            )

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "order": list(self.order),
                "weights": (
                    None if self.weights is None else list(self.weights)
                ),
                "gain": round(float(self.gain), 6),
            },
            sort_keys=True, separators=(",", ":"),
        ).encode()

    def digest(self) -> bytes:
        return hashlib.blake2b(self.to_bytes(), digest_size=16).digest()

    def describe(self) -> str:
        arrow = "→".join(str(r) for r in self.order)
        w = "" if self.weights is None else " (weighted segments)"
        return f"{arrow}{w}"


def plan_digest(plan: Optional[RingPlan]) -> bytes:
    """Digest of a possibly-absent plan (None = the naive rank-order
    ring with equal segments) — the bytes the adoption consensus walks."""
    return plan.digest() if plan is not None else b"naive-ring"


def _fill_unknown(bw: np.ndarray) -> Optional[np.ndarray]:
    """Score matrix with unknown (<= 0 / non-finite) edges set to the
    median known estimate — unknown is neutral, not slow. None when
    nothing is estimated at all."""
    m = np.array(bw, np.float64, copy=True)
    k = m.shape[0]
    mask = np.isfinite(m) & (m > 0)
    np.fill_diagonal(mask, False)
    known = m[mask]
    if known.size == 0:
        return None
    fill = float(np.median(known))
    m[~mask] = fill
    np.fill_diagonal(m, 0.0)
    return m


def _ring_edges(order: Sequence[int]) -> List[Tuple[int, int]]:
    k = len(order)
    return [(order[i], order[(i + 1) % k]) for i in range(k)]


def _objective(score: np.ndarray, order: Sequence[int]) -> Tuple[float, float]:
    """(min edge, sum of edges) — lexicographic, maximized."""
    edges = _ring_edges(order)
    vals = [float(score[i, j]) for i, j in edges]
    return (min(vals), sum(vals))


def ring_order(bw: np.ndarray) -> Tuple[int, ...]:
    """Deterministic ring permutation over ``range(k)`` maximizing the
    lexicographic ``(min edge bw, total edge bw)`` objective: greedy
    max-min-edge construction (append the unvisited peer with the
    fastest measured link from the current tail; ties take the lowest
    rank) followed by 2-opt refinement (first-improvement segment
    reversal with rank 0 pinned first; candidate orders are re-scored
    against the DIRECTED matrix, so asymmetric links are handled).

    Pure function of the matrix: every peer derives the identical
    permutation from the same bytes. A matrix with no estimates, a
    uniform matrix, or k <= 2 returns rank order (re-planning is a
    no-op without information)."""
    k = int(np.asarray(bw).shape[0])
    identity = tuple(range(k))
    if k <= 2:
        return identity
    score = _fill_unknown(np.asarray(bw))
    if score is None:
        return identity
    off_diag = score[~np.eye(k, dtype=bool)]
    if off_diag.size and np.allclose(off_diag, off_diag[0], rtol=1e-6):
        return identity  # uniform: nothing to optimize, keep rank order
    # greedy max-min-edge construction
    order = [0]
    remaining = set(range(1, k))
    while remaining:
        last = order[-1]
        best = max(
            sorted(remaining), key=lambda c: (score[last, c], -c)
        )
        order.append(best)
        remaining.discard(best)
    # 2-opt refinement (rank 0 pinned at position 0)
    best_obj = _objective(score, order)
    for _ in range(MAX_2OPT_PASSES):
        improved = False
        for i in range(1, k - 1):
            for j in range(i + 1, k):
                cand = order[:i] + list(reversed(order[i:j + 1])) + order[j + 1:]
                obj = _objective(score, cand)
                if obj > best_obj:
                    order, best_obj = cand, obj
                    improved = True
        if not improved:
            break
    return tuple(order)


def peer_throughput_weights(bw: np.ndarray) -> Optional[Tuple[float, ...]]:
    """Per-RANK throughput weights from the matrix: the mean of each
    peer's known outgoing estimates (its measured ability to move
    bytes), clamped to ``mean/WEIGHT_CLAMP .. mean*WEIGHT_CLAMP`` and
    normalized to sum 1. None when unmeasured or effectively uniform
    (equal segments already optimal)."""
    m = np.asarray(bw, np.float64)
    k = m.shape[0]
    mask = np.isfinite(m) & (m > 0)
    np.fill_diagonal(mask, False)
    if not mask.any():
        return None
    fill = float(np.median(m[mask]))
    rows = np.where(mask, m, fill)
    np.fill_diagonal(rows, 0.0)
    per_rank = rows.sum(axis=1) / max(1, k - 1)
    return weights_from_throughput(per_rank)


def weights_from_throughput(
    throughput: Sequence[float],
) -> Optional[Tuple[float, ...]]:
    """Normalize measured per-peer throughputs into segment weights:
    clamp the spread to ``WEIGHT_CLAMP`` around the mean (a bad estimate
    shifts work, never zeroes a peer out), normalize to sum 1, round for
    canonical bytes. None when the result is effectively uniform."""
    t = np.asarray([float(x) for x in throughput], np.float64)
    if t.size == 0 or not np.isfinite(t).all() or (t <= 0).any():
        return None
    mean = float(t.mean())
    t = np.clip(t, mean / WEIGHT_CLAMP, mean * WEIGHT_CLAMP)
    t = t / t.sum()
    if np.allclose(t, 1.0 / t.size, rtol=1e-3, atol=1e-9):
        return None
    return tuple(round(float(x), 9) for x in t)


def segment_weights(
    order: Sequence[int], rank_weights: Sequence[float]
) -> Tuple[float, ...]:
    """Re-index per-RANK weights into per-SEGMENT weights: segment ``s``
    is owned by the member at ring position ``(s - 1) % k``
    (SegmentedSchedule.owned_segment), so its weight is that rank's."""
    k = len(order)
    return tuple(
        rank_weights[order[(s - 1) % k]] for s in range(k)
    )


def min_edge_bw(bw: np.ndarray, order: Sequence[int]) -> Optional[float]:
    """Slowest MEASURED ring edge of ``order`` (None when the ring
    touches no estimated edge) — the denominator of predicted gain."""
    m = np.asarray(bw, np.float64)
    vals = [
        float(m[i, j]) for i, j in _ring_edges(order)
        if np.isfinite(m[i, j]) and m[i, j] > 0
    ]
    return min(vals) if vals else None


def derive_plan(
    bw: np.ndarray,
    mode: str = "auto",
    current: Optional[RingPlan] = None,
    compute_frac: float = 0.0,
) -> Optional[RingPlan]:
    """Turn the merged k×k bandwidth matrix into a :class:`RingPlan`,
    or None when re-planning would be a no-op (no estimates, uniform
    matrix, or the derived plan equals the current one).

    ``mode`` mirrors ``KF_CONFIG_REPLAN``: ``ring`` reorders only,
    ``ring+segments``/``auto`` also weight the segments by measured
    per-peer throughput. Pure function of (matrix bytes, mode, current
    plan, compute_frac) — the cross-peer determinism the adoption
    digest asserts; callers must feed a cluster-agreed ``compute_frac``
    (``HostSession.check_replan`` all-gathers it).

    ``compute_frac`` is the measured compute floor from the resource
    plane (ISSUE 16): the fraction of the step the busiest peer spends
    burning CPU rather than waiting on the network. Amdahl caps what a
    ring re-order can buy — only the network share shrinks — so the
    predicted gain is clamped to ``1 / compute_frac`` (r12's ledger
    showed the unclamped min-edge-bandwidth predictor 86x optimistic on
    a CPU-bound host run). 0.0 = unmeasured, no clamp: a missing
    measurement must never fabricate pessimism."""
    if mode in ("off", ""):
        return None
    if mode not in ("ring", "ring+segments", "auto"):
        raise ValueError(f"unknown replan mode: {mode!r}")
    m = np.asarray(bw, np.float64)
    k = int(m.shape[0])
    if k < 2 or m.shape != (k, k):
        return None
    order = ring_order(m)
    weights: Optional[Tuple[float, ...]] = None
    if mode in ("ring+segments", "auto"):
        rank_w = peer_throughput_weights(m)
        if rank_w is not None:
            weights = segment_weights(order, rank_w)
    cur_order = current.order if current is not None else tuple(range(k))
    cur_weights = current.weights if current is not None else None
    if order == cur_order and weights == cur_weights:
        return None
    old_min = min_edge_bw(m, cur_order)
    new_min = min_edge_bw(m, order)
    gain = 1.0
    if old_min and new_min and old_min > 0:
        gain = new_min / old_min
    cf = float(compute_frac)
    if cf > 0.0 and np.isfinite(cf):
        gain = min(gain, 1.0 / max(min(cf, 1.0), 1e-6))
    return RingPlan(order=order, weights=weights, gain=round(gain, 6))
