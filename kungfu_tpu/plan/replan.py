"""Measured-topology re-planning (ROADMAP item 2 / ISSUE 14 tentpole).

PR 6 gave every worker a measured k×k bandwidth/latency matrix and PR 11
named the blocking (peer, edge) per training step; this module closes
the loop: pure functions that turn the MEASURED matrix into a better
ring plan — the source paper's "adapt the communication strategy to the
monitored network" applied to the segmented ring engine
(arXiv:1909.09756 motivates topology-matched collective shapes).

Everything here is a **pure, deterministic function of its inputs**:
every peer that feeds the same matrix in derives the byte-identical
:class:`RingPlan` out. That is the cluster-safety contract — the plan
digest is asserted on the knob-independent consensus walk at adoption
(``HostSession.adopt_replan``), so a peer whose derivation diverged
gets a named error, never a rendezvous hang.

Two levers:

- :func:`ring_order` — a ring permutation placing each peer next to its
  fastest measured links: greedy max-min-edge construction refined by
  2-opt (segment reversal, asymmetric-aware: candidate orders are
  re-scored, not mirrored). The objective is lexicographic
  ``(min edge bandwidth, total edge bandwidth)`` — a ring walk
  serializes on its slowest edge, so the minimum edge is what step
  wall-clock sees. Rank 0 stays first (rings are rotation-invariant;
  pinning the start keeps plans canonical and diffs readable).
- :func:`weighted_partition` — contiguous throughput-proportional
  segments. The owned segment sizes the per-peer work that does NOT
  rotate around the ring: the ZeRO-1 shard update (optimizer FLOPs +
  state ∝ owned size), the all-gather seed encode, and the one segment
  a peer never sends. A slow peer gets a smaller owned segment, so the
  update tail stops straggling on it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

# weights are clamped to [mean/CLAMP, mean*CLAMP] before normalizing: a
# wildly mis-measured peer must shift segment sizes, not collapse its
# segment to zero (an empty owned segment would drop that peer's update
# work entirely and concentrate it elsewhere)
WEIGHT_CLAMP = 4.0
# 2-opt refinement passes are capped for bounded runtime at k=64 (the
# scan is deterministic first-improvement, so the cap never introduces
# cross-peer divergence — every peer stops at the same pass)
MAX_2OPT_PASSES = 64


def weighted_partition(
    count: int, weights: Sequence[float]
) -> List[Tuple[int, int]]:
    """Split [0, count) into ``len(weights)`` contiguous intervals with
    sizes proportional to ``weights``.

    Boundaries are cumulative-rounded (``floor(count·cum + 0.5)``), which
    gives three properties the shard layout depends on (property-tested):

    - **contiguous + lossless**: intervals tile [0, count) exactly;
    - **monotone**: growing one weight (others fixed) never shrinks its
      interval — boundaries left of it stay put, boundaries right of it
      only move right;
    - **degenerate-safe**: an all-zero weight vector falls back to
      :func:`~kungfu_tpu.base.workspace.even_partition`; ``count < k``
      produces empty intervals exactly like the even split.

    Negative weights are a caller bug and raise."""
    k = len(weights)
    if k <= 0:
        raise ValueError("weighted_partition needs at least one weight")
    w = [float(x) for x in weights]
    if any(x < 0 for x in w):
        raise ValueError(f"weights must be non-negative, got {w}")
    total = sum(w)
    if total <= 0.0:
        from kungfu_tpu.base.workspace import even_partition

        return even_partition(count, k)
    bounds: List[Tuple[int, int]] = []
    cum = 0.0
    prev = 0
    for i in range(k):
        cum += w[i]
        end = count if i == k - 1 else min(count, int(count * (cum / total) + 0.5))
        end = max(end, prev)
        bounds.append((prev, end))
        prev = end
    return bounds


@dataclasses.dataclass(frozen=True)
class RingPlan:
    """A measured-topology plan for the global segmented ring.

    ``order`` is the ranks in ring order (a permutation of
    ``range(k)``, ``order[0] == 0``); ``weights`` — when present — are
    per-SEGMENT weights (segment ``s`` is owned by the member at ring
    position ``(s - 1) % k``, i.e. rank ``order[(s - 1) % k]``), summing
    to ~1. ``gain`` is the optimizer's predicted step-throughput ratio
    vs the plan it replaces (min-ring-edge bandwidth ratio — the edge a
    ring walk serializes on).

    Byte serialization is canonical (sorted keys, fixed float rounding
    upstream), so equality of derivations is equality of bytes — what
    the adoption digest asserts."""

    order: Tuple[int, ...]
    weights: Optional[Tuple[float, ...]] = None
    gain: float = 1.0

    def __post_init__(self):
        k = len(self.order)
        if sorted(self.order) != list(range(k)):
            raise ValueError(f"order must be a permutation of 0..{k - 1}: "
                             f"{self.order}")
        if self.weights is not None and len(self.weights) != k:
            raise ValueError(
                f"{len(self.weights)} weights for a ring of {k}"
            )

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "order": list(self.order),
                "weights": (
                    None if self.weights is None else list(self.weights)
                ),
                "gain": round(float(self.gain), 6),
            },
            sort_keys=True, separators=(",", ":"),
        ).encode()

    def digest(self) -> bytes:
        return hashlib.blake2b(self.to_bytes(), digest_size=16).digest()

    def describe(self) -> str:
        arrow = "→".join(str(r) for r in self.order)
        w = "" if self.weights is None else " (weighted segments)"
        return f"{arrow}{w}"


@dataclasses.dataclass(frozen=True)
class HierPlan:
    """A measured-topology TWO-LEVEL plan (ISSUE 19): per-host intra
    star (reduce members → a host head over the fast local links) × an
    inter-host ring over the heads (the wire-codec-eligible DCN leg) ×
    an intra broadcast back out — the 2D hierarchical all-reduce shape
    arXiv:1909.09756 scales to pod size.

    ``groups`` are the host groups in INTER-RING order; each group
    tuple lists its members with the elected head FIRST. ``heads`` is
    the per-group head (``heads[i] == groups[i][0]``), so the inter
    ring is ``heads[0] → heads[1] → … → heads[0]``. ``demoted`` ranks
    stay members of their group (they receive the result in the final
    broadcast) but contribute nothing: excluded from head election,
    from the inter ring, and from the reduce — the source paper's
    adaptive peer selection, a persistent straggler moved to a backup
    role instead of serializing the ring.

    Byte serialization is canonical exactly like :class:`RingPlan` —
    adoption walks the digest, so a diverged derivation is a named
    error, never a hang."""

    groups: Tuple[Tuple[int, ...], ...]
    heads: Tuple[int, ...]
    demoted: Tuple[int, ...] = ()
    gain: float = 1.0

    def __post_init__(self):
        members = [r for g in self.groups for r in g]
        k = len(members)
        if sorted(members) != list(range(k)):
            raise ValueError(
                f"groups must partition 0..{k - 1}: {self.groups}"
            )
        if len(self.heads) != len(self.groups):
            raise ValueError(
                f"{len(self.heads)} heads for {len(self.groups)} groups"
            )
        for head, grp in zip(self.heads, self.groups):
            if not grp or grp[0] != head:
                raise ValueError(
                    f"head {head} must lead its group {grp}"
                )
            if head in self.demoted:
                raise ValueError(f"head {head} cannot be demoted")
        if list(self.demoted) != sorted(set(self.demoted)):
            raise ValueError(f"demoted must be sorted unique: "
                             f"{self.demoted}")
        for d in self.demoted:
            if d not in members:
                raise ValueError(f"demoted rank {d} not in any group")

    @property
    def size(self) -> int:
        return sum(len(g) for g in self.groups)

    def group_of(self, rank: int) -> int:
        for gi, g in enumerate(self.groups):
            if rank in g:
                return gi
        raise ValueError(f"rank {rank} not in plan")

    def active(self) -> Tuple[int, ...]:
        """Contributing ranks (everyone not demoted), in group order."""
        dem = set(self.demoted)
        return tuple(
            r for g in self.groups for r in g if r not in dem
        )

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "kind": "hier",
                "groups": [list(g) for g in self.groups],
                "heads": list(self.heads),
                "demoted": list(self.demoted),
                "gain": round(float(self.gain), 6),
            },
            sort_keys=True, separators=(",", ":"),
        ).encode()

    def digest(self) -> bytes:
        return hashlib.blake2b(self.to_bytes(), digest_size=16).digest()

    def describe(self) -> str:
        parts = []
        for head, grp in zip(self.heads, self.groups):
            inner = ",".join(
                (f"{r}▽" if r in self.demoted else str(r))
                for r in grp
            )
            parts.append(f"[{inner}|h{head}]")
        return "→".join(parts)

    def as_ring_plan(self) -> RingPlan:
        """Flat projection for everything that thinks in one ring: the
        ZeRO shard layout (``owned_bounds``), the ring-position gauges,
        and the segmented RS/AG legs. Order concatenates the groups in
        inter-ring order (rotated so rank 0 leads, rings being
        rotation-invariant); demoted ranks carry ZERO segment weight —
        an empty owned shard, no update work parked on a straggler."""
        flat = [r for g in self.groups for r in g]
        zero = flat.index(0)
        order = tuple(flat[zero:] + flat[:zero])
        k = len(order)
        weights: Optional[Tuple[float, ...]] = None
        if self.demoted:
            rank_w = [0.0 if r in self.demoted else 1.0
                      for r in range(k)]
            total = sum(rank_w)
            if total > 0:
                rank_w = [w / total for w in rank_w]
            weights = tuple(
                round(float(x), 9)
                for x in segment_weights(order, rank_w)
            )
        return RingPlan(order=order, weights=weights,
                        gain=round(float(self.gain), 6))


def plan_digest(plan) -> bytes:
    """Digest of a possibly-absent plan (None = the naive rank-order
    ring with equal segments) — the bytes the adoption consensus walks.
    Accepts :class:`RingPlan` or :class:`HierPlan` (canonical bytes
    disambiguate the two)."""
    return plan.digest() if plan is not None else b"naive-ring"


def _fill_unknown(bw: np.ndarray) -> Optional[np.ndarray]:
    """Score matrix with unknown (<= 0 / non-finite) edges set to the
    median known estimate — unknown is neutral, not slow. None when
    nothing is estimated at all."""
    m = np.array(bw, np.float64, copy=True)
    k = m.shape[0]
    mask = np.isfinite(m) & (m > 0)
    np.fill_diagonal(mask, False)
    known = m[mask]
    if known.size == 0:
        return None
    fill = float(np.median(known))
    m[~mask] = fill
    np.fill_diagonal(m, 0.0)
    return m


def _ring_edges(order: Sequence[int]) -> List[Tuple[int, int]]:
    k = len(order)
    return [(order[i], order[(i + 1) % k]) for i in range(k)]


def _objective(score: np.ndarray, order: Sequence[int]) -> Tuple[float, float]:
    """(min edge, sum of edges) — lexicographic, maximized."""
    edges = _ring_edges(order)
    vals = [float(score[i, j]) for i, j in edges]
    return (min(vals), sum(vals))


def ring_order(bw: np.ndarray) -> Tuple[int, ...]:
    """Deterministic ring permutation over ``range(k)`` maximizing the
    lexicographic ``(min edge bw, total edge bw)`` objective: greedy
    max-min-edge construction (append the unvisited peer with the
    fastest measured link from the current tail; ties take the lowest
    rank) followed by 2-opt refinement (first-improvement segment
    reversal with rank 0 pinned first; candidate orders are re-scored
    against the DIRECTED matrix, so asymmetric links are handled).

    Pure function of the matrix: every peer derives the identical
    permutation from the same bytes. A matrix with no estimates, a
    uniform matrix, or k <= 2 returns rank order (re-planning is a
    no-op without information)."""
    k = int(np.asarray(bw).shape[0])
    identity = tuple(range(k))
    if k <= 2:
        return identity
    score = _fill_unknown(np.asarray(bw))
    if score is None:
        return identity
    off_diag = score[~np.eye(k, dtype=bool)]
    if off_diag.size and np.allclose(off_diag, off_diag[0], rtol=1e-6):
        return identity  # uniform: nothing to optimize, keep rank order
    # greedy max-min-edge construction
    order = [0]
    remaining = set(range(1, k))
    while remaining:
        last = order[-1]
        best = max(
            sorted(remaining), key=lambda c: (score[last, c], -c)
        )
        order.append(best)
        remaining.discard(best)
    # 2-opt refinement (rank 0 pinned at position 0)
    best_obj = _objective(score, order)
    for _ in range(MAX_2OPT_PASSES):
        improved = False
        for i in range(1, k - 1):
            for j in range(i + 1, k):
                cand = order[:i] + list(reversed(order[i:j + 1])) + order[j + 1:]
                obj = _objective(score, cand)
                if obj > best_obj:
                    order, best_obj = cand, obj
                    improved = True
        if not improved:
            break
    return tuple(order)


def peer_throughput_weights(bw: np.ndarray) -> Optional[Tuple[float, ...]]:
    """Per-RANK throughput weights from the matrix: the mean of each
    peer's known outgoing estimates (its measured ability to move
    bytes), clamped to ``mean/WEIGHT_CLAMP .. mean*WEIGHT_CLAMP`` and
    normalized to sum 1. None when unmeasured or effectively uniform
    (equal segments already optimal)."""
    m = np.asarray(bw, np.float64)
    k = m.shape[0]
    mask = np.isfinite(m) & (m > 0)
    np.fill_diagonal(mask, False)
    if not mask.any():
        return None
    fill = float(np.median(m[mask]))
    rows = np.where(mask, m, fill)
    np.fill_diagonal(rows, 0.0)
    per_rank = rows.sum(axis=1) / max(1, k - 1)
    return weights_from_throughput(per_rank)


def weights_from_throughput(
    throughput: Sequence[float],
) -> Optional[Tuple[float, ...]]:
    """Normalize measured per-peer throughputs into segment weights:
    clamp the spread to ``WEIGHT_CLAMP`` around the mean (a bad estimate
    shifts work, never zeroes a peer out), normalize to sum 1, round for
    canonical bytes. None when the result is effectively uniform."""
    t = np.asarray([float(x) for x in throughput], np.float64)
    if t.size == 0 or not np.isfinite(t).all() or (t <= 0).any():
        return None
    mean = float(t.mean())
    t = np.clip(t, mean / WEIGHT_CLAMP, mean * WEIGHT_CLAMP)
    t = t / t.sum()
    if np.allclose(t, 1.0 / t.size, rtol=1e-3, atol=1e-9):
        return None
    return tuple(round(float(x), 9) for x in t)


def segment_weights(
    order: Sequence[int], rank_weights: Sequence[float]
) -> Tuple[float, ...]:
    """Re-index per-RANK weights into per-SEGMENT weights: segment ``s``
    is owned by the member at ring position ``(s - 1) % k``
    (SegmentedSchedule.owned_segment), so its weight is that rank's."""
    k = len(order)
    return tuple(
        rank_weights[order[(s - 1) % k]] for s in range(k)
    )


def min_edge_bw(bw: np.ndarray, order: Sequence[int]) -> Optional[float]:
    """Slowest MEASURED ring edge of ``order`` (None when the ring
    touches no estimated edge) — the denominator of predicted gain."""
    m = np.asarray(bw, np.float64)
    vals = [
        float(m[i, j]) for i, j in _ring_edges(order)
        if np.isfinite(m[i, j]) and m[i, j] > 0
    ]
    return min(vals) if vals else None


def derive_plan(
    bw: np.ndarray,
    mode: str = "auto",
    current: Optional[RingPlan] = None,
    compute_frac: float = 0.0,
) -> Optional[RingPlan]:
    """Turn the merged k×k bandwidth matrix into a :class:`RingPlan`,
    or None when re-planning would be a no-op (no estimates, uniform
    matrix, or the derived plan equals the current one).

    ``mode`` mirrors ``KF_CONFIG_REPLAN``: ``ring`` reorders only,
    ``ring+segments``/``auto`` also weight the segments by measured
    per-peer throughput. Pure function of (matrix bytes, mode, current
    plan, compute_frac) — the cross-peer determinism the adoption
    digest asserts; callers must feed a cluster-agreed ``compute_frac``
    (``HostSession.check_replan`` all-gathers it).

    ``compute_frac`` is the measured compute floor from the resource
    plane (ISSUE 16): the fraction of the step the busiest peer spends
    burning CPU rather than waiting on the network. Amdahl caps what a
    ring re-order can buy — only the network share shrinks — so the
    predicted gain is clamped to ``1 / compute_frac`` (r12's ledger
    showed the unclamped min-edge-bandwidth predictor 86x optimistic on
    a CPU-bound host run). 0.0 = unmeasured, no clamp: a missing
    measurement must never fabricate pessimism."""
    if mode in ("off", ""):
        return None
    if mode not in ("ring", "ring+segments", "auto"):
        raise ValueError(f"unknown replan mode: {mode!r}")
    m = np.asarray(bw, np.float64)
    k = int(m.shape[0])
    if k < 2 or m.shape != (k, k):
        return None
    order = ring_order(m)
    weights: Optional[Tuple[float, ...]] = None
    if mode in ("ring+segments", "auto"):
        rank_w = peer_throughput_weights(m)
        if rank_w is not None:
            weights = segment_weights(order, rank_w)
    cur_order = current.order if current is not None else tuple(range(k))
    cur_weights = current.weights if current is not None else None
    if order == cur_order and weights == cur_weights:
        return None
    old_min = min_edge_bw(m, cur_order)
    new_min = min_edge_bw(m, order)
    gain = 1.0
    if old_min and new_min and old_min > 0:
        gain = new_min / old_min
    cf = float(compute_frac)
    if cf > 0.0 and np.isfinite(cf):
        gain = min(gain, 1.0 / max(min(cf, 1.0), 1e-6))
    return RingPlan(order=order, weights=weights, gain=round(gain, 6))


# ---------------------------------------------------------------------------
# two-level (hierarchical) plans — ISSUE 19
# ---------------------------------------------------------------------------

# a measured matrix is considered bimodal (fast intra-host links vs
# slow cross-host links) when the edge values split at a log-gap of at
# least this ratio; below it, clustering falls back to the static host
# partition (the measurement cannot distinguish the levels)
HIER_BIMODAL_RATIO = 4.0


def cluster_hosts(
    bw: np.ndarray,
    fallback: Sequence[Sequence[int]] = (),
) -> List[List[int]]:
    """Group ranks into host-like clusters from the MEASURED matrix:
    symmetrize (max of the two directions), sort the edge estimates,
    cut at the largest log-gap, and union-find the edges above the cut
    — intra-host links (shm/loopback) measure orders of magnitude
    faster than the DCN, so the gap is the host boundary.

    Deterministic function of the matrix bytes (cluster-safety: every
    peer derives identical groups). Falls back to ``fallback`` (the
    static host partition, each inner list sorted) when the matrix is
    unmeasured, unimodal (gap ratio < :data:`HIER_BIMODAL_RATIO`), or
    the cut yields a degenerate grouping; an empty fallback means
    "no grouping" ([])."""
    m = np.asarray(bw, np.float64)
    k = int(m.shape[0])
    fb = [sorted(int(r) for r in g) for g in fallback if len(g)]
    fb.sort(key=lambda g: g[0])
    if k < 2 or m.shape != (k, k):
        return fb
    sym = np.maximum(m, m.T)
    mask = np.isfinite(sym) & (sym > 0)
    np.fill_diagonal(mask, False)
    iu = np.triu_indices(k, 1)
    vals = sym[iu][mask[iu]]
    if vals.size < 2:
        return fb
    s = np.sort(vals)
    logs = np.log(s)
    gaps = np.diff(logs)
    gi = int(np.argmax(gaps))
    ratio = float(s[gi + 1] / s[gi]) if s[gi] > 0 else 0.0
    if not np.isfinite(ratio) or ratio < HIER_BIMODAL_RATIO:
        return fb
    thresh = float(np.sqrt(s[gi] * s[gi + 1]))  # geometric midpoint
    # union-find over edges faster than the cut
    parent = list(range(k))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for i in range(k):
        for j in range(i + 1, k):
            if mask[i, j] and sym[i, j] > thresh:
                ri, rj = find(i), find(j)
                if ri != rj:
                    parent[max(ri, rj)] = min(ri, rj)
    comps: dict = {}
    for r in range(k):
        comps.setdefault(find(r), []).append(r)
    groups = sorted(comps.values(), key=lambda g: g[0])
    if len(groups) < 2 or len(groups) == k:
        return fb  # one blob or all singletons: the cut told us nothing
    return [sorted(g) for g in groups]


def _cross_group_bw(
    sym: np.ndarray, rank: int, own: Sequence[int]
) -> float:
    """Mean measured bandwidth from ``rank`` to ranks OUTSIDE its group
    — the head-election score (the head carries the uplink leg)."""
    k = sym.shape[0]
    own_set = set(own)
    vals = [
        float(sym[rank, j]) for j in range(k)
        if j not in own_set
        and np.isfinite(sym[rank, j]) and sym[rank, j] > 0
    ]
    return sum(vals) / len(vals) if vals else 0.0


def derive_hier_plan(
    bw: np.ndarray,
    hosts: Sequence[Sequence[int]] = (),
    mode: str = "hier",
    current=None,
    compute_frac: float = 0.0,
    demoted: Sequence[int] = (),
) -> Optional[HierPlan]:
    """Turn the merged k×k matrix into a two-level :class:`HierPlan`,
    or None when a hierarchy would be a no-op: fewer than two host
    groups (nothing to nest), a group left with no contributing member
    (every candidate head demoted), or a derivation byte-identical to
    ``current``.

    Pure function of (matrix bytes, hosts, mode, current, compute_frac,
    demoted) — same determinism contract as :func:`derive_plan`; the
    caller (``HostSession.check_replan``) feeds cluster-agreed inputs
    only. Host grouping prefers the measured clustering
    (:func:`cluster_hosts`) and falls back to the static ``hosts``
    partition; head election takes the highest measured cross-group
    bandwidth (ties to the lowest rank); the inter-host ring over the
    heads is :func:`ring_order` on the head submatrix.

    Predicted ``gain`` compares serialized bytes/bandwidth of the flat
    ring (2·(k-1)/k·N at its min edge) against the two-level walk
    (2·(H-1)/H·N at the min inter-head edge + 2·N at the min intra
    edge), Amdahl-clamped by ``compute_frac`` like :func:`derive_plan`
    — a prediction the decision ledger grades with a measured verdict."""
    if mode in ("off", ""):
        return None
    m = np.asarray(bw, np.float64)
    k = int(m.shape[0])
    if k < 2 or m.shape != (k, k):
        return None
    dem = tuple(sorted({int(d) for d in demoted if 0 <= int(d) < k}))
    groups = cluster_hosts(m, fallback=hosts)
    if len(groups) < 2:
        return None
    if sorted(r for g in groups for r in g) != list(range(k)):
        return None  # partial partition: refuse to guess
    sym = np.maximum(m, m.T)
    np.fill_diagonal(sym, 0.0)
    heads: List[int] = []
    ordered_groups: List[List[int]] = []
    for g in groups:
        cands = [r for r in g if r not in dem]
        if not cands:
            return None  # a fully-demoted host has no head to carry it
        head = max(
            cands,
            key=lambda r: (_cross_group_bw(sym, r, g), -r),
        )
        heads.append(head)
        ordered_groups.append([head] + [r for r in g if r != head])
    # inter-host ring over the heads: ring_order on the head submatrix
    # (heads ascending → index 0 is the lowest head, which ring_order
    # pins first — canonical across peers)
    hsorted = sorted(range(len(heads)), key=lambda i: heads[i])
    sub = m[np.ix_([heads[i] for i in hsorted],
                   [heads[i] for i in hsorted])]
    inter = ring_order(sub)
    perm = [hsorted[i] for i in inter]
    heads = [heads[i] for i in perm]
    ordered_groups = [ordered_groups[i] for i in perm]
    H = len(heads)
    # predicted gain: serialized bytes/bandwidth, flat vs two-level
    flat_order = (
        current.as_ring_plan().order if isinstance(current, HierPlan)
        else (current.order if isinstance(current, RingPlan)
              else tuple(range(k)))
    )
    flat_min = min_edge_bw(m, flat_order)
    inter_min = min_edge_bw(
        m, [heads[i] for i in range(H)]
    ) if H > 1 else None
    intra_vals = [
        float(sym[i, j])
        for g in ordered_groups
        for i in g for j in g
        if i != j and np.isfinite(sym[i, j]) and sym[i, j] > 0
    ]
    intra_min = min(intra_vals) if intra_vals else None
    gain = 1.0
    if flat_min and inter_min and intra_min:
        flat_cost = 2.0 * (k - 1) / k / flat_min
        hier_cost = (
            2.0 * (H - 1) / H / inter_min + 2.0 / intra_min
        )
        if hier_cost > 0:
            gain = flat_cost / hier_cost
    cf = float(compute_frac)
    if cf > 0.0 and np.isfinite(cf):
        gain = min(gain, 1.0 / max(min(cf, 1.0), 1e-6))
    plan = HierPlan(
        groups=tuple(tuple(g) for g in ordered_groups),
        heads=tuple(heads),
        demoted=dem,
        gain=round(float(gain), 6),
    )
    if current is not None and hasattr(current, "to_bytes") \
            and current.to_bytes() == plan.to_bytes():
        return None
    return plan
