"""Minimum spanning tree over a peer latency matrix.

Capability parity: the reference's MST topology optimization
(srcs/cpp/include/kungfu/mst.hpp:9-59, exposed as the MinimumSpanningTree
TF op, ops/cpu/topology.cpp:84-196). The control plane probes per-peer
RTTs, allgathers them into a dense matrix, and the MST over that matrix
becomes the reduce/broadcast forest for HOST-plane (DCN) collectives.

Native Prim's kernel in native/mst.cpp (ctypes), numpy fallback here.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Sequence

import numpy as np

_kf_mst = None
_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "base", "libkfnative.so")
try:
    _lib = ctypes.CDLL(os.path.abspath(_LIB_PATH))
    _fn = getattr(_lib, "kf_mst", None)
    if _fn is not None:
        _fn.restype = ctypes.c_int
        _fn.argtypes = [ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
        _kf_mst = _fn
except OSError:
    pass


def _mst_numpy(w: np.ndarray) -> np.ndarray:
    """Prim's, O(n^2); father[0] == 0 (root)."""
    n = w.shape[0]
    father = np.zeros(n, np.int32)
    done = np.zeros(n, bool)
    done[0] = True
    best_cost = w[0].copy()
    best_from = np.zeros(n, np.int64)
    best_cost[0] = np.inf
    for _ in range(n - 1):
        masked = np.where(done, np.inf, best_cost)
        pick = int(np.argmin(masked))
        if not np.isfinite(masked[pick]):
            raise ValueError("disconnected latency graph")
        done[pick] = True
        father[pick] = best_from[pick]
        better = (~done) & (w[pick] < best_cost)
        best_cost[better] = w[pick][better]
        best_from[better] = pick
    return father


def minimum_spanning_tree(weights: Sequence[Sequence[float]]) -> List[int]:
    """Father array of the MST of a dense symmetric cost matrix."""
    w = np.ascontiguousarray(weights, np.float64)
    if w.ndim != 2 or w.shape[0] != w.shape[1]:
        raise ValueError(f"weights must be square, got {w.shape}")
    n = w.shape[0]
    if n == 0:
        return []
    if n == 1:
        return [0]
    if _kf_mst is not None:
        father = np.zeros(n, np.int32)
        rc = _kf_mst(
            n,
            w.ctypes.data_as(ctypes.c_void_p),
            father.ctypes.data_as(ctypes.c_void_p),
        )
        if rc == 0:
            return father.tolist()
        if rc == 2:
            raise ValueError("disconnected latency graph")
    return _mst_numpy(w).tolist()


def uses_native() -> bool:
    return _kf_mst is not None
