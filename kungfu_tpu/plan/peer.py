"""Peer identity and ordered peer lists.

Capability parity: srcs/go/plan/id.go (PeerID{IPv4,Port}) and
srcs/go/plan/peerlist.go:11-178 (rank/local-rank/host-count/diff/select/
partition-by-host). Hosts are strings (TPU-VM hostnames or IPs) rather than
packed uint32 IPv4 — DNS names are the norm on TPU pods.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True, order=True)
class PeerID:
    host: str
    port: int

    def colocated_with(self, other: "PeerID") -> bool:
        return self.host == other.host

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"

    @classmethod
    def parse(cls, s: str) -> "PeerID":
        host, _, port = s.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"invalid peer spec: {s!r}")
        return cls(host, int(port))


class PeerList:
    """Immutable ordered list of PeerIDs; rank == index."""

    def __init__(self, peers: Iterable[PeerID] = ()):
        self._peers: Tuple[PeerID, ...] = tuple(peers)

    def __len__(self) -> int:
        return len(self._peers)

    def __iter__(self) -> Iterator[PeerID]:
        return iter(self._peers)

    def __getitem__(self, i: int) -> PeerID:
        return self._peers[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, PeerList) and self._peers == other._peers

    def __hash__(self) -> int:
        return hash(self._peers)

    def __repr__(self) -> str:
        return f"[{len(self)}]{{{','.join(map(str, self))}}}"

    def rank(self, q: PeerID) -> Optional[int]:
        for i, p in enumerate(self._peers):
            if p == q:
                return i
        return None

    def local_rank(self, q: PeerID) -> Optional[int]:
        i = 0
        for p in self._peers:
            if p == q:
                return i
            if p.colocated_with(q):
                i += 1
        return None

    def local_size(self, q: PeerID) -> int:
        return sum(1 for p in self._peers if p.colocated_with(q))

    def host_count(self) -> int:
        return len({p.host for p in self._peers})

    def hosts(self) -> List[str]:
        """Distinct hosts in first-appearance order."""
        seen: Dict[str, None] = {}
        for p in self._peers:
            seen.setdefault(p.host, None)
        return list(seen)

    def select(self, ranks: Sequence[int]) -> "PeerList":
        return PeerList(self._peers[r] for r in ranks)

    def others(self, self_id: PeerID) -> "PeerList":
        return PeerList(p for p in self._peers if p != self_id)

    def on(self, host: str) -> "PeerList":
        return PeerList(p for p in self._peers if p.host == host)

    def contains(self, q: PeerID) -> bool:
        return q in self._peers

    def intersection(self, other: "PeerList") -> "PeerList":
        s = set(other._peers)
        return PeerList(p for p in self._peers if p in s)

    def disjoint(self, other: "PeerList") -> bool:
        return len(self.intersection(other)) == 0

    def diff(self, other: "PeerList") -> Tuple["PeerList", "PeerList"]:
        """Returns (self - other, other - self), order-preserving."""
        a = set(other._peers)
        b = set(self._peers)
        return (
            PeerList(p for p in self._peers if p not in a),
            PeerList(p for p in other._peers if p not in b),
        )

    def partition_by_host(self) -> Tuple[List[int], List[int]]:
        """Group ranks by host; the first rank seen on a host is its master.

        Returns (masters, master_of): masters = ranks of host masters in
        order, master_of[i] = master rank of rank i. master_of is a valid
        forest array (masters are roots).
        """
        masters: List[int] = []
        host_master: Dict[str, int] = {}
        master_of = [0] * len(self._peers)
        for rank, p in enumerate(self._peers):
            if p.host not in host_master:
                host_master[p.host] = rank
                masters.append(rank)
            master_of[rank] = host_master[p.host]
        return masters, master_of

    def to_bytes(self) -> bytes:
        return ";".join(map(str, self._peers)).encode()

    def digest(self) -> bytes:
        return hashlib.blake2b(self.to_bytes(), digest_size=16).digest()

    def to_json(self) -> List[str]:
        return [str(p) for p in self._peers]

    @classmethod
    def from_json(cls, specs: Sequence[str]) -> "PeerList":
        return cls(PeerID.parse(s) for s in specs)

    @classmethod
    def parse(cls, s: str) -> "PeerList":
        s = s.strip()
        if not s:
            return cls()
        return cls(PeerID.parse(part) for part in s.split(","))
