"""Cluster = (runners, workers) membership model with elastic resize.

Capability parity: srcs/go/plan/cluster.go — Validate (unique ports, one
runner per host, every worker's host has a runner), Resize (shrink by
truncation, grow onto the least-loaded host), canonical bytes for
consensus. JSON codec matches the config-server REST contract
(srcs/go/kungfu/elastic/configserver/configserver.go).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Optional

from kungfu_tpu.plan.hostspec import DEFAULT_PORT_RANGE
from kungfu_tpu.plan.peer import PeerID, PeerList


class ClusterError(ValueError):
    pass


@dataclasses.dataclass
class Cluster:
    runners: PeerList
    workers: PeerList

    def validate(self) -> None:
        seen_ports = set()
        runner_hosts = set()
        for r in self.runners:
            if r in seen_ports:
                raise ClusterError(f"duplicated peer: {r}")
            seen_ports.add(r)
            if r.host in runner_hosts:
                raise ClusterError(f"duplicated runner on host: {r.host}")
            runner_hosts.add(r.host)
        for w in self.workers:
            if w in seen_ports:
                raise ClusterError(f"duplicated peer: {w}")
            seen_ports.add(w)
            if w.host not in runner_hosts:
                raise ClusterError(f"worker {w} has no runner on its host")

    def clone(self) -> "Cluster":
        return Cluster(PeerList(self.runners), PeerList(self.workers))

    def _grow_one(self) -> None:
        if len(self.runners) == 0:
            raise ClusterError("no runner in cluster")
        used: Dict[str, int] = {r.host: 0 for r in self.runners}
        for w in self.workers:
            used[w.host] = used.get(w.host, 0) + 1
        host = min((r.host for r in self.runners), key=lambda h: used[h])
        port = 0
        for w in self.workers:
            if w.host == host and port <= w.port:
                port = w.port + 1
        if port == 0:
            port = DEFAULT_PORT_RANGE[0]
        self.workers = PeerList(list(self.workers) + [PeerID(host, port)])

    def resize(self, new_size: int) -> "Cluster":
        d = self.clone()
        if len(d.workers) > new_size:
            d.workers = PeerList(list(d.workers)[:new_size])
        while len(d.workers) < new_size:
            d._grow_one()
        return d

    def to_bytes(self) -> bytes:
        return (self.runners.to_bytes() + b"|" + self.workers.to_bytes())

    def digest(self) -> bytes:
        return hashlib.blake2b(self.to_bytes(), digest_size=16).digest()

    def to_json(self) -> dict:
        return {
            "Runners": self.runners.to_json(),
            "Workers": self.workers.to_json(),
        }

    def dumps(self) -> str:
        return json.dumps(self.to_json())

    @classmethod
    def from_json(cls, obj: dict) -> "Cluster":
        return cls(
            runners=PeerList.from_json(obj.get("Runners", [])),
            workers=PeerList.from_json(obj.get("Workers", [])),
        )

    @classmethod
    def loads(cls, s: str) -> "Cluster":
        return cls.from_json(json.loads(s))

    def debug_string(self) -> str:
        return f"[{len(self.workers)}@{len(self.runners)}]{{{self.workers}}}@{{{self.runners}}}"
