"""Host specifications and hostfile parsing.

Capability parity: srcs/go/plan/hostspec.go:29-55 (``ip:slots[:pub]``) and
srcs/go/plan/hostfile.go. A "slot" on TPU is one worker process (one chip
or one process per host, depending on topology).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, List, Tuple

from kungfu_tpu.plan.peer import PeerID, PeerList

DEFAULT_PORT_RANGE = (38000, 38999)


@dataclasses.dataclass(frozen=True)
class HostSpec:
    host: str
    slots: int = 1
    public_addr: str = ""

    def __post_init__(self):
        if not self.public_addr:
            object.__setattr__(self, "public_addr", self.host)

    @classmethod
    def parse(cls, s: str) -> "HostSpec":
        parts = s.strip().split(":")
        if not parts or not parts[0]:
            raise ValueError(f"invalid host spec: {s!r}")
        host = parts[0]
        slots = 1
        public = host
        if len(parts) >= 2 and parts[1]:
            if not parts[1].isdigit():
                raise ValueError(f"invalid slot count in host spec: {s!r}")
            slots = int(parts[1])
        if len(parts) >= 3 and parts[2]:
            public = parts[2]
        if len(parts) > 3:
            raise ValueError(f"invalid host spec: {s!r}")
        return cls(host, slots, public)

    def __str__(self) -> str:
        return f"{self.host}:{self.slots}:{self.public_addr or self.host}"


class HostList:
    def __init__(self, specs: Iterable[HostSpec] = ()):
        self._specs: Tuple[HostSpec, ...] = tuple(specs)

    def __len__(self) -> int:
        return len(self._specs)

    def __iter__(self) -> Iterator[HostSpec]:
        return iter(self._specs)

    def __getitem__(self, i: int) -> HostSpec:
        return self._specs[i]

    @property
    def total_slots(self) -> int:
        return sum(h.slots for h in self._specs)

    @classmethod
    def parse(cls, s: str) -> "HostList":
        s = s.strip()
        if not s:
            return cls()
        return cls(HostSpec.parse(part) for part in s.split(","))

    def gen_peer_list(self, np: int, port_range: Tuple[int, int] = DEFAULT_PORT_RANGE) -> PeerList:
        """First-fit np workers over hosts in order, ports from port_range.

        Mirrors HostList.GenPeerList (hostspec.go): fill each host up to its
        slot count before moving on.
        """
        if np > self.total_slots:
            raise ValueError(f"requested {np} workers but only {self.total_slots} slots")
        cap = port_range[1] - port_range[0] + 1
        for h in self._specs:
            if h.slots > cap:
                raise ValueError(
                    f"host {h.host} has {h.slots} slots but port range holds {cap}"
                )
        peers: List[PeerID] = []
        for h in self._specs:
            for slot in range(h.slots):
                if len(peers) >= np:
                    return PeerList(peers)
                peers.append(PeerID(h.host, port_range[0] + slot))
        return PeerList(peers)

    def gen_runner_list(self, port: int) -> PeerList:
        """One runner (supervisor) per host on a fixed port."""
        return PeerList(PeerID(h.host, port) for h in self._specs)


def parse_hostfile(text: str) -> HostList:
    """Parse hostfile lines ``host slots=N [public=addr]``; '#' comments."""
    specs = []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        fields = line.split()
        host = fields[0]
        slots = 1
        public = ""
        for f in fields[1:]:
            if f.startswith("slots="):
                slots = int(f[len("slots="):])
            elif f.startswith("public="):
                public = f[len("public="):]
            else:
                raise ValueError(f"invalid hostfile field: {f!r}")
        specs.append(HostSpec(host, slots, public or host))
    return HostList(specs)
