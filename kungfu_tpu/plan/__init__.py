from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.plan.hostspec import HostSpec, HostList, parse_hostfile

__all__ = [
    "Cluster",
    "Graph",
    "HostList",
    "HostSpec",
    "PeerID",
    "PeerList",
    "parse_hostfile",
]
