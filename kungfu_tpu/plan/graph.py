"""Directed-graph algebra for collective communication plans.

Capability parity: srcs/go/plan/graph/graph.go:29-154 — a DAG over ranks
0..n-1 with per-node prev/next edge lists and a self-loop marker (a
self-loop on the reduce graph means "this rank accumulates"), plus
forest-array construction, reversal, and a canonical digest used for
cluster-wide consensus on topology.
"""

from __future__ import annotations

import hashlib
import struct
from typing import List, Optional, Sequence, Tuple


class Graph:
    """Graph over ranks 0..n-1. Edges are directed i -> j."""

    def __init__(self, n: int):
        self.n = n
        self._prevs: List[List[int]] = [[] for _ in range(n)]
        self._nexts: List[List[int]] = [[] for _ in range(n)]
        self._self_loop = [False] * n

    def add_edge(self, i: int, j: int) -> None:
        if i == j:
            self._self_loop[i] = True
            return
        self._nexts[i].append(j)
        self._prevs[j].append(i)

    def prevs(self, i: int) -> List[int]:
        return self._prevs[i]

    def nexts(self, i: int) -> List[int]:
        return self._nexts[i]

    def is_self_loop(self, i: int) -> bool:
        return self._self_loop[i]

    def is_isolated(self, i: int) -> bool:
        return not self._prevs[i] and not self._nexts[i]

    def reverse(self) -> "Graph":
        r = Graph(self.n)
        for i in range(self.n):
            r._self_loop[i] = self._self_loop[i]
            for j in self._nexts[i]:
                r._nexts[j].append(i)
            for j in self._prevs[i]:
                r._prevs[j].append(i)
        return r

    @classmethod
    def from_forest_array(cls, fathers: Sequence[int]) -> Tuple[Optional["Graph"], int, bool]:
        """Build a broadcast forest from a father-array.

        fathers[i] is the father of rank i; fathers[i] == i marks a root.
        Returns (graph, num_roots, ok); ok is False on out-of-range entries
        or cycles.
        """
        n = len(fathers)
        g = cls(n)
        roots = 0
        for i, f in enumerate(fathers):
            if f < 0 or f >= n:
                return None, 0, False
            if f == i:
                roots += 1
            else:
                g.add_edge(f, i)
        # cycle check: walk each node to its root, bounded by n hops
        for i in range(n):
            cur, hops = i, 0
            while fathers[cur] != cur:
                cur = fathers[cur]
                hops += 1
                if hops > n:
                    return None, 0, False
        return g, roots, True

    def digest(self) -> bytes:
        """Canonical byte digest, equal iff topologies are equal.

        Mirrors DigestBytes (graph.go:129-146): per node, (self_loop,
        out-degree, sorted nexts), little-endian i32, then hashed.
        """
        h = hashlib.blake2b(digest_size=16)
        h.update(struct.pack("<i", self.n))
        for i in range(self.n):
            nexts = sorted(self._nexts[i])
            h.update(struct.pack("<ii", int(self._self_loop[i]), len(nexts)))
            h.update(struct.pack(f"<{len(nexts)}i", *nexts) if nexts else b"")
        return h.digest()

    def debug_string(self) -> str:
        loops = "".join(f"({i})" for i in range(self.n) if self._self_loop[i])
        edges = "".join(
            f"({i}->{j})" for i in range(self.n) for j in self._nexts[i]
        )
        return f"[{self.n}]{{{loops}{edges}}}"

    def __repr__(self) -> str:
        return f"Graph{self.debug_string()}"
