"""Usage: python3 -m kungfu_tpu.info [--no-devices] [--telemetry [URL]]
       python3 -m kungfu_tpu.info top [--watch] [--json] [--interval S] [URL]
       python3 -m kungfu_tpu.info links [--watch] [--json] [--interval S] [URL]
       python3 -m kungfu_tpu.info steps [--watch] [--json] [--interval S] [-n N] [URL]
       python3 -m kungfu_tpu.info decisions [--watch] [--json] [--interval S] [-n N] [URL]
       python3 -m kungfu_tpu.info resources [--watch] [--json] [--interval S] [URL]
       python3 -m kungfu_tpu.info memory [--watch] [--json] [--interval S] [URL]
       python3 -m kungfu_tpu.info postmortem [DIR|URL]

Prints framework, backend and cluster-env diagnostics (parity:
python -m kungfu.info; the CUDA/NCCL/TF report becomes JAX/TPU/KF_* —
what an operator actually needs when a TPU-VM worker misbehaves).

--telemetry shows the telemetry configuration (KF_TELEMETRY features,
endpoint scheme) and, given a worker URL (http://host:port — the
worker's peer port + 10000), fetches and prints its live /metrics
page.

`top` is the live operator view of the cluster plane (ISSUE 2): it
reads the runner's /cluster/health endpoint (URL argument, or
KF_CLUSTER_HEALTH_URL — exported to every worker by kfrun -w
-debug-port N) and renders one row per peer: step rate, step-time
p50/p99, bytes tx/rx, scrape age, straggler flag. --watch refreshes in
place until interrupted.

`links` renders the cluster's k×k link matrix (ISSUE 6): per directed
edge the passively-measured EWMA bandwidth (MiB/s) from the runner's
/cluster/links endpoint, slow edges (< half the median) highlighted
with `!`. Point it at the runner debug endpoint (or it derives the URL
from KF_CLUSTER_HEALTH_URL). This is the "which link is slow?" view —
see the runbook in docs/telemetry.md.

`steps` renders the step plane (ISSUE 13): recent merged training
steps from the runner's /cluster/steps endpoint as aligned per-peer
lanes, the critical (peer, bucket, edge) chain highlighted with `*`,
plus each step's overlap and queue-delay fractions. This is the "why
is this step slow?" view — see the runbook in docs/telemetry.md.

`decisions` renders the decision ledger (ISSUE 15): the cluster's
merged causal adaptation timeline from the runner's /cluster/decisions
endpoint — every strategy/wire vote, measured re-plan, engine-mode flip
and elastic resize with its trigger, predicted gain and MEASURED
outcome (realized gain, delivered/neutral/regressed verdict, regression
watchdog flag). This is the "the cluster adapted — did it help?" view —
see the runbook in docs/telemetry.md.

`resources` renders the resource plane (ISSUE 16): every worker's
per-thread CPU attribution from the runner's /cluster/resources
endpoint — per peer the window CPU fraction, effective cores, the
per-bucket busy split (train/walk/codec/sched/telemetry/other) and the
compute-saturation flag. This is the "is this peer compute-bound or
network-bound?" view — see the runbook in docs/telemetry.md.

`memory` renders the memory plane (ISSUE 17): every worker's RSS
decomposition from the runner's /cluster/memory endpoint — per peer the
RSS against its effective memory limit, the per-bucket byte split
(arena/pool/zero_state/sched_inflight/telemetry/untracked), the RSS
trend and headroom forecast, plus pressure/thrashing/leak flags. This
is the "which worker is about to OOM, and what's eating it?" view —
see the runbook in docs/telemetry.md.

`--json` (top/links/steps/decisions/resources/memory) emits the raw cluster
endpoint payload instead of the rendered table — one flag for
scripting/CI, applied in the shared fetch loop.

`postmortem` reconstructs the death timeline of crashed workers
(ISSUE 3): point it at a telemetry run dir (KF_TELEMETRY_DIR, default
/tmp/kungfu-telemetry/<run-id>) to read the durable postmortems.jsonl
and per-peer flight journals, or at a live runner's debug endpoint
(http://host:port) to fetch /cluster/postmortem. With no argument it
uses $KF_TELEMETRY_DIR."""

import json
import os
import sys
import time
import urllib.request

from kungfu_tpu import knobs


def _show_versions() -> None:
    import kungfu_tpu

    print(f"kungfu_tpu: {getattr(kungfu_tpu, '__version__', 'dev')} "
          f"({os.path.dirname(kungfu_tpu.__file__)})")
    try:
        import jax

        print(f"JAX: {jax.__version__}")
    except ImportError:
        print("JAX is NOT installed")
    for mod in ("flax", "optax", "orbax.checkpoint", "torch"):
        try:
            m = __import__(mod)
            for part in mod.split(".")[1:]:
                m = getattr(m, part)
            print(f"{mod}: {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod} is NOT installed")


def _show_devices() -> None:
    try:
        import jax

        devs = jax.devices()
        kinds = {}
        for d in devs:
            kinds.setdefault((d.platform, d.device_kind), []).append(d.id)
        for (platform, kind), ids in kinds.items():
            print(f"devices: {len(ids)} x {kind} ({platform})")
    except Exception as e:  # noqa: BLE001 - a broken backend is a finding
        print(f"device init FAILED: {e}")


def _show_cluster_env() -> None:
    kf = {k: v for k, v in os.environ.items() if k.startswith("KF_")}
    if not kf:
        print("cluster env: none (not under kfrun)")
        return
    print("cluster env:")
    for k in sorted(kf):
        print(f"  {k}={kf[k]}")


def _show_telemetry(argv) -> None:
    from kungfu_tpu import telemetry

    feats = sorted(telemetry.features())
    print(f"telemetry: {','.join(feats) if feats else 'off'} "
          f"(KF_TELEMETRY={knobs.raw('KF_TELEMETRY')!r})")
    print("telemetry endpoints: http://<worker>:<peer_port+10000>"
          "/metrics | /trace | /audit")
    # an URL argument right after --telemetry: scrape a live worker
    idx = argv.index("--telemetry")
    url = argv[idx + 1] if idx + 1 < len(argv) else ""
    if url.startswith("http"):
        import urllib.request

        try:
            with urllib.request.urlopen(
                url.rstrip("/") + "/metrics", timeout=5
            ) as r:
                print(r.read().decode())
        except OSError as e:
            print(f"telemetry fetch FAILED: {e}")
        return
    # no URL: dump this process's own registry/trace/audit state
    d = telemetry.dump()
    n_spans = len(d["trace"]["traceEvents"])
    print(f"local trace buffer: {n_spans} events; "
          f"audit records: {len(d['audit'])}")
    if d["metrics"].strip():
        print(d["metrics"])


def _interval_flag(argv, cmd: str):
    """Parse --interval seconds (default 2.0); (None, rc) on bad input."""
    if "--interval" not in argv:
        return 2.0, None
    idx = argv.index("--interval")
    try:
        return float(argv[idx + 1]), None
    except (IndexError, ValueError):
        print(f"info {cmd}: --interval wants seconds, e.g. --interval 2",
              file=sys.stderr)
        return None, 2


def _cluster_url(argv, endpoint: str) -> str:
    """Resolve a /cluster/<endpoint> URL: explicit argument (full path
    or debug-endpoint base), else derived from KF_CLUSTER_HEALTH_URL —
    shared by the top/links/steps commands so the suffix munging can't
    drift between them."""
    urls = [a for a in argv if a.startswith("http")]
    url = urls[0] if urls else knobs.raw("KF_CLUSTER_HEALTH_URL")
    if not url:
        return ""
    url = url.rstrip("/")
    if url.endswith("/cluster/health"):
        url = url[: -len("/cluster/health")]
    if not url.endswith(endpoint):
        url += endpoint
    return url


def _count_flag(argv, cmd: str, default: int):
    """Parse `-n COUNT` (shared by steps/decisions); (None, rc) on bad
    input — the _interval_flag shape."""
    if "-n" not in argv:
        return default, None
    idx = argv.index("-n")
    try:
        return max(1, int(argv[idx + 1])), None
    except (IndexError, ValueError):
        print(f"info {cmd}: -n wants a count, e.g. -n 8", file=sys.stderr)
        return None, 2


def _json_flag(argv, render):
    """The --json satellite (ISSUE 15): one flag in one place — every
    cluster subcommand swaps its renderer for a raw-payload dump when
    --json is passed, so scripts/CI read the endpoint document through
    the same URL resolution and fetch loop the human view uses."""
    if "--json" not in argv:
        return render
    return lambda doc: json.dumps(doc, indent=2)


def _fetch_render_loop(cmd: str, url: str, render, watch: bool,
                       interval: float) -> int:
    """The shared fetch-JSON → render → print/refresh loop behind the
    one-shot and --watch modes of top/links/steps/decisions. Watch mode
    rides out transient fetch blips (runner mid-restart) instead of
    killing the live view; the whole iteration is interruptible."""
    while True:
        try:
            try:
                with urllib.request.urlopen(url, timeout=5) as r:
                    frame = render(json.loads(r.read().decode()))
            except (OSError, ValueError) as e:
                if not watch:
                    print(f"info {cmd}: fetch {url} failed: {e}",
                          file=sys.stderr)
                    return 1
                frame = f"info {cmd}: fetch failed, retrying: {e}"
            if watch:
                # home + clear-to-end keeps the view refreshing in place
                print("\x1b[H\x1b[2J" + frame, flush=True)
                time.sleep(interval)
            else:
                print(frame)
                return 0
        except KeyboardInterrupt:
            return 0


def _fmt_num(v, fmt="{:.1f}", dash="-") -> str:
    return fmt.format(v) if isinstance(v, (int, float)) else dash


def _fmt_bytes(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return "-"


def render_top(health: dict) -> str:
    """One refresh frame of `info top`: a fixed-width table over
    /cluster/health, stragglers flagged in the last column. The CRIT%
    and CRIT-EDGE columns come from the step plane (ISSUE 13): the share
    of recent merged steps this peer was elected critical in, and the
    blocking edge those elections named. The CPU% and TRAIN% columns
    come from the resource plane (ISSUE 16): the window CPU fraction of
    the peer's effective cores and the training loop's share of the
    busy window; a flagged straggler carries its measured cause
    (STRAGGLER(network) vs STRAGGLER(compute) vs STRAGGLER(memory)).
    The MEM% and HEADROOM columns come from the memory plane (ISSUE
    17): RSS as a share of the peer's effective memory limit, and the
    forecast headroom fraction."""
    steps = health.get("steps") or {}
    crit_frac = steps.get("crit_frac") or {}
    crit_edge = steps.get("crit_edge") or {}
    res_peers = (health.get("resources") or {}).get("peers") or {}
    mem_block = health.get("memory") or {}
    mem_peers = mem_block.get("peers") or {}
    cols = ("PEER", "STEP/S", "P50(ms)", "P99(ms)", "TX", "RX",
            "RTT(ms)", "AGE(s)", "CPU%", "TRAIN%", "MEM%", "HEADROOM",
            "CRIT%", "CRIT-EDGE", "FLAGS")
    rows = [cols]
    peers = health.get("peers", {})
    for label in sorted(peers):
        p = peers[label]
        flags = []
        if p.get("straggler"):
            cause = p.get("straggler_cause")
            flags.append(
                f"STRAGGLER({cause})"
                if cause and cause != "unknown" else "STRAGGLER"
            )
        if p.get("rtt_outlier"):
            flags.append("RTT")
        if p.get("error"):
            flags.append("UNREACHABLE")
        cf = crit_frac.get(label)
        r = res_peers.get(label) or {}
        cpu = r.get("cpu_frac")
        train = r.get("train_frac")
        m = mem_peers.get(label) or {}
        used = m.get("used_frac")
        headroom = m.get("headroom_frac")
        rows.append((
            label,
            _fmt_num(p.get("step_rate"), "{:.2f}"),
            _fmt_num(p.get("step_time_p50_ms")),
            _fmt_num(p.get("step_time_p99_ms")),
            _fmt_bytes(p.get("bytes_tx")),
            _fmt_bytes(p.get("bytes_rx")),
            _fmt_num(p.get("rtt_ms"), "{:.2f}"),
            _fmt_num(p.get("last_scrape_age_s")),
            f"{cpu:.0%}" if isinstance(cpu, (int, float)) else "-",
            f"{train:.0%}" if isinstance(train, (int, float)) else "-",
            f"{used:.0%}" if isinstance(used, (int, float)) else "-",
            f"{headroom:.0%}" if isinstance(headroom, (int, float)) else "-",
            f"{cf:.0%}" if isinstance(cf, (int, float)) else "-",
            f"→{crit_edge[label]}" if label in crit_edge else "-",
            ",".join(flags) or "ok",
        ))
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    skew = health.get("step_skew")
    stragglers = health.get("stragglers", [])
    summary = (
        f"{len(peers)} peers"
        + (f", step skew {skew:.2f}x" if isinstance(skew, (int, float)) else "")
        + (f", STRAGGLERS: {', '.join(stragglers)}" if stragglers else "")
    )
    crit_peer = steps.get("critical_peer")
    if crit_peer:
        edge = steps.get("critical_edge")
        ov = steps.get("overlap_frac")
        summary += (
            f"; last step critical: {crit_peer}"
            + (f" →{edge}" if edge else "")
            + (
                f", overlap {ov:.0%}"
                if isinstance(ov, (int, float)) else ""
            )
        )
    sat = (health.get("resources") or {}).get("saturated") or []
    if sat:
        summary += f"; compute-saturated: {', '.join(sat)}"
    pressured = mem_block.get("pressure") or []
    if pressured:
        summary += f"; memory-pressured: {', '.join(pressured)}"
    leaks = mem_block.get("leak_suspects") or {}
    if leaks:
        summary += "; leak suspects: " + ", ".join(
            f"{peer}({','.join(buckets)})"
            for peer, buckets in sorted(leaks.items())
        )
    plane_line = _render_plane_line(health.get("plane"))
    head = [summary] + ([plane_line] if plane_line else [])
    return "\n".join(head + lines)


def _render_plane_line(plane) -> str:
    """Telemetry-plane health line (ISSUE 18): surfaces whether the
    aggregator itself is keeping up — scrape mode (flat vs the scaled
    hier/sampled shapes), last sweep wall time against its effective
    interval (> interval means the plane is in backoff and the columns
    above are staler than configured), and peers whose scrapes are
    stale."""
    if not isinstance(plane, dict) or not plane:
        return ""
    parts = [f"plane: {plane.get('mode', '?')}"]
    sweep = plane.get("sweep_seconds")
    interval = plane.get("effective_interval_s") or plane.get("interval_s")
    if isinstance(sweep, (int, float)):
        part = f"sweep {sweep:.2f}s"
        if isinstance(interval, (int, float)) and interval > 0:
            part += f"/{interval:g}s"
            if sweep > interval:
                part += " OVERLOADED"
        parts.append(part)
    scraped = plane.get("scraped_peers")
    stale = plane.get("stale_peers")
    if isinstance(scraped, int):
        parts.append(f"{scraped} scraped")
    # the envelope ships a count; older health docs may carry labels
    if isinstance(stale, bool):
        pass
    elif isinstance(stale, int) and stale > 0:
        parts.append(f"{stale} stale")
    elif isinstance(stale, (list, tuple)) and stale:
        parts.append(f"stale: {', '.join(stale)}")
    age = plane.get("oldest_link_row_age_s")
    if isinstance(age, (int, float)):
        parts.append(f"oldest link row {age:.0f}s")
    return ", ".join(parts)


def _cmd_top(argv) -> int:
    watch = "--watch" in argv
    interval, rc = _interval_flag(argv, "top")
    if rc is not None:
        return rc
    url = _cluster_url(argv, "/cluster/health")
    if not url:
        print(
            "info top: no /cluster/health URL — pass one, or run under "
            "kfrun -w -debug-port N (which exports KF_CLUSTER_HEALTH_URL)",
            file=sys.stderr,
        )
        return 2
    return _fetch_render_loop(
        "top", url, _json_flag(argv, render_top), watch, interval
    )


def render_links(doc: dict) -> str:
    """One frame of `info links`: the k×k bandwidth matrix over
    /cluster/links. Rows are source peers (numbered, legend below),
    columns destinations; cells are EWMA bandwidth in MiB/s. Edges
    slower than half the median carry a `!` marker — the "which link is
    slow?" answer at a glance."""
    peers = doc.get("peers", [])
    edges = doc.get("edges", {})
    if not peers:
        return "no peers in the link matrix yet (no scrape, or telemetry off)"
    idx = {p: i for i, p in enumerate(peers)}
    bws = [
        info.get("bw")
        for row in edges.values()
        for info in row.values()
        if isinstance(info.get("bw"), (int, float)) and info.get("bw") > 0
    ]
    median = sorted(bws)[len(bws) // 2] if bws else None
    slow_cut = median / 2 if median else None

    def cell(src: str, dst: str) -> str:
        if src == dst:
            return "."
        bw = edges.get(src, {}).get(dst, {}).get("bw")
        if not isinstance(bw, (int, float)) or bw <= 0:
            return "-"
        mark = "!" if slow_cut is not None and bw < slow_cut else ""
        return f"{bw / (1 << 20):.1f}{mark}"

    cols = ["SRC\\DST"] + [f"[{idx[p]}]" for p in peers]
    rows = [cols]
    for src in peers:
        rows.append([f"[{idx[src]}]"] + [cell(src, dst) for dst in peers])
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    min_bw = doc.get("min_bw")
    slowest = doc.get("slowest_edge")
    summary = f"{len(peers)} peers, bandwidth MiB/s (EWMA, passive)"
    if isinstance(min_bw, (int, float)) and slowest:
        summary += (
            f"; slowest edge [{idx.get(slowest[0], '?')}]→"
            f"[{idx.get(slowest[1], '?')}] at {min_bw / (1 << 20):.1f} MiB/s"
        )
    legend = [f"  [{i}] {p}" for p, i in sorted(idx.items(), key=lambda kv: kv[1])]
    notes = "cells: MiB/s, '-' no estimate yet, '!' under half the median"
    return "\n".join(
        [summary] + lines + _render_ring_lines(doc, peers, idx)
        + [notes, "peers:"] + legend
    )


def _same_cycle(a: list, b: list) -> bool:
    """Directed-cycle equality up to rotation: rings are
    rotation-invariant, and the CLI's derivation starts from the
    first LISTED peer while the engine pins rank 0 — the two can agree
    on the cycle yet disagree on where to start printing it."""
    if len(a) != len(b) or not a:
        return False
    if set(a) != set(b):
        return False
    i = b.index(a[0])
    return list(a) == list(b[i:]) + list(b[:i])


def _render_ring_lines(doc: dict, peers: list, idx: dict) -> list:
    """Ring view under the matrix (ISSUE 14): the ACTIVE ring order the
    workers export (starred when it differs from rank order — a measured
    re-plan landed) and the order the optimizer would derive from the
    rendered matrix — so an operator sees a PENDING re-plan before the
    vote lands. Derivation runs the same pure `plan.replan.ring_order`
    the engine votes on, fed by the same matrix this frame renders;
    ADVISORY only: the CLI indexes peers in listing order (it cannot
    know ranks), so agreement with the active ring is judged as a
    directed CYCLE (rotation-invariant), and a greedy construction from
    a different start can still legitimately differ on near-tie
    matrices."""
    lines = []

    def fmt(order_labels) -> str:
        return "→".join(f"[{idx[p]}]" for p in order_labels if p in idx)

    ring = doc.get("ring") or {}
    active = ring.get("order")
    if active:
        star = " ★ re-planned (differs from rank order)" if (
            not _same_cycle(list(active), list(peers))
        ) else " (rank order)"
        lines.append(f"active ring:    {fmt(active)}{star}")
    # active wire precision (ISSUE 20): cluster-agreed by the lockstep
    # precision votes, so one value is the norm — a split view means a
    # scrape straddled a flip (or a real codec divergence: investigate)
    wire = ring.get("wire") or {}
    if wire:
        modes = sorted(set(wire.values()))
        if len(modes) == 1:
            lines.append(f"wire precision: {modes[0]}")
        else:
            split = ", ".join(
                f"[{idx.get(p, '?')}]={m}" for p, m in sorted(
                    wire.items(), key=lambda kv: idx.get(kv[0], len(idx)))
            )
            lines.append(f"wire precision: SPLIT ({split}) ⚠")
    # two-level hierarchy (ISSUE 19): the workers' exported roles name
    # host groups, the head carrying each group's inter-host leg, and
    # demoted peers (▽ — zero-weight, served by broadcast)
    roles = ring.get("role") or {}
    hier = {
        p: r for p, r in roles.items()
        if isinstance(r, dict) and r.get("level") != "flat"
    }
    if hier:
        groups: dict = {}
        for p, r in hier.items():
            groups.setdefault(int(r.get("group") or 0), []).append((p, r))

        def member(p: str, r: dict) -> str:
            return f"[{idx.get(p, '?')}]" + (
                "▽" if r.get("role") == "demoted" else "")

        parts = []
        for g in sorted(groups):
            members = sorted(groups[g],
                             key=lambda kv: idx.get(kv[0], len(idx)))
            head = next(
                (p for p, r in members if r.get("role") == "head"), None)
            body = ",".join(member(p, r) for p, r in members)
            htag = f"|h[{idx[head]}]" if head in idx else ""
            parts.append("{" + body + htag + "}")
        tail = " (▽ demoted)" if any(
            r.get("role") == "demoted" for r in hier.values()) else ""
        lines.append("hierarchy:      " + "→".join(parts) + tail)
    bw = [
        [
            (doc.get("edges", {}).get(src, {}).get(dst, {}) or {}).get("bw")
            or 0.0
            for dst in peers
        ]
        for src in peers
    ]
    try:
        import numpy as _np

        from kungfu_tpu.plan import replan as _replan

        order = _replan.ring_order(_np.asarray(bw, float))
    except Exception as e:  # noqa: BLE001 - a render must survive a bad matrix
        lines.append(f"predicted ring: unavailable ({e})")
        return lines
    predicted = [peers[i] for i in order]
    if active and _same_cycle(list(predicted), list(active)):
        # display the agreeing cycle rotated to match the active line
        i = predicted.index(active[0])
        predicted = predicted[i:] + predicted[:i]
    mark = ""
    if active and list(predicted) != list(active):
        mark = " ← pending re-plan (differs from the active ring)"
    elif not active and not _same_cycle(list(predicted), list(peers)):
        mark = " ← differs from rank order"
    lines.append(f"predicted ring: {fmt(predicted)}{mark}")
    return lines


def _cmd_links(argv) -> int:
    watch = "--watch" in argv
    interval, rc = _interval_flag(argv, "links")
    if rc is not None:
        return rc
    url = _cluster_url(argv, "/cluster/links")
    if not url:
        print(
            "info links: no /cluster/links URL — pass one (or a runner "
            "debug endpoint), or run under kfrun -w -debug-port N "
            "(which exports KF_CLUSTER_HEALTH_URL)",
            file=sys.stderr,
        )
        return 2
    return _fetch_render_loop(
        "links", url, _json_flag(argv, render_links), watch, interval
    )


def render_steps(doc: dict, limit: int = 8) -> str:
    """One frame of `info steps`: the newest merged steps (newest last)
    as aligned per-peer lanes with the critical chain called out —
    rendering shared with the flight postmortem (steptrace.render_step)
    so the live view and the black box read identically."""
    from kungfu_tpu.telemetry import steptrace

    steps = doc.get("steps") or []
    if not steps:
        return (
            "no merged steps yet — the step plane needs the async "
            "scheduler (KF_CONFIG_ASYNC=on|auto) and at least one "
            "recorded round per worker (KF_TELEMETRY_SPAN_SAMPLE > 0)"
        )
    shown = steps[-limit:]
    lines: list = [
        f"{len(steps)} merged steps on record, showing {len(shown)} "
        "(lanes: · queued  ≈ wait  ■ compute  > send  g gather tail; "
        "* = critical peer)"
    ]
    for s in shown:
        lines.append("")
        lines.extend(steptrace.render_step(s))
        chain = s.get("chain") or []
        if len(chain) > 1:
            tail = ", ".join(
                f"{c['peer']}#{c['bucket']}"
                + (f"→{c['edge']}" if c.get("edge") else "")
                + f" {c['self_us'] / 1e3:.1f}ms"
                for c in chain[1:]
            )
            lines.append(f"   chain tail: {tail}")
    return "\n".join(lines)


def _cmd_steps(argv) -> int:
    watch = "--watch" in argv
    interval, rc = _interval_flag(argv, "steps")
    if rc is not None:
        return rc
    limit, rc = _count_flag(argv, "steps", 8)
    if rc is not None:
        return rc
    url = _cluster_url(argv, "/cluster/steps")
    if not url:
        print(
            "info steps: no /cluster/steps URL — pass one (or a runner "
            "debug endpoint), or run under kfrun -w -debug-port N "
            "(which exports KF_CLUSTER_HEALTH_URL)",
            file=sys.stderr,
        )
        return 2
    return _fetch_render_loop(
        "steps", url,
        _json_flag(argv, lambda doc: render_steps(doc, limit=limit)),
        watch, interval,
    )


def _cmd_decisions(argv) -> int:
    watch = "--watch" in argv
    interval, rc = _interval_flag(argv, "decisions")
    if rc is not None:
        return rc
    limit, rc = _count_flag(argv, "decisions", 16)
    if rc is not None:
        return rc
    url = _cluster_url(argv, "/cluster/decisions")
    if not url:
        print(
            "info decisions: no /cluster/decisions URL — pass one (or a "
            "runner debug endpoint), or run under kfrun -w -debug-port N "
            "(which exports KF_CLUSTER_HEALTH_URL)",
            file=sys.stderr,
        )
        return 2
    from kungfu_tpu.telemetry import decisions as _dec

    return _fetch_render_loop(
        "decisions", url,
        _json_flag(argv, lambda doc: _dec.render_decisions(doc, limit=limit)),
        watch, interval,
    )


def render_resources(doc: dict) -> str:
    """One frame of `info resources`: the merged per-peer CPU
    attribution table — rendering shared with the worker view
    (resource.render_resources) so the live view and tests read
    identically."""
    from kungfu_tpu.telemetry import resource as _tres

    if not (doc.get("peers") or {}):
        return (
            "no resource documents yet — workers publish /resources "
            "once telemetry is on (kfrun -w) and a scrape has landed; "
            "per-thread accounting needs Linux (/proc)"
        )
    return "\n".join(_tres.render_resources(doc))


def _cmd_resources(argv) -> int:
    watch = "--watch" in argv
    interval, rc = _interval_flag(argv, "resources")
    if rc is not None:
        return rc
    url = _cluster_url(argv, "/cluster/resources")
    if not url:
        print(
            "info resources: no /cluster/resources URL — pass one (or a "
            "runner debug endpoint), or run under kfrun -w -debug-port N "
            "(which exports KF_CLUSTER_HEALTH_URL)",
            file=sys.stderr,
        )
        return 2
    return _fetch_render_loop(
        "resources", url, _json_flag(argv, render_resources), watch, interval
    )


def render_memory(doc: dict) -> str:
    """One frame of `info memory`: the merged per-peer RSS
    decomposition table — rendering shared with the merge tests
    (memory.render_memory) so the live view and tests read
    identically."""
    from kungfu_tpu.telemetry import memory as _tmem

    if not (doc.get("peers") or {}):
        return (
            "no memory documents yet — workers publish /memory once "
            "telemetry is on (kfrun -w) and a scrape has landed; RSS "
            "accounting needs Linux (/proc)"
        )
    return "\n".join(_tmem.render_memory(doc))


def _cmd_memory(argv) -> int:
    watch = "--watch" in argv
    interval, rc = _interval_flag(argv, "memory")
    if rc is not None:
        return rc
    url = _cluster_url(argv, "/cluster/memory")
    if not url:
        print(
            "info memory: no /cluster/memory URL — pass one (or a "
            "runner debug endpoint), or run under kfrun -w -debug-port N "
            "(which exports KF_CLUSTER_HEALTH_URL)",
            file=sys.stderr,
        )
        return 2
    return _fetch_render_loop(
        "memory", url, _json_flag(argv, render_memory), watch, interval
    )


def _cmd_postmortem(argv) -> int:
    from kungfu_tpu.telemetry import flight

    target = next(
        (a for a in argv if not a.startswith("-")), ""
    ) or knobs.raw(flight.DIR_ENV)
    if not target:
        print(
            "info postmortem: no target — pass a telemetry run dir or a "
            "runner debug URL (or set KF_TELEMETRY_DIR)",
            file=sys.stderr,
        )
        return 2
    if target.startswith("http"):
        url = target.rstrip("/")
        if not url.endswith("/cluster/postmortem"):
            url += "/cluster/postmortem"
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                doc = json.loads(r.read().decode())
        except (OSError, ValueError) as e:
            print(f"info postmortem: fetch {url} failed: {e}", file=sys.stderr)
            return 1
        pms = [pm for recs in doc.get("peers", {}).values() for pm in recs]
    else:
        if not os.path.isdir(target):
            print(f"info postmortem: {target}: not a directory", file=sys.stderr)
            return 2
        # a single PEER dir (holds a journal itself) or a run dir
        single = flight.harvest_peer_dir(target)
        pms = [single] if single is not None else flight.harvest_run_dir(target)
    if not pms:
        print(f"no postmortems found in {target}")
        return 0
    pms.sort(key=lambda p: p.get("wall_time") or 0.0)
    print(f"{len(pms)} worker death(s) on record")
    for pm in pms:
        print()
        print(flight.render_postmortem(pm))
    return 0


def main(argv) -> None:
    if argv and argv[0] == "top":
        sys.exit(_cmd_top(argv[1:]))
    if argv and argv[0] == "links":
        sys.exit(_cmd_links(argv[1:]))
    if argv and argv[0] == "steps":
        sys.exit(_cmd_steps(argv[1:]))
    if argv and argv[0] == "decisions":
        sys.exit(_cmd_decisions(argv[1:]))
    if argv and argv[0] == "resources":
        sys.exit(_cmd_resources(argv[1:]))
    if argv and argv[0] == "memory":
        sys.exit(_cmd_memory(argv[1:]))
    if argv and argv[0] == "postmortem":
        sys.exit(_cmd_postmortem(argv[1:]))
    _show_versions()
    if "--no-devices" not in argv:
        _show_devices()
    _show_cluster_env()
    if "--telemetry" in argv:
        _show_telemetry(argv)
    allowed = (
        len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")  # Linux-only
        else os.cpu_count()
    )
    print(f"cpus: {allowed} allowed / {os.cpu_count()} online")


if __name__ == "__main__":
    main(sys.argv[1:])
