"""Usage: python3 -m kungfu_tpu.info [--no-devices] [--telemetry [URL]]

Prints framework, backend and cluster-env diagnostics (parity:
python -m kungfu.info; the CUDA/NCCL/TF report becomes JAX/TPU/KF_* —
what an operator actually needs when a TPU-VM worker misbehaves).

--telemetry shows the telemetry configuration (KF_TELEMETRY features,
endpoint scheme) and, given a worker URL (http://host:port — the
worker's peer port + 10000), fetches and prints its live /metrics
page."""

import os
import sys


def _show_versions() -> None:
    import kungfu_tpu

    print(f"kungfu_tpu: {getattr(kungfu_tpu, '__version__', 'dev')} "
          f"({os.path.dirname(kungfu_tpu.__file__)})")
    try:
        import jax

        print(f"JAX: {jax.__version__}")
    except ImportError:
        print("JAX is NOT installed")
    for mod in ("flax", "optax", "orbax.checkpoint", "torch"):
        try:
            m = __import__(mod)
            for part in mod.split(".")[1:]:
                m = getattr(m, part)
            print(f"{mod}: {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod} is NOT installed")


def _show_devices() -> None:
    try:
        import jax

        devs = jax.devices()
        kinds = {}
        for d in devs:
            kinds.setdefault((d.platform, d.device_kind), []).append(d.id)
        for (platform, kind), ids in kinds.items():
            print(f"devices: {len(ids)} x {kind} ({platform})")
    except Exception as e:  # noqa: BLE001 - a broken backend is a finding
        print(f"device init FAILED: {e}")


def _show_cluster_env() -> None:
    kf = {k: v for k, v in os.environ.items() if k.startswith("KF_")}
    if not kf:
        print("cluster env: none (not under kfrun)")
        return
    print("cluster env:")
    for k in sorted(kf):
        print(f"  {k}={kf[k]}")


def _show_telemetry(argv) -> None:
    from kungfu_tpu import telemetry

    feats = sorted(telemetry.features())
    print(f"telemetry: {','.join(feats) if feats else 'off'} "
          f"(KF_TELEMETRY={os.environ.get('KF_TELEMETRY', '')!r})")
    print("telemetry endpoints: http://<worker>:<peer_port+10000>"
          "/metrics | /trace | /audit")
    # an URL argument right after --telemetry: scrape a live worker
    idx = argv.index("--telemetry")
    url = argv[idx + 1] if idx + 1 < len(argv) else ""
    if url.startswith("http"):
        import urllib.request

        try:
            with urllib.request.urlopen(
                url.rstrip("/") + "/metrics", timeout=5
            ) as r:
                print(r.read().decode())
        except OSError as e:
            print(f"telemetry fetch FAILED: {e}")
        return
    # no URL: dump this process's own registry/trace/audit state
    d = telemetry.dump()
    n_spans = len(d["trace"]["traceEvents"])
    print(f"local trace buffer: {n_spans} events; "
          f"audit records: {len(d['audit'])}")
    if d["metrics"].strip():
        print(d["metrics"])


def main(argv) -> None:
    _show_versions()
    if "--no-devices" not in argv:
        _show_devices()
    _show_cluster_env()
    if "--telemetry" in argv:
        _show_telemetry(argv)
    allowed = (
        len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")  # Linux-only
        else os.cpu_count()
    )
    print(f"cpus: {allowed} allowed / {os.cpu_count()} online")


if __name__ == "__main__":
    main(sys.argv[1:])
