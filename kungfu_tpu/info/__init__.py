"""Environment/diagnostics report (parity: python -m kungfu.info,
srcs/python/kungfu/info/__main__.py — CUDA/NCCL/TF versions become
TPU/JAX/cluster facts)."""
