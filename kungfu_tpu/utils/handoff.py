"""Abort-aware thread handoff primitives for the collective engine.

One module owns the two shapes every engine stage hands work between
threads with (ISSUE 10 satellite — this used to be three near-identical
private implementations: ``host_session._par``, the fused pipeline's
``put``/``get`` closures, and the scheduler's launch queue):

- :class:`HandoffQueue` — a bounded queue whose every blocking operation
  polls a shared abort :class:`threading.Event`. A producer that died
  without enqueueing its sentinel can never strand a consumer (``get``
  turns into the ``None`` sentinel on abort), and a consumer that died
  can never wedge a producer (``put`` gives up and reports the drop).
- :func:`parallel_run` — goroutine-style fan-out over the shared cached
  thread pool: run all callables, wait under ONE deadline, re-raise the
  first error; on timeout the shared ``cancel`` event is set BEFORE
  raising so abandoned workers that later complete a receive observe it
  and must not mutate caller buffers (the late-write hazard).

Both primitives poll rather than wait unbounded — a lost notify or a
lost sentinel degrades to one poll interval of latency, never a hang
(the KF301 discipline, applied structurally instead of per call site).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

# how often a blocked put/get re-checks the abort flag; latency of an
# abort delivery, not of the data path (a ready item never waits)
_POLL_S = 0.2


class HandoffQueue:
    """Bounded handoff queue with abort-aware blocking put/get.

    All queues wired to the same ``abort`` event abort together — the
    engine passes one event per pipeline so any stage's failure (or the
    caller's timeout) unblocks every other stage at once.
    """

    def __init__(self, maxsize: int = 1,
                 abort: Optional[threading.Event] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, maxsize))
        self.abort = abort if abort is not None else threading.Event()

    def put(self, item) -> bool:
        """Blocking put; returns False (item dropped) once aborted."""
        while True:
            try:
                self._q.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                if self.abort.is_set():
                    return False

    def get(self):
        """Blocking get; returns the ``None`` sentinel once aborted, so
        a consumer can never be stranded by a lost sentinel."""
        while True:
            try:
                return self._q.get(timeout=_POLL_S)
            except queue.Empty:
                if self.abort.is_set():
                    return None

    def try_get(self, timeout: float):
        """Bounded get: the item, or None after ``timeout`` seconds or
        on abort (same sentinel contract as :meth:`get`)."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                return self._q.get(timeout=min(_POLL_S, remaining))
            except queue.Empty:
                if self.abort.is_set():
                    return None

    def close(self) -> None:
        """Abort the queue: wakes every blocked producer and consumer."""
        self.abort.set()

    def __len__(self) -> int:
        return self._q.qsize()


def parallel_run(
    fns: List[Callable[[], None]],
    timeout: float,
    cancel: Optional[threading.Event] = None,
) -> None:
    """Run callables on the shared cached-thread pool, wait for all,
    re-raise the first error (goroutine-style fan-out; an unbounded
    cached pool avoids both thread-spawn cost per call and
    pool-exhaustion deadlocks on nested parallelism).

    All waits share ONE deadline (worst case = timeout, not
    len(fns)*timeout). On timeout ``cancel`` is set before raising so
    abandoned workers that later complete a recv can observe it and must
    NOT mutate the caller's workspace (a reused recv buffer would be
    corrupted by a late write)."""
    if not fns:
        return
    if len(fns) == 1:
        fns[0]()
        return
    cond = threading.Condition()
    state = {"done": 0}
    errs: List[BaseException] = []

    def run(fn):
        err: Optional[BaseException] = None
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - propagated below
            err = e
        with cond:
            state["done"] += 1
            if err is not None:
                errs.append(err)
            cond.notify_all()

    from kungfu_tpu.utils.pool import get_pool

    pool = get_pool()
    for fn in fns:
        pool.submit(lambda f=fn: run(f))
    with cond:
        if not cond.wait_for(lambda: state["done"] >= len(fns), timeout):
            if cancel is not None:
                cancel.set()
            raise TimeoutError("collective thread timed out")
        if errs:
            raise errs[0]
