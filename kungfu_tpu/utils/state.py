"""Stateful scalar helpers: Counter and ExponentialMovingAverage.

Capability parity: srcs/cpp/src/tensorflow/ops/cpu/state.cpp:6-46 — the
reference exposes these as stateful TF graph ops (a step counter that
increments per sess.run, and an EMA accumulator used by adaptation
policies). JAX programs thread state functionally, so the jit-friendly
forms live next to their consumers (GNSState EMAs in monitor.noise_scale);
these host-side classes cover the reference's op surface for control-plane
code (schedules, policies, adaptive monitors).
"""

from __future__ import annotations

import threading
from typing import Optional


class Counter:
    """Monotone step counter (parity: Counter op, state.cpp:6-24).

    Like the reference op, the first call returns 0 ("incremented after
    read"): c() -> 0, 1, 2, ...
    """

    def __init__(self, init: int = 0):
        self._lock = threading.Lock()
        self._value = init

    def __call__(self) -> int:
        with self._lock:
            v = self._value
            self._value += 1
            return v

    @property
    def value(self) -> int:
        """Current count without incrementing."""
        with self._lock:
            return self._value


class ExponentialMovingAverage:
    """EMA accumulator (parity: ExponentialMovingAverage op,
    state.cpp:26-46 + utils/ema.hpp): the first sample seeds the average,
    later samples blend with weight `alpha`."""

    def __init__(self, alpha: float):
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self._lock = threading.Lock()
        self._value: Optional[float] = None

    def update(self, sample: float) -> float:
        with self._lock:
            if self._value is None:
                self._value = float(sample)
            else:
                self._value = self.alpha * float(sample) + (1 - self.alpha) * self._value
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return 0.0 if self._value is None else self._value
