"""Cached-thread executor and reusable byte-buffer pool.

Capability parity: the reference engine runs every graph-walk send/recv in
a goroutine and recycles payload buffers through a pool
(srcs/go/rchannel/connection/byte_slice_pool.go). Python threads are far
more expensive to create than goroutines, so the collective hot path must
not spawn a fresh thread per peer x chunk (the round-3 engine did; it was
the dominant cost at small message sizes).

`CachedThreadPool.submit` never blocks waiting for a free worker — an idle
parked thread is reused, otherwise a new one spawns (goroutine semantics;
a bounded pool would deadlock on nested _par fan-outs). Idle workers park
for `idle_ttl` seconds, then exit, so a big elastic cluster epoch doesn't
pin threads forever.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import defaultdict, deque
from typing import Callable, Deque, Dict, List, Optional

# declared lock hierarchy (kfcheck KF201): the executor takes the pool
# lock first, then a parked worker's condition to hand the task over
_KF_LOCK_ORDER = ("_lock", "cond")


class _Worker:
    __slots__ = ("task", "cond", "dead")

    def __init__(self):
        self.cond = threading.Condition()
        self.task: Optional[Callable[[], None]] = None
        self.dead = False


class CachedThreadPool:
    def __init__(self, idle_ttl: float = 30.0):
        self._idle: Deque[_Worker] = deque()
        self._lock = threading.Lock()
        self._ttl = idle_ttl
        # KF303-style names: the resource plane attributes these
        # threads' CPU to the walk engine by the kf-pool- prefix
        self._names = itertools.count()

    def submit(self, fn: Callable[[], None]) -> None:
        """Run fn on a cached (or new) daemon thread; never blocks."""
        with self._lock:
            while self._idle:
                w = self._idle.pop()
                with w.cond:
                    if w.dead:
                        continue
                    w.task = fn
                    w.cond.notify()
                return
        w = _Worker()
        w.task = fn
        threading.Thread(
            target=self._loop, args=(w,),
            name=f"kf-pool-{next(self._names)}", daemon=True,
        ).start()

    def _loop(self, w: _Worker) -> None:
        while True:
            task = w.task
            w.task = None
            try:
                task()
            except BaseException as e:  # noqa: BLE001 - must not kill the worker
                # submitted fns wrap their own errors; one escaping to
                # here is a caller bug worth a trace, not silence
                from kungfu_tpu.telemetry import log

                log.error("pool: submitted task raised: %r", e)
            with self._lock:
                self._idle.append(w)
            with w.cond:
                if not w.cond.wait_for(lambda: w.task is not None, self._ttl):
                    w.dead = True
                    return


_POOL = CachedThreadPool()


def get_pool() -> CachedThreadPool:
    return _POOL


class BufferPool:
    """Reusable bytearray pool keyed by exact size (parity:
    byte_slice_pool.go). Collectives re-receive the same chunk sizes every
    step, so exact-size bins hit ~always; unreturned buffers (timed-out
    receives whose writer may still be mid-fill) are simply leaked."""

    def __init__(self, max_per_size: int = 16):
        self._bins: Dict[int, List[bytearray]] = defaultdict(list)
        self._lock = threading.Lock()
        self._max = max_per_size

    def cached_bytes(self) -> int:
        """Bytes currently parked in the bins (the memory plane's
        `pool` bucket; buffers checked out to callers are the caller's
        RSS, not the pool's)."""
        with self._lock:
            return sum(
                len(buf) for bin_ in self._bins.values() for buf in bin_
            )

    def get(self, nbytes: int) -> bytearray:
        with self._lock:
            b = self._bins.get(nbytes)
            if b:
                return b.pop()
        return bytearray(nbytes)

    def put(self, buf: bytearray) -> None:
        with self._lock:
            b = self._bins[len(buf)]
            if len(b) < self._max:
                b.append(buf)


_BUFFERS = BufferPool()


def get_buffer_pool() -> BufferPool:
    return _BUFFERS


def _register_pool_accountant() -> None:
    # memory plane (ISSUE 17): the process-singleton buffer pool is a
    # long-lived buffer owner; lazy import keeps utils free of a
    # telemetry dependency at module load, best-effort because
    # telemetry must never break the walk hot path
    try:
        from kungfu_tpu.telemetry import memory as _tmem

        _tmem.register_accountant(
            "buffer_pool", "pool", _BUFFERS.cached_bytes
        )
    # kfcheck: disable=KF400 — byte accounting is best-effort;
    # it must never kill the pool
    except Exception:  # noqa: BLE001
        pass


_register_pool_accountant()
