"""Stall detector: liveness watchdog around blocking operations.

Capability parity: srcs/go/utils/stalldetector.go:15-46 — any guarded
operation that runs longer than the period logs "X stalled for Ns"
repeatedly until it completes; enabled by KF_CONFIG_ENABLE_STALL_DETECTION
around collective calls and resize paths (libkungfu-comm/main.go:179-190).
"""

from __future__ import annotations

import contextlib
import threading
import time

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import log as _log

DEFAULT_PERIOD = 3.0


def enabled() -> bool:
    return bool(knobs.get("KF_CONFIG_ENABLE_STALL_DETECTION"))


@contextlib.contextmanager
def stall_detect(name: str, period: float = DEFAULT_PERIOD, force: bool = False):
    """Context manager: while the body runs, log every `period` seconds."""
    if not (force or enabled()):
        yield
        return
    done = threading.Event()
    t0 = time.monotonic()

    def watch():
        n = 0
        while not done.wait(period):
            n += 1
            elapsed = time.monotonic() - t0
            _log.warn("%s stalled for %.1fs", name, elapsed)

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    try:
        yield
    finally:
        done.set()
