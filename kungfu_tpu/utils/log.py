"""Back-compat shim: the leveled logger moved to kungfu_tpu.telemetry.log.

Same API (debug/info/warn/error with %-args, set_level, set_output)
plus structured key=value fields and ``echo()`` for CLI surfaces; level
honours KF_LOG_LEVEL with fallback to the legacy KF_CONFIG_LOG_LEVEL.
"""

from __future__ import annotations

from kungfu_tpu.telemetry.log import (  # noqa: F401
    LEVELS,
    debug,
    echo,
    error,
    info,
    reset,
    set_level,
    set_output,
    warn,
    warning,
)
