"""Leveled logger with per-process prefixes.

Capability parity: srcs/go/log/logger.go — DEBUG/INFO/WARN/ERROR levels,
level set from the environment (KF_CONFIG_LOG_LEVEL), optional redirection
to a logfile. The runner gives every worker a colored rank prefix (parity:
utils/iostream xterm coloring) via KF_LOG_PREFIX.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional, TextIO

LEVELS = {"DEBUG": 10, "INFO": 20, "WARN": 30, "ERROR": 40, "OFF": 100}
_COLORS = [31, 32, 33, 34, 35, 36]  # red..cyan, cycled by rank

_lock = threading.Lock()
_state = {"level": None, "out": None, "prefix": None}


def _level() -> int:
    if _state["level"] is None:
        name = os.environ.get("KF_CONFIG_LOG_LEVEL", "INFO").upper()
        _state["level"] = LEVELS.get(name, 20)
    return _state["level"]


def set_level(name: str) -> None:
    with _lock:
        _state["level"] = LEVELS.get(name.upper(), 20)


def set_output(f: Optional[TextIO]) -> None:
    """Redirect log output (parity: logger.go output redirection)."""
    with _lock:
        _state["out"] = f


def _prefix() -> str:
    if _state["prefix"] is None:
        p = os.environ.get("KF_LOG_PREFIX", "")
        if p and sys.stderr.isatty():
            try:
                rank = int(p.split("/")[0])
                p = f"\x1b[{_COLORS[rank % len(_COLORS)]}m[{p}]\x1b[0m"
            except ValueError:
                p = f"[{p}]"
        elif p:
            p = f"[{p}]"
        _state["prefix"] = p
    return _state["prefix"]


def _emit(level_name: str, level: int, msg: str) -> None:
    if level < _level():
        return
    out = _state["out"] or sys.stderr
    ts = time.strftime("%H:%M:%S")
    pre = _prefix()
    with _lock:
        print(f"{ts} [{level_name[0]}] kungfu{pre} {msg}", file=out, flush=True)


def debug(msg: str, *args) -> None:
    _emit("DEBUG", 10, msg % args if args else msg)


def info(msg: str, *args) -> None:
    _emit("INFO", 20, msg % args if args else msg)


def warn(msg: str, *args) -> None:
    _emit("WARN", 30, msg % args if args else msg)


def error(msg: str, *args) -> None:
    _emit("ERROR", 40, msg % args if args else msg)
