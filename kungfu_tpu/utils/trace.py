"""Scoped tracer: named spans with wall-clock durations.

Capability parity: the reference's profiling hooks (experimental/hook/
elastic.py ResizeProfiler, srcs/go tracing helpers) — lightweight,
always-on (a span is two perf_counter calls and a deque append), queried
by benchmarks and surfaced per-resize by the peer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Tuple

_lock = threading.Lock()
_events: "deque[Tuple[str, float, float]]" = deque(maxlen=4096)


@contextmanager
def span(name: str):
    """Time a scope; records (name, start, duration_s)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _events.append((name, t0, dt))


def record(name: str, duration_s: float) -> None:
    with _lock:
        _events.append((name, time.perf_counter(), duration_s))


def events(prefix: str = "") -> List[Tuple[str, float, float]]:
    with _lock:
        evs = list(_events)
    if prefix:
        evs = [e for e in evs if e[0].startswith(prefix)]
    return evs


def clear() -> None:
    with _lock:
        _events.clear()


def summary_ms(prefix: str = "") -> Dict[str, float]:
    """Total duration per span name (ms), filtered by prefix."""
    out: Dict[str, float] = {}
    for name, _, dt in events(prefix):
        out[name] = out.get(name, 0.0) + dt * 1e3
    return {k: round(v, 1) for k, v in out.items()}
