"""Back-compat shim: the scoped tracer moved to kungfu_tpu.telemetry.tracing.

Every existing ``utils.trace`` call site (transport, collective walks,
elastic resize phases, benchmarks) now records into the unified
telemetry ring buffer, so the spans show up in ``/trace`` Chrome-trace
exports and ``telemetry.dump()`` alongside metrics and audit records.
"""

from __future__ import annotations

from kungfu_tpu.telemetry.tracing import (  # noqa: F401
    MAX_EVENTS,
    TraceEvent,
    chrome_trace,
    chrome_trace_json,
    clear,
    current_step,
    events,
    export_chrome,
    full_events,
    instant,
    record,
    span,
    step_scope,
    summary_ms,
)
