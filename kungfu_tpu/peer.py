"""Peer: the worker-side runtime root.

Capability parity: srcs/go/kungfu/peer/peer.go:27-308 — every worker embeds
the whole host-side communication runtime: a transport server+client, the
current cluster (version'd), a HostSession cache, and the elastic-resize
protocol (consensus on a proposed cluster, notify runners, bump version,
rebuild session, barrier).

TPU mapping: the Peer manages the HOST plane only. Device work happens in
DeviceSession (kungfu_tpu.parallel.mesh); on a resize the worker process is
expected to rebuild its DeviceSession/mesh (reload-style), which is the
TPU-native elastic mode (ICI mesh shape is fixed per slice — SURVEY §7).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Optional, Tuple

from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.collective.host_session import HostSession
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.runner import env as kfenv
from kungfu_tpu.store.versioned import BlobStore
from kungfu_tpu.transport.client import Client
from kungfu_tpu.transport.handlers import (
    CollectiveEndpoint,
    ControlEndpoint,
    P2PEndpoint,
    QueueEndpoint,
)
from kungfu_tpu.transport.message import ConnType, Flags, Message
from kungfu_tpu.transport.server import Server
from kungfu_tpu.utils import log, trace
from kungfu_tpu.utils.stall import stall_detect

_default_peer: Optional["Peer"] = None
_default_lock = threading.Lock()


def get_default_peer() -> "Peer":
    """Process-wide singleton (parity: Peer::GetDefault, peer.hpp)."""
    global _default_peer
    with _default_lock:
        if _default_peer is None:
            with trace.span("worker.parse_config"):
                cfg = kfenv.parse_config_from_env()
            with trace.span("worker.peer_init"):
                _default_peer = Peer(cfg)
            _default_peer.start()
        return _default_peer


def finalize_default_peer() -> None:
    global _default_peer
    with _default_lock:
        if _default_peer is not None:
            _default_peer.stop()
            _default_peer = None


class Peer:
    def __init__(self, config: kfenv.WorkerConfig):
        self.config = config
        self.self_id = config.self_id
        self.cluster_version = config.cluster_version
        self.detached = False
        self._peers = config.peers
        self._session: Optional[HostSession] = None
        self._session_lock = threading.RLock()
        self._updated = True
        # number of cluster epochs this PROCESS has lived through; 1 after
        # startup, >1 once it survives a delta resize. Lets elastic state
        # sync pick a provably surviving broadcast root.
        self.epoch_count = 0
        # per-phase wall-clock (ms) of the most recent resize, as seen by
        # this (surviving) peer: wait_config / consensus / notify / update
        # (update = reconnect + new-session barrier, i.e. joiner-bounded).
        # Parity: the reference's ResizeProfiler phase breakdown.
        self.last_resize_phases: dict = {}
        # KF700: config-poll/reload consensus rounds consumed, PER cluster
        # version — every member of a session epoch runs these consensus
        # rounds in lockstep (an allreduce needs all of them), so the
        # (version, rounds-this-version) pair agrees cluster-wide where a
        # process-lifetime counter would diverge for joiners
        self._cfg_consensus_seq: dict = {}

        self.store = BlobStore()
        self.client = Client(self.self_id, use_unix=not config.single_process)
        self.server = Server(self.self_id, use_unix=not config.single_process)
        self.collective = CollectiveEndpoint()
        self.queue = QueueEndpoint()
        self.p2p = P2PEndpoint(self.store, self.client, self.self_id)
        self.server.register(ConnType.COLLECTIVE, self.collective.handle)
        self.server.register(ConnType.QUEUE, self.queue.handle)
        self.server.register(ConnType.PEER_TO_PEER, self.p2p.handle)

    # ------------------------------------------------------------------
    def start(self) -> None:
        import os

        from kungfu_tpu import knobs

        spawn_ts = knobs.raw("KF_SPAWN_TS")
        if spawn_ts:
            # joiner-readiness latency: runner spawn (or standby
            # activation) -> host plane up; the term that bounds the
            # survivors' rebuild barrier during an elastic grow
            try:
                startup = time.time() - float(spawn_ts)
                trace.record("worker.startup", startup)
                log.info("worker ready %.0f ms after spawn", startup * 1e3)
            except ValueError:
                pass
        if not self.config.single_process:
            with trace.span("worker.start.server"):
                self.server.start()
        self._start_telemetry_server()
        self._start_flight_recorder()
        with trace.span("worker.start.update"):
            self._update_to(self._peers)

    def _start_telemetry_server(self) -> None:
        """Expose /metrics + /trace + /audit on self.port+10000 when any
        telemetry is on (parity: peer/peer.go:96-104, generalized from the
        old /metrics-only server in monitor/net.py)."""
        self.metrics_server = None
        from kungfu_tpu import telemetry
        from kungfu_tpu.monitor import net as _net

        want = _net.enabled() or telemetry.features()
        if want and not self.config.single_process:
            # materialize the singleton so transport counters mirror into
            # the registry this server renders
            _net.get_monitor()
            try:
                from kungfu_tpu.telemetry.http import TelemetryServer

                self.metrics_server = TelemetryServer(self.self_id.port + 10000)
                self.metrics_server.start()
            except (OSError, OverflowError) as e:
                # OverflowError: peer port within 10000 of 65535
                log.warn("telemetry server failed to start: %s", e)

    def _start_flight_recorder(self) -> None:
        """Durable flight recorder (ISSUE 3): journal telemetry
        snapshots to disk so a SIGKILL'd/OOM'd worker leaves a black
        box. kfrun injects KF_TELEMETRY_DIR, which turns it on; bare
        in-process peers (tests, single_process) stay off unless asked."""
        self.flight_recorder = None
        if self.config.single_process:
            return
        from kungfu_tpu.telemetry import flight

        self.flight_recorder = flight.start_recorder(peer=str(self.self_id))

    def stop(self) -> None:
        with self._session_lock:
            if self._session is not None:
                self._session.close(timeout=5.0)
        self.server.stop()
        self.client.close()
        if getattr(self, "metrics_server", None) is not None:
            # clean shutdown on peer exit: close the listening socket too
            self.metrics_server.stop()
        if getattr(self, "flight_recorder", None) is not None:
            from kungfu_tpu.telemetry import flight

            flight.stop_recorder(reason="peer_stop")
            self.flight_recorder = None

    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        return self.current_session().rank

    @property
    def size(self) -> int:
        return self.current_session().size

    def current_session(self) -> HostSession:
        with self._session_lock:
            if self._session is None:
                raise RuntimeError("peer not started")
            return self._session

    def _update_to(self, peers: PeerList) -> bool:
        """Rebuild the session for a new peer list; returns False if self is
        not a member (detached). Parity: peer.updateTo (peer.go:148-170)."""
        with self._session_lock:
            old_session = self._session
            if self._session is not None:
                # session-epoch invalidation (ISSUE 10): the old epoch's
                # async scheduler must drain or cancel its in-flight
                # buckets BEFORE the transport token advances and the
                # session is replaced — a walk left running would wedge
                # on fenced messages and could write caller buffers the
                # new epoch already reuses. Detached peers drain too:
                # their epoch ended just as finally.
                with trace.span("resize.drain_scheduler"):
                    self._session.close(timeout=10.0)
            if peers.rank(self.self_id) is None:
                self.detached = True
                # a detached peer is not in the target set, so the
                # election below would clear the role anyway — but it
                # must happen even on this early exit
                self._update_host_role(peers)
                return False
            self.server.set_token(self.cluster_version)
            self.client.set_token(self.cluster_version)
            self.client.reset_connections()
            self._session = HostSession(
                self.config.strategy,
                self.self_id,
                peers,
                self.client,
                self.collective,
                cluster_version=self.cluster_version,
            )
            self._peers = peers
            self.epoch_count += 1
            # decision ledger (ISSUE 15): an engine-mode flip at a
            # session epoch (KF_CONFIG_ASYNC / KF_CONFIG_ZERO resolving
            # differently — env change under `reload`, or `auto`
            # crossing the multi-peer threshold on a resize) is an
            # adaptation like any vote: open its causal record so the
            # paired step windows measure whether it helped
            if old_session is not None:
                from kungfu_tpu.telemetry import decisions as _decisions

                for kind, was, now in (
                    ("async_mode", old_session.async_enabled(),
                     self._session.async_enabled()),
                    ("zero_mode", old_session.zero_enabled(),
                     self._session.zero_enabled()),
                ):
                    if was != now:
                        _decisions.open_decision(
                            kind,
                            peer=str(self.self_id),
                            epoch=self.cluster_version,
                            trigger="session_epoch",
                            old="on" if was else "off",
                            new="on" if now else "off",
                        )
            # link plane: drop estimators for departed destinations —
            # a shed peer's frozen bandwidth estimate must not keep
            # winning links/min_bw or walk-efficiency scoring (runners
            # stay: stable control-plane membership)
            from kungfu_tpu.telemetry import link as tlink

            if tlink.enabled():
                tlink.get_table().prune(
                    list(peers) + list(self.config.runners)
                )
            # host sub-aggregator election (ISSUE 18): at scale the
            # lowest-labelled worker per host pre-merges its siblings'
            # telemetry for the root aggregator; membership changes
            # re-elect deterministically on every peer
            self._update_host_role(peers)
        if not self.config.single_process:
            # fail-fast BEFORE the barrier: the barrier itself walks
            # strategy-dependent graphs, so knob-divergent peers would
            # hang right here instead of raising a named error
            with trace.span("worker.knob_consensus"):
                self._session.check_knob_consensus()
            self._session.barrier(tag=f":v{self.cluster_version}")
        self._updated = True
        return True

    def _update_host_role(self, peers: PeerList) -> None:
        """Recompute this worker's host sub-aggregator election (ISSUE
        18). Never lets a telemetry-plane failure touch the resize
        path: the role is an optimization the root falls back from."""
        if self.config.single_process:
            return
        if getattr(self, "metrics_server", None) is None:
            return  # no telemetry server, nothing to elect for
        try:
            from kungfu_tpu.telemetry import cluster as _cluster

            _cluster.update_host_role(self.self_id, list(peers))
        except Exception as e:  # noqa: BLE001 - telemetry must not break resizes
            log.warn("host telemetry role update failed: %s", e)

    def set_tree(self, fathers) -> None:
        """Install a runtime collective tree on the CURRENT session epoch.

        Parity: SetTree (adaptation.cpp:5-33). The father array indexes
        this epoch's rank space, so it does NOT survive a resize — like the
        reference, a new session reverts to the configured strategy and the
        caller re-probes (api.optimized_tree) if it wants a tuned topology.
        A same-size resize can swap members, so persisting would silently
        apply an MST probed on different machines (ADVICE r2)."""
        self.current_session().set_tree(list(int(f) for f in fathers))

    # ------------------------------------------------------------------
    # elastic resize protocol (parity: peer.go propose/ResizeCluster*)
    # ------------------------------------------------------------------

    def _notify_runners(self, stage: dict) -> None:
        """Send the new Stage to every runner (parity: peer.go:200-214)."""
        payload = json.dumps(stage).encode()
        log.debug("notifying %d runners: v%s", len(self.config.runners), stage.get("Version"))
        for runner in self.config.runners:
            if not self.client.wait_peer(runner, timeout=30):
                raise ConnectionError(f"runner {runner} unreachable")
            self.client.send(runner, "update", payload, ConnType.CONTROL)
            log.debug("notified runner %s", runner)

    def _propose(
        self,
        cluster: Cluster,
        progress: int = 0,
        trigger: str = "explicit",
        pre_phases: Optional[dict] = None,
    ) -> Tuple[bool, bool]:
        """Consensus-check and adopt a new cluster.

        Returns (accepted, keep): keep=False means self is detached.
        Parity: peer.propose (peer.go:181-233) including the safety check —
        peers must agree on the proposed bytes or the resize is rejected.
        `trigger` and `pre_phases` (e.g. the config-server wait) feed the
        telemetry resize audit record.
        """
        sess = self.current_session()
        t0 = time.perf_counter()
        with trace.span("resize.consensus"):
            agreed = sess.bytes_consensus(
                cluster.to_bytes(), f":propose:v{self.cluster_version}"
            )
        if not agreed:
            return False, True
        if self._peers == cluster.workers:
            return True, True  # no change
        old_peers = self._peers
        self.last_resize_phases = dict(pre_phases or {})
        self.last_resize_phases["consensus_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1
        )
        stage = {
            "Version": self.cluster_version + 1,
            "Progress": progress,
            "Cluster": cluster.to_json(),
        }
        if sess.rank == 0 and self.config.runners:
            t1 = time.perf_counter()
            with trace.span("resize.notify"):
                self._notify_runners(stage)
            self.last_resize_phases["notify_ms"] = round(
                (time.perf_counter() - t1) * 1e3, 1
            )
        # all peers advance the version together (they all ran the consensus)
        self.cluster_version += 1
        t2 = time.perf_counter()
        with trace.span("resize.update"):
            keep = self._update_to(cluster.workers)
        self.last_resize_phases["update_ms"] = round(
            (time.perf_counter() - t2) * 1e3, 1
        )
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_resize(
            peer=str(self.self_id),
            cluster_version=self.cluster_version,
            trigger=trigger,
            old_peers=list(old_peers),
            new_peers=list(cluster.workers),
            phases_ms=self.last_resize_phases,
            progress=progress or None,
            detached=not keep,
        )
        if keep:
            # decision ledger (ISSUE 15): the resize is the capacity
            # decision ROADMAP item 4's autoscaler must trust — open the
            # outcome record on every surviving peer (a detached peer
            # has no post-flip steps to measure)
            from kungfu_tpu.telemetry import decisions as _decisions

            _decisions.open_decision(
                "resize",
                peer=str(self.self_id),
                epoch=self.cluster_version,
                trigger=trigger,
                old_size=len(old_peers),
                new_size=len(cluster.workers),
            )
        log.info(
            "resize v%d: %d -> %d workers (%s)%s",
            self.cluster_version,
            len(old_peers),
            len(cluster.workers),
            trigger,
            "" if keep else " [detached]",
        )
        return True, keep

    def _get_config(self, url: str, attempts: int = 3) -> Optional[Cluster]:
        """GET the desired cluster; a few retries absorb transient server
        blips so a published resize isn't silently dropped by the
        current-cluster fallback in _wait_new_config."""
        for i in range(attempts):
            try:
                with urllib.request.urlopen(url, timeout=5) as resp:
                    return Cluster.loads(resp.read().decode())
            except Exception as e:
                if i + 1 < attempts:
                    time.sleep(0.3)
                else:
                    log.warn("config server unreachable after %d tries "
                             "(%s): %s", attempts, url, e)
        return None

    def _wait_new_config(self, url: str) -> Cluster:
        """Poll the config server until all current peers see the same
        cluster (parity: waitNewConfig, peer.go:242-263). When the server is
        unreachable or has no config, each peer falls back to its CURRENT
        cluster (the reference's "using current config" path) — once all
        peers agree (e.g. the server is down for everyone) the resize
        degrades to a no-op instead of hanging the training loop."""
        sess = self.current_session()
        current = Cluster(runners=self.config.runners, workers=self._peers)
        # KF700: the poll retries back-to-back consensus rounds, so each
        # round gets its own rendezvous name — a slow peer's round r must
        # never consume the lanes of a fast peer's round r+1. Peers
        # iterate in lockstep (bytes_consensus resolves identically
        # cluster-wide), and the per-epoch sequence survives REPEATED
        # calls at the same version (a plain per-call attempt counter
        # would reuse names across calls)
        while True:
            cluster = self._get_config(url) or current
            with stall_detect(f"wait_new_config({url})"):
                if sess.bytes_consensus(
                    cluster.to_bytes(), self._cfg_consensus_name("cfg")
                ):
                    return cluster
            time.sleep(0.2)

    def _cfg_consensus_name(self, kind: str) -> str:
        """Round-stamped rendezvous name for the config-plane consensus
        lanes: `:{kind}:v{version}:{seq}` with seq the count of such
        rounds THIS session epoch has run (all epoch members run them in
        lockstep, so the stamp agrees cluster-wide; a joiner starts the
        new epoch at 0 together with everyone else)."""
        v = self.cluster_version
        seq = self._cfg_consensus_seq.get(v, 0)
        self._cfg_consensus_seq[v] = seq + 1
        return f":{kind}:v{v}:{seq}"

    def resize_cluster_from_url(self) -> Tuple[bool, bool]:
        """(changed, detached). Parity: ResizeClusterFromURL (peer.go:265)."""
        url = self.config.config_server
        if not url:
            return False, False
        t0 = time.perf_counter()
        with trace.span("resize.wait_config"):
            cluster = self._wait_new_config(url)
        wait_ms = round((time.perf_counter() - t0) * 1e3, 1)
        if cluster.workers == self._peers:
            return False, False
        # pre_phases rides into _propose so a REJECTED proposal never
        # splices this wait into the previous resize's phase breakdown
        accepted, keep = self._propose(
            cluster,
            trigger="config_server",
            pre_phases={"wait_config_ms": wait_ms},
        )
        return accepted, not keep

    def resize_cluster(self, new_size: int) -> Tuple[bool, bool]:
        """Explicit resize to new_size workers (parity: ResizeCluster)."""
        current = Cluster(runners=self.config.runners, workers=self._peers)
        cluster = current.resize(new_size)
        if cluster.workers == self._peers:
            return False, False
        accepted, keep = self._propose(cluster, trigger="explicit")
        return accepted, not keep

    def propose_new_size(self, new_size: int) -> None:
        """Publish a desired size to the config server (rank-agnostic;
        parity: ProposeNewSize -> config-server PUT)."""
        url = self.config.config_server
        if not url:
            raise RuntimeError("no config server configured")
        current = Cluster(runners=self.config.runners, workers=self._peers)
        cluster = current.resize(new_size)
        data = cluster.dumps().encode()
        req = urllib.request.Request(url, data=data, method="PUT")
        with urllib.request.urlopen(req, timeout=5) as resp:
            resp.read()

    def change_cluster(self, progress: int) -> Tuple[bool, bool]:
        """Reload-mode resize: every worker exits and the runners relaunch
        from `progress` (parity: ChangeCluster, peer.go:279-291 +
        ElasticModeReload). Returns (changed, detached_all)."""
        url = self.config.config_server
        if not url:
            return False, False
        cluster = self._wait_new_config(url)
        if cluster.workers == self._peers:
            return False, False
        sess = self.current_session()
        # KF700: epoch-sequenced — a reload agreement must not rendezvous
        # with an earlier attempt's lanes (repeat change_cluster calls at
        # one version) nor with another epoch's
        if not sess.bytes_consensus(
            cluster.to_bytes(), self._cfg_consensus_name("reload")
        ):
            return False, False
        stage = {
            "Version": self.cluster_version + 1,
            "Progress": progress,
            "Cluster": cluster.to_json(),
            "Reload": True,
        }
        if sess.rank == 0 and self.config.runners:
            self._notify_runners(stage)
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_resize(
            peer=str(self.self_id),
            cluster_version=self.cluster_version + 1,
            trigger="reload",
            old_peers=list(self._peers),
            new_peers=list(cluster.workers),
            progress=progress,
            detached=True,
        )
        # in reload mode every worker detaches; runners restart the world
        self.detached = True
        return True, True
