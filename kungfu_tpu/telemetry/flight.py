"""Durable flight recorder + crash forensics (ISSUE 3 tentpole).

Every telemetry surface built so far (metrics registry, span ring,
audit log, structured log) lives in process memory and dies with the
worker — at exactly the moment ``recover_from_failure`` needs to know
*why* it died. This module is the black box:

- **Journal**: a crash-safe, append-only on-disk file of length-prefixed
  CRC-framed JSON records under ``KF_TELEMETRY_DIR`` (default
  ``/tmp/kungfu-telemetry/<run-id>/<peer>/``). Appends are a single
  buffered write + flush, so a SIGKILL can at worst truncate the final
  record — the reader yields every complete record and stops at the
  first torn/corrupt frame instead of failing.
- **FlightRecorder**: periodically checkpoints the metrics registry,
  recent/open trace spans, audit events and the structured-log tail;
  enables ``faulthandler`` into a dedicated per-worker file; registers
  atexit + SIGTERM flush; dumps on demand on SIGUSR2.
- **Harvesting**: the runner-side :func:`harvest_postmortem` reads a
  dead worker's journal + faulthandler file and synthesizes a
  postmortem dict (exit code/signal, last step, final audit events,
  open spans at death, tracebacks, output tail);
  :func:`render_postmortem` turns it into the human-readable death
  timeline behind ``python -m kungfu_tpu.info postmortem``.

The journal is size-bounded: when it exceeds ``KF_FLIGHT_MAX_BYTES``
it rotates to ``journal.prev.bin`` (one generation), so a long run costs
at most ~2x the cap per worker. Snapshots are bounded staleness by
design — a SIGKILL loses at most the last ``KF_FLIGHT_INTERVAL``
seconds, which is the flight-recorder contract, not a bug.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import struct
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import audit, decisions, log, metrics, steptrace, tracing
from kungfu_tpu.telemetry.config import env_truthy, truthy

DIR_ENV = "KF_TELEMETRY_DIR"
FLIGHT_ENV = "KF_FLIGHT"  # explicit on/off override
INTERVAL_ENV = "KF_FLIGHT_INTERVAL"
FSYNC_ENV = "KF_FLIGHT_FSYNC"
MAX_BYTES_ENV = "KF_FLIGHT_MAX_BYTES"

DEFAULT_BASE = "/tmp/kungfu-telemetry"
DEFAULT_INTERVAL = 5.0
DEFAULT_MAX_BYTES = 8 * 1024 * 1024

JOURNAL_NAME = "journal.bin"
JOURNAL_PREV_NAME = "journal.prev.bin"
FAULT_NAME = "faulthandler.log"
META_NAME = "meta.json"
POSTMORTEM_NAME = "postmortems.jsonl"

MAGIC = b"KFJ1"  # journal file header
_FRAME = struct.Struct("<II")  # payload length, crc32(payload)

# journaled snapshot bounds: a record must stay cheap to write every
# few seconds AND cheap to read back in bulk
SPAN_TAIL = 48
AUDIT_TAIL = 32
LOG_TAIL = 60
DECISION_TAIL = 8


def _env_float(name: str, default: float) -> float:
    """Declared float knob, floored at the built-in default when the
    configured value is non-positive (a zero snapshot interval or
    journal bound would mean a busy loop / instant rotation)."""
    from kungfu_tpu import knobs

    v = float(knobs.get(name))
    return v if v > 0 else default


def sanitize_label(label: str) -> str:
    """A peer label ("host:port") as a safe single path component."""
    out = "".join(c if c.isalnum() or c in "._-" else "_" for c in str(label))
    return out or "peer"


def default_run_dir() -> str:
    """A fresh per-run directory under the default base (the runner
    mints one and injects it as KF_TELEMETRY_DIR into every worker)."""
    run_id = f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
    return os.path.join(DEFAULT_BASE, run_id)


def peer_dir(run_dir: str, peer: str) -> str:
    return os.path.join(run_dir, sanitize_label(peer))


def prune_runs(base: str = DEFAULT_BASE, keep: int = 32) -> int:
    """Drop the oldest run dirs under the DEFAULT base so unattended CI
    or dev loops don't grow /tmp forever. Only ever called with the
    default base; an operator-chosen KF_TELEMETRY_DIR is never touched."""
    import shutil

    try:
        runs = sorted(
            (e for e in os.scandir(base) if e.is_dir()),
            key=lambda e: e.stat().st_mtime,
        )
    except OSError:
        return 0
    doomed = runs[: max(0, len(runs) - keep)]
    n = 0
    for e in doomed:
        try:
            shutil.rmtree(e.path)
            n += 1
        except OSError:
            pass
    return n


# ---------------------------------------------------------------------------
# journal format
# ---------------------------------------------------------------------------


class JournalWriter:
    """Append-only CRC-framed record file. Thread-safe; every append is
    one buffered write + flush so a dying process tears at most the
    final frame."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = (
            max_bytes
            if max_bytes is not None
            else int(_env_float(MAX_BYTES_ENV, DEFAULT_MAX_BYTES))
        )
        self.fsync = env_truthy(FSYNC_ENV)
        self._lock = threading.Lock()
        self._f = None
        self._open()

    def _open(self) -> None:
        fresh = not os.path.exists(self.path) or os.path.getsize(self.path) == 0
        self._f = open(self.path, "ab")
        if fresh:
            self._f.write(MAGIC)
            self._f.flush()

    def append(self, record: dict) -> None:
        payload = json.dumps(record, separators=(",", ":")).encode()
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._f is None:
                return
            if self._f.tell() + len(frame) > self.max_bytes:
                self._rotate()
            self._f.write(frame)
            self._f.flush()
            if self.fsync:
                try:
                    os.fsync(self._f.fileno())
                except OSError:
                    pass

    def _rotate(self) -> None:
        # one prev generation: bounded disk, and the reader still sees
        # a long history across the rotation boundary
        self._f.close()
        try:
            os.replace(self.path, _prev_path(self.path))
        except OSError:
            pass
        self._f = None
        self._open()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def _prev_path(path: str) -> str:
    return os.path.join(os.path.dirname(path), JOURNAL_PREV_NAME)


def read_journal_file(path: str) -> Tuple[List[dict], Optional[str]]:
    """All complete records of one journal file, tolerantly: a
    truncated or corrupt tail frame ends the read (returning everything
    before it) instead of raising. Returns (records, error) where error
    describes why reading stopped early, or None for a clean EOF."""
    records: List[dict] = []
    try:
        f = open(path, "rb")
    except OSError as e:
        return records, str(e)
    with f:
        head = f.read(len(MAGIC))
        if head != MAGIC:
            return records, f"bad journal magic {head!r}"
        while True:
            hdr = f.read(_FRAME.size)
            if not hdr:
                return records, None  # clean EOF
            if len(hdr) < _FRAME.size:
                return records, "truncated frame header"
            length, crc = _FRAME.unpack(hdr)
            payload = f.read(length)
            if len(payload) < length:
                return records, "truncated record payload"
            if zlib.crc32(payload) != crc:
                # after a CRC mismatch the length framing itself is
                # untrusted: stop, keep everything complete before it
                return records, "CRC mismatch"
            try:
                records.append(json.loads(payload.decode()))
            except ValueError:
                return records, "undecodable record"


def read_journal(dir_or_file: str) -> Tuple[List[dict], List[str]]:
    """Records of one peer's journal (prev generation first), with a
    list of non-fatal read errors."""
    if os.path.isdir(dir_or_file):
        paths = [
            os.path.join(dir_or_file, JOURNAL_PREV_NAME),
            os.path.join(dir_or_file, JOURNAL_NAME),
        ]
    else:
        paths = [dir_or_file]
    records: List[dict] = []
    errors: List[str] = []
    for p in paths:
        if not os.path.exists(p):
            continue
        recs, err = read_journal_file(p)
        records.extend(recs)
        if err is not None:
            errors.append(f"{os.path.basename(p)}: {err}")
    return records, errors


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """One per worker process: journals periodic telemetry snapshots and
    terminal events into its peer directory."""

    def __init__(
        self,
        directory: str,
        peer: str = "",
        interval: Optional[float] = None,
        enable_faulthandler: bool = True,
        install_signal_handlers: bool = True,
    ):
        self.dir = directory
        self.peer = str(peer)
        self.interval = (
            interval
            if interval is not None
            else _env_float(INTERVAL_ENV, DEFAULT_INTERVAL)
        )
        os.makedirs(self.dir, exist_ok=True)
        self.journal = JournalWriter(os.path.join(self.dir, JOURNAL_NAME))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()
        self._fault_file = None
        meta = {
            "kind": "meta",
            "wall_time": time.time(),
            "peer": self.peer,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "argv": list(sys.argv),
            "python": sys.version.split()[0],
            "interval_s": self.interval,
        }
        try:
            with open(os.path.join(self.dir, META_NAME), "w") as f:
                json.dump(meta, f, indent=2)
        except OSError:
            pass
        self.journal.append(meta)
        if enable_faulthandler:
            self._enable_faulthandler()
        if install_signal_handlers:
            self._install_signal_handlers()
        atexit.register(self._atexit)

    # -- setup ---------------------------------------------------------
    def _enable_faulthandler(self) -> None:
        import faulthandler

        try:
            self._fault_file = open(os.path.join(self.dir, FAULT_NAME), "w")
            faulthandler.enable(file=self._fault_file, all_threads=True)
        except (OSError, ValueError):
            self._fault_file = None

    def _install_signal_handlers(self) -> None:
        # only possible on the main thread; a recorder started from a
        # helper thread still journals, it just can't hook signals
        try:
            prev_term = signal.getsignal(signal.SIGTERM)
            if prev_term is not None:
                # getsignal() -> None means a handler installed from C
                # that we cannot chain faithfully — leave SIGTERM alone
                # (atexit still covers a clean teardown)

                def on_term(signum, frame):
                    # flush from a fresh thread with a bounded join: the
                    # handler may have interrupted THIS thread mid-append,
                    # and close() re-acquiring those non-reentrant locks
                    # inline would deadlock the shutdown forever. If the
                    # locks are wedged we lose the exit record (the reader
                    # tolerates the torn tail) but the SIGTERM still kills.
                    t = threading.Thread(
                        target=self.close, kwargs={"reason": "sigterm"},
                        name="kf-flight-term", daemon=True,
                    )
                    t.start()
                    t.join(2.0)
                    if prev_term == signal.SIG_IGN:
                        return  # the process chose to survive SIGTERM
                    if callable(prev_term):
                        prev_term(signum, frame)
                    else:  # SIG_DFL
                        signal.signal(signum, signal.SIG_DFL)
                        os.kill(os.getpid(), signum)

                signal.signal(signal.SIGTERM, on_term)
            if hasattr(signal, "SIGUSR2"):

                def on_usr2(signum, frame):
                    # dump from a fresh thread: a handler interrupting
                    # the main thread mid-append must not re-enter the
                    # journal lock it already holds
                    threading.Thread(
                        target=self.dump, kwargs={"reason": "sigusr2"},
                        name="kf-flight-usr2", daemon=True,
                    ).start()

                signal.signal(signal.SIGUSR2, on_usr2)
        except (ValueError, OSError):
            pass

    # -- recording -----------------------------------------------------
    def _snapshot_record(self, kind: str, **extra) -> dict:
        metrics.update_process_health()
        spans = [
            # compact tuples: name, start (perf s), duration (ms)
            [e.name, round(e.start, 6), round(e.duration * 1e3, 3)]
            for e in tracing.full_events()[-SPAN_TAIL:]
        ]
        rec = {
            "kind": kind,
            "wall_time": time.time(),
            "perf_now": time.perf_counter(),
            "peer": self.peer,
            "step": self._current_step(),
            "metrics": metrics.render(),
            "spans": spans,
            "open_spans": tracing.open_spans(),
            "audit": audit.to_json()[-AUDIT_TAIL:],
            "log_tail": log.tail(LOG_TAIL),
            # the step plane's ring (ISSUE 13): the last
            # KF_STEP_TIMELINE_KEEP per-step timelines, so a postmortem
            # can say WHERE IN THE STEP the worker died (an unflushed
            # final timeline names the bucket that never finished)
            "steps": steptrace.get_store().timelines(),
            # the decision ledger's tail (ISSUE 15): a postmortem can
            # name the adaptation the cluster was mid-flip on at death
            # (an unclosed decision with no outcome IS that answer)
            "decisions": decisions.get_ledger().tail(DECISION_TAIL),
            # the resource plane's attribution (ISSUE 16): a worker that
            # died pegged at 100% telemetry CPU is a named finding, not
            # a mystery — the final CPU split rides every snapshot
            "resources": self._resources_doc(),
            # the memory plane's decomposition (ISSUE 17): the last RSS
            # breakdown + headroom trend rides every snapshot, so an
            # OOM-killed worker's final record names the bucket that ate
            # the budget instead of leaving a bare exit code -9
            "memory": self._memory_doc(),
        }
        rec.update(extra)
        return rec

    @staticmethod
    def _resources_doc() -> Optional[dict]:
        try:
            from kungfu_tpu.telemetry import resource

            return resource.get_plane().export()
        # kfcheck: disable=KF400 — snapshot enrichment is best-effort:
        # a failed /proc sweep must cost the record one None field, not
        # the journal the whole snapshot
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def _memory_doc() -> Optional[dict]:
        try:
            from kungfu_tpu.telemetry import memory as tmemory

            plane = tmemory.get_plane()
            plane.maybe_sweep(force=True)
            return plane.export()
        # kfcheck: disable=KF400 — same posture as _resources_doc: the
        # memory tail is enrichment, never the reason a snapshot fails
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def _current_step() -> Optional[float]:
        m = metrics.get_registry().get("kungfu_steps_total")
        try:
            return m.value if m is not None else None
        except ValueError:
            return None  # labelled family — no scalar step

    def snapshot(self, kind: str = "snapshot", **extra) -> None:
        with self._lock:
            if self._closed:
                return
            try:
                self.journal.append(self._snapshot_record(kind, **extra))
            except Exception as e:  # noqa: BLE001 - the recorder must never kill training
                log.warn("flight: snapshot failed: %s", e)

    def dump(self, reason: str = "manual") -> None:
        """On-demand full snapshot (SIGUSR2 / debugging)."""
        self.snapshot(kind="dump", reason=reason)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FlightRecorder":
        if self._thread is not None:
            return self
        self.snapshot(kind="start")

        def loop():
            while not self._stop.wait(self.interval):
                self.snapshot()

        self._thread = threading.Thread(
            target=loop, name="kf-flight", daemon=True
        )
        self._thread.start()
        return self

    def _atexit(self) -> None:
        self.close(reason="atexit")

    def close(self, reason: str = "exit") -> None:
        """Final flush: one terminal record, then the journal closes.
        Idempotent — the first reason wins (sigterm beats atexit)."""
        with self._lock:
            if self._closed:
                return
            try:
                self.journal.append(
                    self._snapshot_record("exit", reason=reason)
                )
            # kfcheck: disable=KF400 — SIGTERM/atexit teardown: the
            # journal append is best-effort and logging can itself fail
            # mid-death; the journal's absence IS the postmortem signal
            except Exception:  # noqa: BLE001
                pass
            self._closed = True
        self._stop.set()
        try:
            atexit.unregister(self._atexit)
        # kfcheck: disable=KF400 — atexit.unregister during interpreter
        # teardown may race module clearing; nothing to report, nowhere
        # reliable left to report it
        except Exception:  # noqa: BLE001
            pass
        self.journal.close()
        if self._fault_file is not None:
            import faulthandler

            try:
                if faulthandler.is_enabled():
                    faulthandler.disable()
                self._fault_file.close()
            except (OSError, ValueError):
                pass
            self._fault_file = None


# -- process-wide recorder ---------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def flight_enabled() -> bool:
    """On when a telemetry dir is set (kfrun injects one) or any
    telemetry feature is enabled; KF_FLIGHT overrides both ways."""
    from kungfu_tpu import knobs

    if knobs.raw(FLIGHT_ENV).strip() != "":  # unset/empty = auto
        return truthy(knobs.raw(FLIGHT_ENV))
    if knobs.raw(DIR_ENV):
        return True
    from kungfu_tpu.telemetry import config

    return bool(config.features())


def start_recorder(
    peer: str = "", directory: Optional[str] = None, **kw
) -> Optional[FlightRecorder]:
    """Start (idempotently) this process's flight recorder in
    ``<KF_TELEMETRY_DIR>/<peer>/``. Returns None when disabled."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            return _recorder
        if directory is None:
            if not flight_enabled():
                return None
            from kungfu_tpu import knobs

            run_dir = knobs.raw(DIR_ENV)
            if not run_dir:
                # self-minted fallback (no runner plumbed a run dir):
                # apply the same retention kfrun does, or every bare
                # run grows the default base forever
                prune_runs()
                run_dir = default_run_dir()
            label = peer or knobs.raw("KF_SELF_SPEC") or str(os.getpid())
            directory = peer_dir(run_dir, label)
        try:
            _recorder = FlightRecorder(directory, peer=peer, **kw).start()
        except OSError as e:
            log.warn("flight: recorder disabled (%s)", e)
            return None
        return _recorder


def get_recorder() -> Optional[FlightRecorder]:
    with _recorder_lock:
        return _recorder


def stop_recorder(reason: str = "stop") -> None:
    global _recorder
    with _recorder_lock:
        rec, _recorder = _recorder, None
    if rec is not None:
        rec.close(reason=reason)


# ---------------------------------------------------------------------------
# runner-side harvesting
# ---------------------------------------------------------------------------


def describe_exit(exit_code: Optional[int]) -> str:
    """'exit code 7' / 'signal SIGKILL (-9)' / 'unknown'."""
    if exit_code is None:
        return "unknown"
    if exit_code < 0:
        try:
            name = signal.Signals(-exit_code).name
        except ValueError:
            return f"signal {-exit_code} ({exit_code})"
        return f"signal {name} ({exit_code})"
    return f"exit code {exit_code}"


def _read_text_tail(path: str, max_bytes: int = 16384) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def harvest_postmortem(
    run_dir: str,
    peer: str,
    exit_code: Optional[int] = None,
    output_tail: Optional[List[str]] = None,
    journal_dir: Optional[str] = None,
) -> dict:
    """Synthesize a dead worker's postmortem from whatever it left
    behind. Never raises on missing/torn artifacts: a worker that died
    before writing anything still yields a postmortem carrying the
    runner-side facts (exit code, output tail). An empty ``run_dir``
    (no KF_TELEMETRY_DIR plumbed) skips disk reads entirely rather
    than probing a structurally wrong location. ``journal_dir``
    overrides the ``<run_dir>/<peer>`` layout for offline forensics on
    a dir that was copied/renamed out of its run."""
    if journal_dir:
        d = journal_dir
        records, errors = read_journal(d)
    elif run_dir:
        d = peer_dir(run_dir, peer)
        records, errors = read_journal(d)
    else:
        d, records, errors = "", [], []
    # scope to the LAST incarnation: a respawned peer appends a fresh
    # meta to the same journal, and the postmortem describes the one
    # that died — an older incarnation's clean exit record must not
    # make this death look flushed
    meta_idx = next(
        (i for i in range(len(records) - 1, -1, -1)
         if records[i].get("kind") == "meta"),
        None,
    )
    meta = records[meta_idx] if meta_idx is not None else None
    incarnation = records[meta_idx:] if meta_idx is not None else records
    snaps = [
        r for r in incarnation
        if r.get("kind") in ("snapshot", "start", "dump", "exit")
    ]
    last = snaps[-1] if snaps else None
    exit_rec = next(
        (r for r in reversed(incarnation) if r.get("kind") == "exit"), None
    )
    now = time.time()
    pm = {
        "kind": "worker_postmortem",
        "peer": str(peer),
        "wall_time": now,
        "exit_code": exit_code,
        "death": describe_exit(exit_code),
        "clean_exit": exit_rec is not None,
        "exit_reason": exit_rec.get("reason") if exit_rec else None,
        "pid": meta.get("pid") if meta else None,
        "started_at": meta.get("wall_time") if meta else None,
        "journal_dir": d if d and (records or os.path.isdir(d)) else None,
        "journal_records": len(records),
        "journal_errors": errors,
        "last_record_at": last.get("wall_time") if last else None,
        "last_record_age_s": (
            round(now - last["wall_time"], 3)
            if last and isinstance(last.get("wall_time"), (int, float))
            else None
        ),
        "last_step": last.get("step") if last else None,
        "last_step_timeline": (
            (last.get("steps") or [None])[-1] if last else None
        ),
        "last_decisions": (last.get("decisions") or []) if last else [],
        "last_resources": last.get("resources") if last else None,
        "last_memory": last.get("memory") if last else None,
        "open_spans": (last.get("open_spans") or {}) if last else {},
        "audit_tail": (last.get("audit") or [])[-10:] if last else [],
        "log_tail": (last.get("log_tail") or [])[-20:] if last else [],
        "process_health": _health_from_metrics(last),
        "faulthandler": (
            _read_text_tail(os.path.join(d, FAULT_NAME)) or None
        ) if d else None,
        "output_tail": list(output_tail or [])[-40:],
    }
    pm["oom_suspected"] = oom_suspected(
        pm.get("last_memory"), exit_code
    )
    return pm


def oom_suspected(last_memory: Optional[dict],
                  exit_code: Optional[int]) -> bool:
    """Did the kernel's OOM killer plausibly end this worker? True when
    the final journalled RSS was within ``KF_MEMORY_OOM_MARGIN`` of the
    measured memory limit, or the death was SIGKILL with the memory
    trend still rising (the OOM killer's exact signature: -9 out of
    nowhere while RSS climbs). A verdict, not a fact — the kernel logs
    the real one in dmesg, which the worker can never report itself."""
    mem = last_memory or {}
    rss = mem.get("rss_bytes")
    limit = mem.get("limit_bytes")
    if rss and limit:
        margin = float(knobs.get("KF_MEMORY_OOM_MARGIN"))
        if rss >= limit * (1.0 - margin):
            return True
    if exit_code == -int(signal.SIGKILL):
        trend = mem.get("trend_bytes_per_s")
        if trend is not None and trend > 0:
            return True
    return False


def _health_from_metrics(snap: Optional[dict]) -> dict:
    """Pull the kungfu_process_* gauges out of a snapshot's exposition
    text — the OOM/fd-leak trend's final point."""
    if not snap or not snap.get("metrics"):
        return {}
    out = {}
    for line in snap["metrics"].splitlines():
        if line.startswith("kungfu_process_") and " " in line:
            name, _, val = line.rpartition(" ")
            try:
                out[name.replace("kungfu_process_", "")] = float(val)
            except ValueError:
                pass
    return out


def append_postmortem(run_dir: str, pm: dict) -> Optional[str]:
    """Durably record a postmortem in <run_dir>/postmortems.jsonl (the
    runner-side black box: it survives the runner exiting too)."""
    try:
        os.makedirs(run_dir, exist_ok=True)
        path = os.path.join(run_dir, POSTMORTEM_NAME)
        with open(path, "a") as f:
            f.write(json.dumps(pm, separators=(",", ":")) + "\n")
        return path
    except OSError as e:
        log.warn("flight: postmortem not persisted: %s", e)
        return None


def read_postmortems(run_dir: str) -> List[dict]:
    out: List[dict] = []
    try:
        with open(os.path.join(run_dir, POSTMORTEM_NAME)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn final line: same contract as the journal
    except OSError:
        pass
    return out


# ---------------------------------------------------------------------------
# rendering (the `info postmortem` timeline)
# ---------------------------------------------------------------------------


def _ts(wall: Optional[float]) -> str:
    if not isinstance(wall, (int, float)):
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(wall))


def render_postmortem(pm: dict) -> str:
    """One postmortem as a human-readable death timeline."""
    peer = pm.get("peer", "?")
    lines = [f"== postmortem: {peer} =="]
    death = pm.get("death") or describe_exit(pm.get("exit_code"))
    when = _ts(pm.get("wall_time"))
    lines.append(f"died: {death}  (harvested {when})")
    if pm.get("clean_exit"):
        lines.append(
            f"exit record present (reason: {pm.get('exit_reason') or '?'}) "
            "— the worker flushed its journal on the way down"
        )
    else:
        lines.append(
            "no exit record — the worker was killed before it could flush "
            "(SIGKILL/OOM/SIGBUS class)"
        )
    if pm.get("started_at") is not None:
        lines.append(
            f"started: {_ts(pm['started_at'])}  pid={pm.get('pid', '?')}"
        )
    age = pm.get("last_record_age_s")
    if pm.get("last_record_at") is not None:
        lines.append(
            f"last journal record: {_ts(pm['last_record_at'])}"
            + (f"  ({age:.1f}s before harvest)" if isinstance(age, (int, float)) else "")
        )
    if pm.get("last_step") is not None:
        lines.append(f"last step: {int(pm['last_step'])}")
    health = pm.get("process_health") or {}
    if health:
        parts = []
        if "rss_bytes" in health:
            parts.append(f"rss={health['rss_bytes'] / (1024 * 1024):.1f}MiB")
        if "open_fds" in health:
            parts.append(f"fds={int(health['open_fds'])}")
        if "threads" in health:
            parts.append(f"threads={int(health['threads'])}")
        if "uptime_seconds" in health:
            parts.append(f"uptime={health['uptime_seconds']:.0f}s")
        if parts:
            lines.append("last self-health: " + " ".join(parts))
    open_spans = pm.get("open_spans") or {}
    if open_spans:
        lines.append("open spans at last snapshot:")
        for thread, stack in sorted(open_spans.items()):
            lines.append(f"  {thread}: {' > '.join(stack)}")
    tl = pm.get("last_step_timeline")
    if tl:
        lines.append("final step timeline (where in the step it died):")
        lines.extend(
            " " + l for l in steptrace.render_timeline(tl, peer=str(peer))
        )
    res = pm.get("last_resources")
    if res:
        from kungfu_tpu.telemetry import resource as _tres

        lines.append("final CPU attribution (resource plane):")
        lines.extend(" " + l for l in _tres.render_worker_resources(res))
    mem = pm.get("last_memory")
    if mem:
        from kungfu_tpu.telemetry import memory as _tmem

        lines.append("final memory attribution (memory plane):")
        lines.extend(" " + l for l in _tmem.render_worker_memory(mem))
    if pm.get("oom_suspected"):
        lines.append(
            "⚠ OOM suspected: final RSS was at the memory limit (or the "
            "death was SIGKILL while RSS was still climbing) — check the "
            "buckets above for the consumer, and dmesg on the host for "
            "the kernel's verdict"
        )
    last_dec = pm.get("last_decisions") or []
    if last_dec:
        lines.append("final adaptation decisions (ledger tail):")
        for rec in last_dec[-4:]:
            lines.append("  " + decisions.render_record(rec))
        unclosed = [r for r in last_dec if r.get("status") != "closed"]
        if unclosed:
            lines.append(
                "  ⚠ unclosed decision(s) above: the cluster was "
                "mid-flip on "
                + ", ".join(str(r.get("kind")) for r in unclosed)
                + " at death — the adaptation never got its outcome "
                "measured"
            )
    audit_tail = pm.get("audit_tail") or []
    if audit_tail:
        lines.append("final audit events:")
        for rec in audit_tail:
            wall = rec.get("wall_time")
            kind = rec.get("kind", "?")
            detail = {
                k: v for k, v in rec.items()
                if k not in ("kind", "wall_time")
            }
            lines.append(f"  {_ts(wall)}  {kind}  {json.dumps(detail, default=str)}")
    log_tail = pm.get("log_tail") or []
    if log_tail:
        lines.append("log tail:")
        lines.extend(f"  {l}" for l in log_tail)
    fh = pm.get("faulthandler")
    if fh and fh.strip():
        lines.append("faulthandler:")
        lines.extend(f"  {l}" for l in fh.strip().splitlines())
    out_tail = pm.get("output_tail") or []
    if out_tail:
        lines.append("output tail (runner-captured stdout/stderr):")
        lines.extend(f"  {l}" for l in out_tail)
    errs = pm.get("journal_errors") or []
    if errs:
        lines.append(
            "journal read notes: " + "; ".join(errs)
            + " (complete records up to the tear were recovered)"
        )
    if not pm.get("journal_records"):
        lines.append(
            "journal: empty or missing — timeline built from "
            "runner-side capture only"
        )
    return "\n".join(lines)


def harvest_peer_dir(path: str) -> Optional[dict]:
    """Harvest one peer journal dir directly (exit code unknown —
    offline forensics, not a live runner). None when the dir holds no
    journal."""
    path = os.path.normpath(path)
    if not (
        os.path.exists(os.path.join(path, JOURNAL_NAME))
        or os.path.exists(os.path.join(path, JOURNAL_PREV_NAME))
    ):
        return None
    records, _ = read_journal(path)
    meta = next((r for r in records if r.get("kind") == "meta"), None)
    label = (meta or {}).get("peer") or os.path.basename(path)
    # harvest against THIS dir, not a re-derivation from the label: a
    # dir copied/renamed for offline forensics must still harvest
    return harvest_postmortem("", label, journal_dir=path)


def harvest_run_dir(run_dir: str) -> List[dict]:
    """Postmortems for an entire run dir: the runner's durable
    postmortems.jsonl entries, MERGED with fresh harvests of peer
    journals the runner never got to (e.g. the runner itself was
    killed mid-recovery). With no jsonl at all, every journaled peer
    is harvested (exit codes unknown); with one, uncovered peers are
    added only when their journal lacks a clean exit record — a
    normally-completed worker is not a death."""
    pms = list(read_postmortems(run_dir))
    covered = {sanitize_label(pm.get("peer", "")) for pm in pms}
    try:
        entries = sorted(os.scandir(run_dir), key=lambda e: e.name)
    except OSError:
        return pms
    for e in entries:
        if not e.is_dir() or e.name in covered:
            continue
        pm = harvest_peer_dir(e.path)
        if pm is None:
            continue
        if covered and pm.get("clean_exit"):
            continue
        pms.append(pm)
    return pms
