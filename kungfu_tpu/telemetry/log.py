"""Structured, rank-prefixed leveled logger — the host plane's one
logging surface.

Capability parity: srcs/go/log/logger.go (DEBUG/INFO/WARN/ERROR, level
from the environment, optional redirection) + the runner's colored rank
prefixes (utils/iostream xterm coloring) — extended with structured
key=value fields:

    log.info("resize landed", old=4, new=3)
    # 12:00:01 [I] kungfu[0/4] resize landed old=4 new=3

Level comes from ``KF_LOG_LEVEL`` (falling back to the reference's
``KF_CONFIG_LOG_LEVEL``). The per-worker prefix comes from
``KF_LOG_PREFIX`` (set by the runner) or, under a bare worker, from
``KF_SELF_SPEC``. ``echo()`` is the CLI escape hatch: raw, unleveled
stdout output for user-facing surfaces (benchmark results, server
banners) that must never be filtered by the log level — and the reason
``print()`` stays banned everywhere outside runner/cli.py and info/
(see tests/test_no_bare_print.py).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional, TextIO

from kungfu_tpu import knobs

LEVELS = {"DEBUG": 10, "INFO": 20, "WARN": 30, "WARNING": 30, "ERROR": 40, "OFF": 100}
_COLORS = [31, 32, 33, 34, 35, 36]  # red..cyan, cycled by rank

_lock = threading.Lock()
_state = {"level": None, "out": None, "prefix": None}

# bounded tail of emitted lines, independent of where `out` points: the
# flight recorder journals it so a crashed worker's last words survive
# even when its stderr pipe died with the runner
TAIL_LINES = 200
_tail: "deque[str]" = deque(maxlen=TAIL_LINES)


def _level() -> int:
    if _state["level"] is None:
        name = (
            knobs.raw("KF_LOG_LEVEL") or knobs.raw("KF_CONFIG_LOG_LEVEL")
        ).upper()
        _state["level"] = LEVELS.get(name, 20)
    return _state["level"]


def set_level(name: str) -> None:
    with _lock:
        _state["level"] = LEVELS.get(name.upper(), 20)


def set_output(f: Optional[TextIO]) -> None:
    """Redirect log output (parity: logger.go output redirection)."""
    with _lock:
        _state["out"] = f


def reset() -> None:
    """Re-read level/prefix from the environment (tests)."""
    with _lock:
        _state["level"] = None
        _state["prefix"] = None


def _prefix() -> str:
    if _state["prefix"] is None:
        p = knobs.raw("KF_LOG_PREFIX") or knobs.raw("KF_SELF_SPEC")
        if p and sys.stderr.isatty():
            try:
                rank = int(p.split("/")[0])
                p = f"\x1b[{_COLORS[rank % len(_COLORS)]}m[{p}]\x1b[0m"
            except ValueError:
                p = f"[{p}]"
        elif p:
            p = f"[{p}]"
        _state["prefix"] = p
    return _state["prefix"]


def _emit(level_name: str, level: int, msg: str, args: tuple, fields: dict) -> None:
    if level < _level():
        return
    out = _state["out"] or sys.stderr
    if args:
        msg = msg % args
    if fields:
        kv = " ".join(f"{k}={v}" for k, v in fields.items())
        msg = f"{msg} {kv}" if msg else kv
    ts = time.strftime("%H:%M:%S")
    pre = _prefix()
    with _lock:
        _tail.append(f"{ts} [{level_name[0]}] {msg}")
        try:
            out.write(f"{ts} [{level_name[0]}] kungfu{pre} {msg}\n")
            out.flush()
        except (ValueError, OSError):
            pass  # closed stream at interpreter teardown


def tail(n: Optional[int] = None) -> List[str]:
    """The most recent emitted log lines (level-filtered, un-colored)."""
    with _lock:
        lines = list(_tail)
    return lines if n is None else lines[-n:]


def clear_tail() -> None:
    with _lock:
        _tail.clear()


def debug(msg: str, *args, **fields) -> None:
    _emit("DEBUG", 10, msg, args, fields)


def info(msg: str, *args, **fields) -> None:
    _emit("INFO", 20, msg, args, fields)


def warn(msg: str, *args, **fields) -> None:
    _emit("WARN", 30, msg, args, fields)


warning = warn


def error(msg: str, *args, **fields) -> None:
    _emit("ERROR", 40, msg, args, fields)


def echo(msg: str = "", *, err: bool = False) -> None:
    """Raw CLI-facing output (results, banners): bypasses levels and
    prefixes, never filtered. The lint-compliant replacement for print()
    in CLI surfaces outside runner/cli.py and info/."""
    out = sys.stderr if err else sys.stdout
    try:
        out.write(str(msg) + "\n")
        out.flush()
    except (ValueError, OSError):
        pass
