"""Cluster observability plane: runner-side telemetry aggregation.

ISSUE 2 tentpole. PR 1 gave every worker its own ``/metrics`` +
``/trace`` + ``/audit`` endpoint on ``peer_port + 10000``; this module
is the runner-side :class:`TelemetryAggregator` that periodically
scrapes every live worker (it learns the cluster from the elastic
watcher's Stages), merges the results into one cluster snapshot, and
serves it from the watcher's debug endpoint:

- ``/cluster/metrics`` — federated Prometheus exposition, every sample
  labelled ``peer="host:port"`` (collisions become ``exported_peer``,
  the Prometheus federation rule);
- ``/cluster/trace``   — all workers' Chrome traces merged onto the
  runner's timeline, per-peer clock offsets estimated NTP-style from
  the scrape round trip (each response carries the worker's monotonic
  clock in an ``X-KF-Perf-Now-Us`` header; offset error <= RTT/2, and
  the stored offset only improves as lower-RTT scrapes land);
- ``/cluster/health``  — JSON: per-peer step rate, step-time p50/p99,
  bytes tx/rx, last-scrape age, straggler score/flag;
- ``/cluster/links``   — the k×k link matrix (ISSUE 6): every worker's
  ``kungfu_link_*`` row (passive per-destination EWMA bandwidth/latency
  from real collective traffic) merged into one document, with the
  slowest edge called out — the input signal for straggler-adaptive
  topology re-planning;
- ``/cluster/steps``   — the step plane (ISSUE 13): every worker's
  ``/steptrace`` ring merged per (session_epoch, round) with the same
  clock offsets, each step carrying its elected critical (peer, bucket,
  edge) chain, overlap fraction and queue-delay fraction — "which
  bucket on which peer over which edge was the long pole" as data;
- ``/cluster/decisions`` — the decision plane (ISSUE 15): every
  worker's ``/decisions`` ledger merged into one NTP-aligned causal
  timeline — each adaptation (strategy/wire vote, re-plan, mode flip,
  resize) with its trigger, predicted gain and MEASURED outcome
  (realized gain, verdict, regression flag) — "the cluster adapted;
  did it help?" as data;
- ``/cluster/resources`` — the resource plane (ISSUE 16): every
  worker's ``/resources`` per-thread CPU attribution merged into one
  view with the saturated (compute-bound) peers elected — the input
  that lets straggler events carry ``cause=compute`` vs ``network``
  and lets re-planning clamp predicted gains by the compute floor;
- ``/cluster/memory`` — the memory plane (ISSUE 17): every worker's
  ``/memory`` bucket decomposition, headroom forecast and thrash flag
  merged into one view with the minimum-headroom peer elected — the
  grow-gate input the unattended autoscaler consults and the feed for
  ``cause=memory`` straggler attribution.

On top of the snapshot the aggregator runs straggler detection
(:mod:`~kungfu_tpu.telemetry.straggler`): rolling per-peer step-time
medians, robust-z flagging of slow peers and RTT outliers. Flags are
published three ways so every consumer sees the same truth:
``kungfu_cluster_*`` gauges (the aggregator's own registry, appended to
``/cluster/metrics``), ``telemetry.audit`` events on flag transitions,
and adaptation-facing signals (``monitor.cluster_health()`` →
``PolicyContext.metrics``) that let a ``BasePolicy`` trigger a resize
or strategy switch on skew.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
import urllib.request
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import audit, log, metrics, promparse
from kungfu_tpu.telemetry import decisions as tdecisions
from kungfu_tpu.telemetry import link as tlink
from kungfu_tpu.telemetry import memory as tmemory
from kungfu_tpu.telemetry import resource as tresource
from kungfu_tpu.telemetry import steptrace as tstep
from kungfu_tpu.telemetry import straggler as tstraggler
from kungfu_tpu.telemetry.straggler import StragglerScorer

# metric families scraped off each worker's exposition
STEPS_TOTAL = "kungfu_steps_total"
STEP_SECONDS = "kungfu_step_duration_seconds"
COLLECTIVE_SECONDS = "kungfu_collective_latency_seconds"
EGRESS_BYTES = "kungfu_egress_bytes_total"
INGRESS_BYTES = "kungfu_ingress_bytes_total"
PEER_RTT = "kungfu_peer_rtt_seconds"
# link-plane families (ISSUE 6): each worker's exposition carries its
# own ROW of the link matrix; the aggregator assembles the k x k view
LINK_BW = "kungfu_link_bandwidth_bytes_per_second"
LINK_LAT = "kungfu_link_latency_seconds"
LINK_BYTES = "kungfu_link_tx_bytes_total"
LINK_MSGS = "kungfu_link_tx_messages_total"
# active-ring families (ISSUE 14): each worker exports its position in
# the current segmented-ring order and its successor edge, so
# /cluster/links can render the ACTIVE ring next to the measured matrix
RING_POS = "kungfu_topology_ring_position"
RING_NEXT = "kungfu_topology_ring_next"

CLOCK_HEADER = "X-KF-Perf-Now-Us"

# step plane (ISSUE 13): how many merged steps the aggregator retains
# for /cluster/steps and the info-top critical columns, and how many
# consecutive merged steps the same (peer, edge) must dominate before a
# `step_critical_path` audit event fires (matches StragglerPolicy's
# default patience — one noisy step is weather, three is a bottleneck)
STEP_KEEP = 64
STEP_CRIT_PATIENCE = 3

DEFAULT_INTERVAL = 5.0
INTERVAL_ENV = "KF_CLUSTER_SCRAPE_INTERVAL"
HEALTH_URL_ENV = "KF_CLUSTER_HEALTH_URL"


def scrape_interval() -> float:
    v = float(knobs.get(INTERVAL_ENV))
    return v if v > 0 else DEFAULT_INTERVAL


class _HistSnapshot:
    """Cumulative histogram state parsed from one exposition page."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds, counts, total_sum, count):
        self.bounds = bounds  # sorted finite bucket bounds
        self.counts = counts  # cumulative counts aligned to bounds + [+Inf]
        self.sum = total_sum
        self.count = count

    @classmethod
    def from_samples(cls, samples, family) -> Optional["_HistSnapshot"]:
        buckets = []
        total_sum = total_count = None
        for s in samples:
            if s.name == family + "_bucket":
                le = s.labels_dict().get("le", "")
                bound = math.inf if le == "+Inf" else float(le)
                buckets.append((bound, s.value))
            elif s.name == family + "_sum":
                total_sum = s.value
            elif s.name == family + "_count":
                total_count = s.value
        if not buckets or total_count is None:
            return None
        buckets.sort(key=lambda b: b[0])
        bounds = [b for b, _ in buckets if b != math.inf]
        counts = [c for _, c in buckets]
        return cls(bounds, counts, total_sum or 0.0, total_count)

    def delta(self, prev: Optional["_HistSnapshot"]) -> "_HistSnapshot":
        """Windowed histogram since `prev` (same buckets), or self."""
        if (
            prev is None
            or prev.bounds != self.bounds
            or prev.count > self.count  # worker restarted: counters reset
        ):
            return self
        return _HistSnapshot(
            self.bounds,
            [c - p for c, p in zip(self.counts, prev.counts)],
            self.sum - prev.sum,
            self.count - prev.count,
        )

    def quantile(self, q: float) -> float:
        """Interpolated quantile (histogram_quantile semantics)."""
        total = self.counts[-1] if self.counts else 0
        if total <= 0:
            return math.nan
        rank = q * total
        prev_cum = 0.0
        for i, cum in enumerate(self.counts):
            if cum >= rank and cum > prev_cum:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else math.inf
                if hi == math.inf:
                    return lo
                frac = (rank - prev_cum) / (cum - prev_cum)
                return lo + (hi - lo) * frac
            prev_cum = cum
        return self.bounds[-1] if self.bounds else math.nan


class PeerState:
    """Everything the aggregator knows about one scrape target."""

    def __init__(self, label: str, url: str):
        self.label = label
        self.url = url.rstrip("/")
        self.last_ok: Optional[float] = None  # monotonic
        self.last_error: str = ""
        self.scrapes = 0
        self.errors = 0
        # a scrape thread is working this peer; the next sweep skips it
        # rather than interleave prev/current swaps on the same state
        self.inflight = False
        self.rtt_s = math.inf  # last scrape round trip
        self.best_rtt_s = math.inf
        self.clock_offset_us: Optional[float] = None
        self.metrics_text = ""
        # step accounting across scrapes
        self.steps_total: Optional[float] = None
        self.step_hist: Optional[_HistSnapshot] = None
        self.prev_steps: Optional[float] = None
        self.prev_hist: Optional[_HistSnapshot] = None
        self.prev_t: Optional[float] = None
        self.step_rate: Optional[float] = None
        self.step_p50: Optional[float] = None
        self.step_p99: Optional[float] = None
        # collective-wait accounting: in SYNCHRONOUS training every
        # peer's wall-clock step converges to the straggler's (the fast
        # peers spend the difference waiting inside collectives), so the
        # straggler signal is compute time = step - collective wait
        self.coll_sum: Optional[float] = None
        self.coll_count: Optional[float] = None
        self.prev_coll_sum: Optional[float] = None
        self.compute_mean: Optional[float] = None
        self.bytes_tx: Optional[float] = None
        self.bytes_rx: Optional[float] = None
        self.reported_rtt: Optional[float] = None  # median of its probes
        # this peer's link-matrix row, parsed off its last exposition:
        # {dst: {"bw":, "latency_s":, "tx_bytes":, "tx_messages":}}
        self.links: Dict[str, dict] = {}
        # active-ring view (ISSUE 14): this peer's position in the
        # current ring order and its successor peer label
        self.ring_pos: Optional[int] = None
        self.ring_next: Optional[str] = None


class TelemetryAggregator:
    """Scrapes every worker's telemetry endpoint, keeps the merged
    cluster snapshot, publishes straggler signals."""

    def __init__(
        self,
        interval: Optional[float] = None,
        timeout: float = 2.0,
        registry: Optional[metrics.Registry] = None,
        scorer: Optional[StragglerScorer] = None,
        rtt_scorer: Optional[StragglerScorer] = None,
    ):
        self.interval = interval if interval is not None else scrape_interval()
        self.timeout = timeout
        self._peers: Dict[str, PeerState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scorer = scorer or StragglerScorer()
        # RTT outliers: interconnect trouble shows up before step times
        # do; a laxer z with a hard ratio floor suits the heavier tail
        self.rtt_scorer = rtt_scorer or StragglerScorer(
            window=8, z_threshold=3.0, ratio_threshold=2.0
        )
        self._flagged: set = set()
        self._rtt_flagged: set = set()
        # the measured cause behind each currently-flagged straggler
        # (network/compute/unknown), classified once at the flag
        # transition — /cluster/health serves it so `info top` renders
        # the same cause the audit event recorded
        self._causes: Dict[str, str] = {}
        self._scraped_at: Optional[float] = None  # wall time of last sweep
        # crash forensics (ISSUE 3): postmortems harvested by the
        # watcher, served at /cluster/postmortem. Deliberately NOT keyed
        # off the scrape membership — dead peers leave the cluster, and
        # their postmortems are the entire point. Bounded overall.
        self._postmortems: "collections.deque" = collections.deque(maxlen=64)
        # a PRIVATE registry by default, not the process-global one: the
        # runner's own transport metrics carry peer labels that mean "a
        # remote peer of the runner" — mixing them into the federated
        # page (where peer means "the scraped worker") would make the
        # label ambiguous. /cluster/metrics appends this exposition.
        reg = registry if registry is not None else metrics.Registry()
        self.registry = reg
        self._g_step_rate = reg.gauge(
            "kungfu_cluster_step_rate",
            "Steps/sec per peer, from scrape-to-scrape deltas",
            ("peer",),
        )
        self._g_step_time = reg.gauge(
            "kungfu_cluster_step_time_seconds",
            "Windowed step-time quantiles per peer",
            ("peer", "quantile"),
        )
        self._g_score = reg.gauge(
            "kungfu_cluster_straggler_score",
            "Robust z-score of each peer's step time vs the cluster median",
            ("peer",),
        )
        self._g_stragglers = reg.gauge(
            "kungfu_cluster_stragglers",
            "Number of peers currently flagged as stragglers",
        )
        self._g_age = reg.gauge(
            "kungfu_cluster_scrape_age_seconds",
            "Seconds since the last successful scrape per peer",
            ("peer",),
        )
        self._c_scrapes = reg.counter(
            "kungfu_cluster_scrapes_total",
            "Aggregator scrape sweeps completed",
        )
        self._c_errors = reg.counter(
            "kungfu_cluster_scrape_errors_total",
            "Failed peer scrapes",
            ("peer",),
        )
        # step plane (ISSUE 13): merged per-step critical-path records,
        # refreshed from every worker's /steptrace on each sweep
        self._steps: "collections.deque" = collections.deque(maxlen=STEP_KEEP)
        self._steps_at: Optional[float] = None  # monotonic, last refresh
        self._steps_last: Optional[Tuple[int, int]] = None  # newest (e, r)
        self._crit_streak: Tuple[Optional[Tuple[str, str]], int] = (None, 0)
        # serializes whole refreshes: the sweep thread and an HTTP
        # handler's inline staleness refresh both call _refresh_steps,
        # and two concurrent runs would compute `fresh` against the
        # same _steps_last — duplicating steps and double-counting the
        # patience streak. NOT self._lock: a refresh spans HTTP fetches.
        self._steps_refresh_lock = threading.Lock()
        # decision plane (ISSUE 15): every worker's /decisions ledger
        # merged into one causal timeline, keyed (peer, seq, open wall
        # time) so a later scrape of the SAME record (now closed, or
        # regressed) updates it in place instead of duplicating it —
        # while a RESPAWNED worker's fresh ledger (seq restarting at 0
        # on the same label) cannot overwrite the dead incarnation's
        # records: its records carry new open stamps. Bounded like a
        # ring: oldest merged entries drop past KF_DECISION_KEEP.
        self._decisions: Dict[Tuple[str, int, float], dict] = {}
        self._decisions_at: Optional[float] = None  # monotonic
        _dkeep = int(knobs.get("KF_DECISION_KEEP"))
        self._decisions_keep = _dkeep if _dkeep > 0 else 64
        self._decisions_refresh_lock = threading.Lock()
        # resource plane (ISSUE 16): the latest merged cluster view of
        # every worker's /resources document — a CURRENT-STATE view
        # (like health), so each refresh REPLACES it wholesale: a dead
        # peer's frozen saturation flag steering straggler causes or
        # the replan clamp hours later would be worse than no data
        self._resources: dict = {}
        self._resources_at: Optional[float] = None  # monotonic
        self._resources_refresh_lock = threading.Lock()
        # memory plane (ISSUE 17): same current-state contract as the
        # resource plane — each refresh replaces the merged view
        self._memory: dict = {}
        self._memory_at: Optional[float] = None  # monotonic
        self._memory_refresh_lock = threading.Lock()

        # the aggregator's own tracked state is a long-lived buffer
        # owner too: account it under the runner's `telemetry` bucket
        # (weakref — the registry must never pin a stopped aggregator)
        def _footprint(ref=weakref.ref(self)) -> Optional[int]:
            agg = ref()
            return agg.footprint_bytes() if agg is not None else None

        self._mem_acct = tmemory.register_accountant(
            "aggregator", "telemetry", _footprint
        )
        self._g_step_overlap = reg.gauge(
            "kungfu_step_overlap_ratio",
            "Latest merged step's overlap fraction: scheduler-busy comm "
            "time hidden under caller compute / total comm time",
        )
        self._g_step_critical = reg.gauge(
            "kungfu_step_critical_seconds",
            "Latest merged step's critical-path blocking seconds, "
            "labelled with the elected (peer, edge)",
            ("peer", "edge"),
        )

    # -- membership ----------------------------------------------------
    @staticmethod
    def targets_for_workers(workers) -> List[Tuple[str, str]]:
        """PeerIDs -> (label, telemetry base URL) on peer_port+10000."""
        out = []
        for w in workers:
            port = w.port + 10000
            if port > 65535:
                # mirror of the worker-side OverflowError guard in
                # peer.py — but say so: an invisible peer can never be
                # flagged, and a silent skip reads as a healthy cluster
                log.warn(
                    "cluster: %s has no telemetry port (peer_port+10000 "
                    "> 65535); excluded from the cluster plane", w,
                )
                continue
            out.append((str(w), f"http://{w.host}:{port}"))
        return out

    def set_peers(self, targets: Sequence[Tuple[str, str]]) -> None:
        """Replace the scrape set (the watcher calls this on every
        Stage). Surviving peers keep their scrape history and clock
        offsets; departed peers drop out of the scorers so they can't
        skew the population as ghosts."""
        with self._lock:
            fresh: Dict[str, PeerState] = {}
            for label, url in targets:
                st = self._peers.get(label)
                if st is None or st.url != url.rstrip("/"):
                    st = PeerState(label, url)
                fresh[label] = st
            self._peers = fresh
        live = list(fresh)
        self.scorer.forget(live)
        self.rtt_scorer.forget(live)
        self._flagged &= set(live)
        self._rtt_flagged &= set(live)
        # per-peer gauge children follow the membership (bounded
        # cardinality across elastic resizes)
        for g in (self._g_step_rate, self._g_step_time, self._g_score,
                  self._g_age):
            g.clear_children()

    def peers(self) -> List[PeerState]:
        with self._lock:
            return list(self._peers.values())

    # -- scraping ------------------------------------------------------
    def _fetch(
        self, st: PeerState, path: str, record_rtt: bool = True
    ) -> Tuple[bytes, dict]:
        """GET one peer endpoint. record_rtt=False for the on-demand
        trace/audit pulls: their multi-MB bodies measure transfer time,
        not the network — writing that into rtt_s would paint a phantom
        'network problem' in /cluster/health whenever someone looks at
        traces (the clock-offset update stays safe either way: it only
        accepts estimates that BEAT the best RTT seen)."""
        t0 = time.perf_counter()
        with urllib.request.urlopen(st.url + path, timeout=self.timeout) as r:
            body = r.read()
            clock = r.headers.get(CLOCK_HEADER)
        t1 = time.perf_counter()
        rtt = t1 - t0
        if record_rtt:
            st.rtt_s = rtt
        if clock is not None:
            # NTP midpoint: assume the worker stamped the header halfway
            # through the round trip. perf_counter epochs are fixed per
            # process, so the TRUE offset is constant — keep the estimate
            # from the lowest-RTT scrape ever seen (its error bound,
            # RTT/2, is the tightest)
            if rtt <= st.best_rtt_s or st.clock_offset_us is None:
                st.best_rtt_s = rtt
                mid_us = (t0 + t1) / 2.0 * 1e6
                try:
                    st.clock_offset_us = mid_us - float(clock)
                except ValueError:
                    pass
        return body, {"rtt_s": rtt}

    def _scrape_peer(self, st: PeerState) -> None:
        now = time.monotonic()
        try:
            body, _ = self._fetch(st, "/metrics")
        except (OSError, ValueError) as e:
            st.last_error = str(e)
            st.errors += 1
            self._c_errors.labels(st.label).inc()
            # a peer that stopped answering must not keep serving its
            # last-known-healthy numbers: a dashboard or policy reading
            # step_rate would see a live peer hours after it died. The
            # delta baselines reset too, so a comeback doesn't compute a
            # rate smeared across the outage — and its SCORER series
            # goes with it: a frozen window would keep the peer flagged
            # (or keep skewing the population) off hours-old data, and
            # straggler_cleared would never fire. The window rebuilds
            # within min_samples scrapes if the endpoint comes back.
            st.step_rate = st.step_p50 = st.step_p99 = None
            st.compute_mean = None
            st.prev_steps = st.prev_t = None
            st.prev_hist = None
            st.prev_coll_sum = None
            # the CUMULATIVE snapshots go too, not just the prev_*
            # baselines: the success path copies current into prev_*
            # before overwriting, so a surviving pre-outage snapshot
            # would become the baseline for a possibly-restarted worker
            # — cross-epoch deltas (negative buckets, garbage quantiles)
            # once the new epoch's counts pass the old ones
            st.steps_total = None
            st.step_hist = None
            st.coll_sum = None
            # the frozen exposition page goes too: cluster_metrics()
            # federates whatever is stored, and a dead peer's last page
            # would keep it looking alive on the Prometheus view
            st.metrics_text = ""
            # and its link row: a dead peer's frozen bandwidth estimates
            # would keep steering topology re-planning hours later
            st.links = {}
            st.ring_pos = st.ring_next = None
            self.scorer.drop(st.label)
            self.rtt_scorer.drop(st.label)
            return
        st.scrapes += 1
        st.last_ok = now
        st.last_error = ""
        st.metrics_text = body.decode(errors="replace")
        samples = promparse.parse_text(st.metrics_text)
        st.prev_steps, st.prev_hist = st.steps_total, st.step_hist
        st.prev_coll_sum = st.coll_sum
        st.steps_total = promparse.sample_value(samples, STEPS_TOTAL)
        st.step_hist = _HistSnapshot.from_samples(samples, STEP_SECONDS)
        tx = rx = None
        coll_sum = None
        rtts = []
        links: Dict[str, dict] = {}
        ring_pos = None
        ring_next = None
        _link_key = {
            LINK_BW: "bw", LINK_LAT: "latency_s",
            LINK_BYTES: "tx_bytes", LINK_MSGS: "tx_messages",
        }
        for s in samples:
            if s.name == EGRESS_BYTES:
                tx = (tx or 0.0) + s.value
            elif s.name == INGRESS_BYTES:
                rx = (rx or 0.0) + s.value
            elif s.name == COLLECTIVE_SECONDS + "_sum":
                # summed across the per-kind label children: total
                # seconds this worker has spent inside host collectives
                coll_sum = (coll_sum or 0.0) + s.value
            elif s.name == PEER_RTT and math.isfinite(s.value) and s.value > 0:
                rtts.append(s.value)
            elif s.name == RING_POS:
                ring_pos = int(s.value)
            elif s.name == RING_NEXT and s.value:
                ring_next = s.labels_dict().get("dst") or ring_next
            elif s.name in _link_key:
                dst = s.labels_dict().get("dst")
                if dst:
                    links.setdefault(dst, {})[_link_key[s.name]] = s.value
        st.links = links
        st.ring_pos = ring_pos
        st.ring_next = ring_next
        st.coll_sum = coll_sum
        st.bytes_tx, st.bytes_rx = tx, rx
        st.reported_rtt = sorted(rtts)[len(rtts) // 2] if rtts else None
        # step rate + windowed quantiles from scrape-to-scrape deltas
        if (
            st.steps_total is not None
            and st.prev_steps is not None
            and st.prev_t is not None
            and now > st.prev_t
            and st.steps_total >= st.prev_steps  # restart resets to 0
        ):
            st.step_rate = (st.steps_total - st.prev_steps) / (now - st.prev_t)
        st.prev_t = now
        if st.step_hist is not None:
            window = st.step_hist.delta(st.prev_hist)
            if window.count > 0:
                st.step_p50 = window.quantile(0.50)
                st.step_p99 = window.quantile(0.99)
                step_mean = window.sum / window.count
                # score COMPUTE time (step minus collective wait) when
                # the worker publishes collective latencies: under
                # synchronous training wall-clock step times converge to
                # the slowest peer's, and the straggler is the one whose
                # time went to compute instead of waiting
                compute = step_mean
                if (
                    st.coll_sum is not None
                    and st.prev_coll_sum is not None
                    and st.coll_sum >= st.prev_coll_sum  # restart guard
                ):
                    wait = (st.coll_sum - st.prev_coll_sum) / window.count
                    compute = max(step_mean - wait, 0.0)
                st.compute_mean = compute
                self.scorer.observe(st.label, compute)
        # outlier scoring uses ONLY the worker-published probe RTTs
        # (kungfu_peer_rtt_seconds): the HTTP scrape duration measures
        # TCP setup + body transfer, an order of magnitude above a probe
        # RTT — mixing the two in one population would flag any peer
        # that simply hasn't probed yet. The scrape RTT stays visible in
        # health as rtt_ms, it just doesn't vote.
        if st.reported_rtt is not None:
            self.rtt_scorer.observe(st.label, st.reported_rtt)

    def scrape_once(self) -> dict:
        """One sweep over every target (parallel, bounded by the HTTP
        timeout), then re-score stragglers and publish. Returns the
        fresh health snapshot. A peer whose previous scrape thread is
        still in flight (a server dripping bytes under the timeout) is
        skipped this sweep — two threads swapping the same peer's
        prev/current baselines would corrupt its rates."""

        def scrape_and_clear(st: PeerState) -> None:
            try:
                self._scrape_peer(st)
            finally:
                st.inflight = False

        threads = []
        for st in self.peers():
            if st.inflight:
                continue
            st.inflight = True
            threads.append(
                threading.Thread(
                    target=scrape_and_clear, args=(st,), daemon=True
                )
            )
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.timeout + 1.0)
        self._c_scrapes.inc()
        self._scraped_at = time.time()
        try:
            self._refresh_steps()
        except Exception as e:  # noqa: BLE001 - the sweep must outlive a bad step merge
            log.warn("cluster: step-plane refresh failed: %s", e)
        try:
            self._refresh_decisions()
        except Exception as e:  # noqa: BLE001 - the sweep must outlive a bad merge
            log.warn("cluster: decision-plane refresh failed: %s", e)
        try:
            self._refresh_resources()
        except Exception as e:  # noqa: BLE001 - the sweep must outlive a bad merge
            log.warn("cluster: resource-plane refresh failed: %s", e)
        try:
            self._refresh_memory()
        except Exception as e:  # noqa: BLE001 - the sweep must outlive a bad merge
            log.warn("cluster: memory-plane refresh failed: %s", e)
        self._publish()
        return self.cluster_health()

    def _publish(self) -> None:
        scores = self.scorer.scores()
        rtt_scores = self.rtt_scorer.scores()
        flagged = {p for p, s in scores.items() if s.flagged}
        rtt_flagged = {p for p, s in rtt_scores.items() if s.flagged}
        cluster_median = self.scorer.cluster_median()
        # rebuild the per-peer gauge children every sweep: set() without
        # a clear would leave a dead peer's last-known-healthy values
        # frozen in the exposition forever (the JSON view nulls them,
        # and the metrics view must agree)
        for g in (self._g_step_rate, self._g_step_time, self._g_score):
            g.clear_children()
        for st in self.peers():
            if st.step_rate is not None:
                self._g_step_rate.labels(st.label).set(st.step_rate)
            if st.step_p50 is not None:
                self._g_step_time.labels(st.label, "0.5").set(st.step_p50)
            if st.step_p99 is not None:
                self._g_step_time.labels(st.label, "0.99").set(st.step_p99)
            sc = scores.get(st.label)
            if sc is not None:
                self._g_score.labels(st.label).set(sc.score)
            if st.last_ok is not None:
                self._g_age.labels(st.label).set(
                    time.monotonic() - st.last_ok
                )
        self._g_stragglers.set(len(flagged))
        # audit on TRANSITIONS only: the log answers "when did peer X
        # become slow", not "is it still slow every 5 seconds"
        newly_flagged = sorted(flagged - self._flagged)
        links_doc = None
        steps: List[dict] = []
        resources: Optional[dict] = None
        memory: Optional[dict] = None
        if newly_flagged:
            # measured attribution for the event (ISSUE 13 satellite +
            # ISSUE 16/17 causes): the step plane's elected edge when
            # this peer was recently critical, else the memory plane's
            # thrash flag, the resource plane's saturation view, else
            # the slowest link touching it — all inputs computed once
            # per transition batch, never per peer
            links_doc = tlink.merge_matrix(
                {st.label: st.links for st in self.peers()},
                copy_edges=False,
            )
            with self._lock:
                steps = list(self._steps)
                resources = self._resources or None
                memory = self._memory or None
        for peer in newly_flagged:
            sc = scores[peer]
            cause, edge = tstraggler.classify_cause(
                peer, steps, links_doc, resources, memory
            )
            self._causes[peer] = cause
            log.warn(
                "cluster: straggler detected: %s step_time=%.1fms "
                "(cluster median %.1fms, z=%.1f, cause=%s, blocking edge %s)",
                peer, sc.value * 1e3, (cluster_median or 0) * 1e3, sc.score,
                cause,
                "->".join(str(e) for e in edge) if edge else "unknown",
            )
            audit.record_event(
                "straggler",
                peer=peer,
                trigger="cluster_scrape",
                score=round(sc.score, 2),
                step_time_ms=round(sc.value * 1e3, 3),
                cluster_median_ms=round((cluster_median or 0) * 1e3, 3),
                blocking_edge=edge,
                cause=cause,
            )
        for peer in sorted(self._flagged - flagged):
            self._causes.pop(peer, None)
            audit.record_event(
                "straggler_cleared", peer=peer, trigger="cluster_scrape"
            )
        for peer in sorted(rtt_flagged - self._rtt_flagged):
            sc = rtt_scores[peer]
            audit.record_event(
                "rtt_outlier",
                peer=peer,
                trigger="cluster_scrape",
                score=round(sc.score, 2),
                rtt_ms=round(sc.value * 1e3, 3),
            )
        self._flagged = flagged
        self._rtt_flagged = rtt_flagged

    # -- background loop -----------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.scrape_once()
                except Exception as e:  # noqa: BLE001 - the plane must outlive a bad sweep
                    log.warn("cluster: scrape sweep failed: %s", e)

        self._thread = threading.Thread(
            target=loop, name="kf-cluster-scrape", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(self.timeout + 1.0)
        self._mem_acct.close()

    # -- merged views ---------------------------------------------------
    def cluster_metrics(self) -> str:
        """Federated exposition of every worker's last-scraped /metrics,
        plus the aggregator's own registry (the kungfu_cluster_* gauges
        and scrape counters — already peer-labelled, no injection) so
        one Prometheus target sees the whole plane."""
        pages: List[Tuple[Optional[str], str]] = [
            (st.label, st.metrics_text)
            for st in self.peers()
            if st.metrics_text
        ]
        pages.append((None, self.registry.render()))
        return promparse.merge_expositions(pages)

    def _fetch_all(self, path: str) -> List[Tuple["PeerState", bytes]]:
        """Parallel fetch of one endpoint from every peer (the serial
        version made /cluster/trace block for N x timeout with a few
        unreachable workers — at exactly the moment an operator is
        debugging a sick cluster). Failures record last_error and drop
        out of the result."""
        targets = sorted(self.peers(), key=lambda s: s.label)
        results: List[Optional[bytes]] = [None] * len(targets)

        def one(i: int, st: PeerState) -> None:
            try:
                body, _ = self._fetch(st, path, record_rtt=False)
                results[i] = body
            except (OSError, ValueError) as e:
                st.last_error = str(e)

        threads = [
            threading.Thread(target=one, args=(i, st), daemon=True)
            for i, st in enumerate(targets)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.timeout + 1.0)
        return [
            (st, body) for st, body in zip(targets, results) if body is not None
        ]

    def cluster_trace(self) -> dict:
        """Live-fetch every worker's /trace and merge onto the runner's
        monotonic timeline: each peer becomes a Chrome-trace process
        (pid = peer index, process_name metadata), and its timestamps
        shift by the estimated clock offset so cross-peer causality
        (e.g. "every peer's allreduce stalls when peer 3 is late") is
        visible in one view."""
        merged: List[dict] = []
        for idx, (st, body) in enumerate(self._fetch_all("/trace")):
            try:
                doc = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            offset = st.clock_offset_us or 0.0
            merged.append({
                "name": "process_name", "ph": "M", "pid": idx, "tid": 0,
                "args": {"name": st.label},
            })
            merged.append({
                "name": "process_sort_index", "ph": "M", "pid": idx,
                "tid": 0, "args": {"sort_index": idx},
            })
            for ev in doc.get("traceEvents", []):
                ev = dict(ev)
                ev["pid"] = idx
                if isinstance(ev.get("ts"), (int, float)):
                    ev["ts"] = ev["ts"] + offset
                merged.append(ev)
        return {"traceEvents": merged, "displayTimeUnit": "ms"}

    def cluster_audit(self) -> List[dict]:
        """Merged audit timeline: every worker's /audit plus the
        runner's own records, sorted by wall time."""
        records = list(audit.to_json())
        for st, body in self._fetch_all("/audit"):
            try:
                peer_records = json.loads(body.decode())
            except ValueError:
                continue
            for rec in peer_records:
                rec = dict(rec)
                rec.setdefault("peer", st.label)
                records.append(rec)
        records.sort(key=lambda r: r.get("wall_time", 0.0))
        return records

    def add_postmortem(self, label: str, pm: dict) -> None:
        """Record a harvested worker postmortem (watcher calls this on
        every worker death it recovers from)."""
        with self._lock:
            self._postmortems.append((str(label), dict(pm)))

    def cluster_postmortem(self) -> dict:
        """The /cluster/postmortem view: every harvested death this
        run, newest last, grouped per peer."""
        with self._lock:
            items = list(self._postmortems)
        peers: Dict[str, List[dict]] = {}
        for label, pm in items:
            peers.setdefault(label, []).append(pm)
        return {
            "wall_time": time.time(),
            "deaths": len(items),
            "peers": peers,
        }

    def cluster_links(self) -> dict:
        """The /cluster/links view: the k×k link matrix assembled from
        every worker's exported row (no extra scrape — rows ride the
        /metrics pages the aggregator already holds), plus the per-peer
        clock offsets already estimated for /cluster/trace so offline
        tooling can align link events without re-deriving them."""
        doc = tlink.merge_matrix({st.label: st.links for st in self.peers()})
        doc["wall_time"] = self._scraped_at
        doc["clock_offset_us"] = {
            st.label: st.clock_offset_us for st in self.peers()
        }
        # active-ring view (ISSUE 14): reconstruct the ring order the
        # workers are actually walking from their exported positions;
        # only published when every scraped peer reported a distinct
        # position (mid-re-plan or partially-scraped clusters return
        # null rather than a half-true ring)
        positions = {
            st.label: st.ring_pos for st in self.peers()
            if st.ring_pos is not None
        }
        order = None
        if positions and len(positions) == len(self.peers()):
            by_pos = sorted(positions.items(), key=lambda kv: kv[1])
            if [p for _, p in by_pos] == list(range(len(by_pos))):
                order = [label for label, _ in by_pos]
        doc["ring"] = {
            "order": order,
            "position": positions,
            "next": {
                st.label: st.ring_next for st in self.peers()
                if st.ring_next is not None
            },
        }
        return doc

    # -- step plane (ISSUE 13) ------------------------------------------

    # merged step records older than this keep only their election; the
    # newest few retain the per-peer lanes `info steps` renders (full
    # lanes for all STEP_KEEP records would hold k x buckets dicts per
    # step on the runner forever)
    STEP_LANES_KEEP = 8

    def _refresh_steps(self) -> None:
        """Pull every worker's /steptrace, align timelines with the
        clock offsets already estimated for /cluster/trace, merge into
        per-step critical-path records, publish the gauges and track the
        patience window behind `step_critical_path` audit events. Only
        steps NEWER than the last refresh append (workers keep a ring;
        re-reading it must not replay old steps into the streak), and
        whole refreshes serialize — the sweep thread and an HTTP
        handler's inline refresh racing here would append the same
        fresh steps twice."""
        with self._steps_refresh_lock:
            self._refresh_steps_locked()

    def _refresh_steps_locked(self) -> None:
        docs: Dict[str, dict] = {}
        offsets: Dict[str, float] = {}
        for st, body in self._fetch_all("/steptrace"):
            try:
                docs[st.label] = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            offsets[st.label] = st.clock_offset_us or 0.0
        self._steps_at = time.monotonic()
        if not docs:
            return
        # merge only FLUSHED timelines (an in-flight round's partial
        # lanes belong to the worker/postmortem views, not a cluster
        # election), and ALWAYS hold the globally-newest flushed round
        # back until a newer one exists: a step merges exactly once, so
        # electing it while some peer is still walking (or unscraped)
        # would freeze a half-flushed critical path into the ring
        # forever (seen live: edge=None, overlap=None). Cost: one
        # step of publication lag, and a fully-quiesced run never
        # publishes its final round — the price of never publishing a
        # partial election.
        for doc in docs.values():
            doc["timelines"] = [
                t for t in doc.get("timelines", [])
                if t.get("t_end_us") is not None
            ]
        keys = {
            (int(t.get("epoch", 0)), int(t.get("round", 0)))
            for doc in docs.values()
            for t in doc["timelines"]
        }
        merged = tstep.merge_steps(docs, offsets)
        if keys:
            newest = max(keys)
            merged = [
                s for s in merged if (s["epoch"], s["round"]) < newest
            ]
        fresh = [
            s for s in merged
            if self._steps_last is None
            or (s["epoch"], s["round"]) > self._steps_last
        ]
        if not fresh:
            return
        with self._lock:
            for s in fresh:
                rec = dict(s)
                rec["peer_count"] = len(s.get("peers", {}))
                self._steps.append(rec)
            # beyond the lane window, keep only the election (the full
            # lanes are bulky and already served by the workers)
            for old in list(self._steps)[:-self.STEP_LANES_KEEP]:
                old.pop("peers", None)
            self._steps_last = (fresh[-1]["epoch"], fresh[-1]["round"])
        latest = fresh[-1]
        if latest.get("overlap_frac") is not None:
            self._g_step_overlap.set(latest["overlap_frac"])
        crit = latest.get("critical")
        self._g_step_critical.clear_children()
        if crit:
            self._g_step_critical.labels(
                str(crit.get("peer")), str(crit.get("edge") or "?")
            ).set((crit.get("self_us") or 0.0) / 1e6)
        # patience window: the SAME (peer, edge) dominating consecutive
        # merged steps is a standing bottleneck, not weather — audit it
        # once per streak, at the moment patience fills
        for s in fresh:
            c = s.get("critical")
            key = (
                (str(c.get("peer")), str(c.get("edge") or ""))
                if c else None
            )
            streak_key, count = self._crit_streak
            count = count + 1 if key is not None and key == streak_key else 1
            self._crit_streak = (key, count)
            if key is not None and count == STEP_CRIT_PATIENCE:
                audit.record_event(
                    "step_critical_path",
                    peer=key[0],
                    edge=key[1] or None,
                    bucket=c.get("bucket"),
                    trigger="step_merge",
                    blocking_ms=round((c.get("self_us") or 0.0) / 1e3, 3),
                    steps=STEP_CRIT_PATIENCE,
                    epoch=s["epoch"],
                    round=s["round"],
                )

    def cluster_steps(self) -> dict:
        """The /cluster/steps view: recent merged per-step critical-path
        records, newest last — the newest STEP_LANES_KEEP still carry
        their per-peer lanes (the `info steps` rendering), older ones
        only the election. Refreshes inline when the cached merge is
        older than a scrape interval, so one-shot consumers (`info
        steps` without a runner loop) still see fresh steps."""
        now = time.monotonic()
        if self._steps_at is None or now - self._steps_at >= self.interval:
            try:
                self._refresh_steps()
            except Exception as e:  # noqa: BLE001 - serve the cache over a 500
                log.warn("cluster: inline step refresh failed: %s", e)
        with self._lock:
            # shallow copies: a later refresh pops "peers" off aged
            # records in place, and serialization must not iterate a
            # dict mid-mutation
            steps = [dict(s) for s in self._steps]
        return {
            "wall_time": time.time(),
            "count": len(steps),
            "patience": STEP_CRIT_PATIENCE,
            "steps": steps,
        }

    # -- decision plane (ISSUE 15) --------------------------------------

    def _refresh_decisions(self) -> None:
        """Pull every worker's /decisions ledger, align the perf stamps
        with the clock offsets already estimated for /cluster/trace and
        merge keyed (peer, seq, open wall time): re-scraping an
        unchanged ledger is idempotent, a record that closed (or
        regressed) since the last sweep UPDATES its merged copy in
        place, and a respawned worker's restarted seq space cannot
        collide with its dead incarnation's records. Whole refreshes
        serialize like the step plane's."""
        with self._decisions_refresh_lock:
            self._refresh_decisions_locked()

    def _refresh_decisions_locked(self) -> None:
        docs: Dict[str, dict] = {}
        offsets: Dict[str, float] = {}
        for st, body in self._fetch_all("/decisions"):
            try:
                docs[st.label] = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            offsets[st.label] = st.clock_offset_us or 0.0
        self._decisions_at = time.monotonic()
        if not docs:
            return
        merged = tdecisions.merge_decisions(docs, offsets)
        with self._lock:
            for rec in merged:
                self._decisions[(
                    rec.get("peer", ""),
                    int(rec.get("seq", 0)),
                    float(rec.get("wall_time") or 0.0),
                )] = rec
            if len(self._decisions) > self._decisions_keep:
                ordered = sorted(
                    self._decisions.items(),
                    key=lambda kv: kv[1].get("t_us") or 0.0,
                )
                for key, _ in ordered[:-self._decisions_keep]:
                    del self._decisions[key]

    def cluster_decisions(self) -> dict:
        """The /cluster/decisions view: the merged causal adaptation
        timeline, oldest first. Refreshes inline when the cached merge
        is older than a scrape interval, so one-shot consumers (`info
        decisions` without a runner loop) still see fresh outcomes."""
        now = time.monotonic()
        if (
            self._decisions_at is None
            or now - self._decisions_at >= self.interval
        ):
            try:
                self._refresh_decisions()
            except Exception as e:  # noqa: BLE001 - serve the cache over a 500
                log.warn("cluster: inline decision refresh failed: %s", e)
        with self._lock:
            recs = sorted(
                self._decisions.values(),
                key=lambda r: r.get("t_us") or r.get("wall_time") or 0.0,
            )
        return {
            "wall_time": time.time(),
            "count": len(recs),
            "open": sum(1 for r in recs if r.get("status") != "closed"),
            "regressed": sum(1 for r in recs if r.get("regressed")),
            "decisions": recs,
        }

    # -- resource plane (ISSUE 16) --------------------------------------

    def _refresh_resources(self) -> None:
        """Pull every worker's /resources document, align the perf
        anchors with the clock offsets already estimated for
        /cluster/trace and REPLACE the merged view (current state, not a
        log: a vanished peer's stale saturation flag must not keep
        classifying straggler causes). Whole refreshes serialize like
        the step plane's."""
        with self._resources_refresh_lock:
            self._refresh_resources_locked()

    def _refresh_resources_locked(self) -> None:
        docs: Dict[str, dict] = {}
        offsets: Dict[str, float] = {}
        for st, body in self._fetch_all("/resources"):
            try:
                docs[st.label] = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            offsets[st.label] = st.clock_offset_us or 0.0
        self._resources_at = time.monotonic()
        merged = tresource.merge_resources(docs, offsets)
        with self._lock:
            self._resources = merged

    def cluster_resources(self) -> dict:
        """The /cluster/resources view: every live worker's resource
        attribution document merged NTP-aligned, plus the cluster
        election (saturated peers, max CPU fraction). Refreshes inline
        when the cached merge is older than a scrape interval, so
        one-shot consumers (`info resources` without a runner loop)
        still see fresh attribution."""
        now = time.monotonic()
        if (
            self._resources_at is None
            or now - self._resources_at >= self.interval
        ):
            try:
                self._refresh_resources()
            except Exception as e:  # noqa: BLE001 - serve the cache over a 500
                log.warn("cluster: inline resource refresh failed: %s", e)
        with self._lock:
            merged = dict(self._resources)
        doc = {
            "wall_time": time.time(),
            "count": len(merged.get("peers") or {}),
        }
        doc.update(merged)
        return doc

    def _resources_summary(self) -> Optional[dict]:
        """Compact resource signal for /cluster/health (the full
        documents stay on /cluster/resources): per peer the window CPU
        fraction, the training bucket's share of the busy window, the
        engine share and the saturation flag — exactly the columns
        `info top` renders."""
        with self._lock:
            merged = self._resources
            if not merged or not merged.get("peers"):
                return None
            peers = {}
            for label, doc in merged["peers"].items():
                buckets = doc.get("buckets") or {}
                peers[label] = {
                    "cpu_frac": doc.get("cpu_frac"),
                    "train_frac": (buckets.get("train") or {}).get("frac"),
                    "engine_frac": doc.get("engine_frac"),
                    "saturated": bool(doc.get("saturated")),
                }
            return {
                "peers": peers,
                "saturated": list(merged.get("saturated") or []),
                "max_cpu_frac": merged.get("max_cpu_frac"),
            }

    # -- memory plane (ISSUE 17) ----------------------------------------

    def _refresh_memory(self) -> None:
        """Pull every worker's /memory document, align the perf anchors
        with the clock offsets already estimated for /cluster/trace and
        REPLACE the merged view (current state, not a log: a vanished
        peer's stale pressure flag must not keep gating resizes).
        Whole refreshes serialize like the resource plane's."""
        with self._memory_refresh_lock:
            self._refresh_memory_locked()

    def _refresh_memory_locked(self) -> None:
        docs: Dict[str, dict] = {}
        offsets: Dict[str, float] = {}
        for st, body in self._fetch_all("/memory"):
            try:
                docs[st.label] = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            offsets[st.label] = st.clock_offset_us or 0.0
        self._memory_at = time.monotonic()
        merged = tmemory.merge_memory(docs, offsets)
        with self._lock:
            self._memory = merged

    def cluster_memory(self) -> dict:
        """The /cluster/memory view: every live worker's memory
        attribution document merged NTP-aligned, plus the cluster
        elections (minimum headroom + its peer, the pressure and
        thrashing sets, leak suspects). Refreshes inline when the
        cached merge is older than a scrape interval, so one-shot
        consumers (`info memory` without a runner loop) still see
        fresh attribution."""
        now = time.monotonic()
        if (
            self._memory_at is None
            or now - self._memory_at >= self.interval
        ):
            try:
                self._refresh_memory()
            except Exception as e:  # noqa: BLE001 - serve the cache over a 500
                log.warn("cluster: inline memory refresh failed: %s", e)
        with self._lock:
            merged = dict(self._memory)
        doc = {
            "wall_time": time.time(),
            "count": len(merged.get("peers") or {}),
        }
        doc.update(merged)
        return doc

    def _memory_summary(self) -> Optional[dict]:
        """Compact memory signal for /cluster/health (the full
        documents stay on /cluster/memory): per peer the used fraction,
        headroom, thrash/pressure flags — exactly the columns `info
        top` renders — plus the cluster elections."""
        with self._lock:
            merged = self._memory
            if not merged or not merged.get("peers"):
                return None
            peers = {}
            for label, doc in merged["peers"].items():
                hf = doc.get("headroom_frac")
                peers[label] = {
                    "rss_bytes": doc.get("rss_bytes"),
                    "headroom_frac": hf,
                    "used_frac": (
                        round(1.0 - hf, 6)
                        if isinstance(hf, (int, float)) else None
                    ),
                    "pressure": bool(doc.get("pressure")),
                    "thrashing": bool(doc.get("thrashing")),
                }
            return {
                "peers": peers,
                "min_headroom_frac": merged.get("min_headroom_frac"),
                "min_headroom_peer": merged.get("min_headroom_peer"),
                "pressure": list(merged.get("pressure") or []),
                "thrashing": list(merged.get("thrashing") or []),
                "leak_suspects": dict(merged.get("leak_suspects") or {}),
            }

    def footprint_bytes(self) -> int:
        """The aggregator's OWN tracked-state footprint: deep size of
        the link matrix, step ring, decision log and the merged
        resource/memory views. This is the O(k^2)-worried state ROADMAP
        item 2 needs bounded at scale — measured, and registered under
        the `telemetry` bucket of the runner's own memory plane."""
        with self._lock:
            state = (
                {st.label: st.links for st in self._peers.values()},
                list(self._steps),
                dict(self._decisions),
                dict(self._resources),
                dict(self._memory),
            )
        return tmemory.deep_sizeof(state)

    def _steps_summary(self) -> Optional[dict]:
        """Compact step signal for /cluster/health (the full records
        stay on /cluster/steps): the latest step's election plus each
        peer's share of recent steps it was critical in."""
        with self._lock:
            steps = list(self._steps)
        if not steps:
            return None
        latest = steps[-1]
        crit_counts: Dict[str, int] = {}
        crit_edges: Dict[str, str] = {}
        for s in steps:
            c = s.get("critical")
            if not c or c.get("peer") is None:
                continue
            peer = str(c["peer"])
            crit_counts[peer] = crit_counts.get(peer, 0) + 1
            if c.get("edge"):
                crit_edges[peer] = str(c["edge"])
        n = len(steps)
        crit = latest.get("critical") or {}
        return {
            "steps": n,
            "critical_peer": crit.get("peer"),
            "critical_edge": crit.get("edge"),
            "critical_ms": (
                round((crit.get("self_us") or 0.0) / 1e3, 3)
                if crit else None
            ),
            "overlap_frac": latest.get("overlap_frac"),
            "queue_delay_frac": latest.get("queue_delay_frac"),
            "crit_frac": {
                p: round(c / n, 3) for p, c in sorted(crit_counts.items())
            },
            "crit_edge": crit_edges,
        }

    def _links_summary(self) -> dict:
        """Compact link signal for /cluster/health (the full matrix
        stays on /cluster/links): the slowest measured edge and how many
        edges have estimates at all. The election itself lives in ONE
        place — tlink.merge_matrix — so this summary can never disagree
        with /cluster/links about which edge is slowest. copy_edges=False:
        this runs on every /cluster/health request (polled by every
        worker), and a k=64 matrix is ~4k edge dicts we would copy only
        to throw away."""
        doc = tlink.merge_matrix(
            {st.label: st.links for st in self.peers()}, copy_edges=False
        )
        edges = sum(
            1
            for row in doc["edges"].values()
            for info in row.values()
            if isinstance(info.get("bw"), (int, float)) and info["bw"] > 0
        )
        return {
            "min_bw": doc["min_bw"],
            "slowest_edge": doc["slowest_edge"],
            "edges": edges,
        }

    def cluster_health(self) -> dict:
        """The JSON health snapshot behind /cluster/health and
        monitor.cluster_health()."""
        now = time.monotonic()
        scores = self.scorer.scores()
        rtt_scores = self.rtt_scorer.scores()
        peers = {}
        for st in self.peers():
            sc = scores.get(st.label)
            rsc = rtt_scores.get(st.label)
            peers[st.label] = {
                "url": st.url,
                "step_rate": st.step_rate,
                "step_time_p50_ms": (
                    round(st.step_p50 * 1e3, 3) if st.step_p50 is not None
                    else None
                ),
                "step_time_p99_ms": (
                    round(st.step_p99 * 1e3, 3) if st.step_p99 is not None
                    else None
                ),
                # the SCORED series' rolling median: compute time (step
                # minus collective wait) when the worker publishes
                # collective latencies, else wall-clock step time
                "step_time_ms": (
                    round(sc.value * 1e3, 3) if sc is not None else None
                ),
                "compute_time_ms": (
                    round(st.compute_mean * 1e3, 3)
                    if st.compute_mean is not None else None
                ),
                "bytes_tx": st.bytes_tx,
                "bytes_rx": st.bytes_rx,
                "rtt_ms": (
                    round(st.rtt_s * 1e3, 3)
                    if math.isfinite(st.rtt_s) else None
                ),
                "clock_offset_us": st.clock_offset_us,
                "last_scrape_age_s": (
                    round(now - st.last_ok, 3)
                    if st.last_ok is not None else None
                ),
                "error": st.last_error or None,
                "straggler": bool(sc.flagged) if sc is not None else False,
                "straggler_score": (
                    round(sc.score, 2) if sc is not None else None
                ),
                "rtt_outlier": bool(rsc.flagged) if rsc is not None else False,
                # the measured cause classified at the flag transition
                # (network/compute/unknown); None while unflagged
                "straggler_cause": self._causes.get(st.label),
            }
        med = self.scorer.cluster_median()
        return {
            # wall_time is the LAST SCRAPE's stamp, not request time:
            # consumers debounce refreshes on it (cluster/updated_at),
            # so re-reading an unchanged snapshot must not look fresh
            "wall_time": self._scraped_at,
            "interval_s": self.interval,
            "peers": peers,
            "stragglers": sorted(self._flagged),
            "rtt_outliers": sorted(self._rtt_flagged),
            "cluster_step_time_ms": (
                round(med * 1e3, 3) if med is not None else None
            ),
            "step_skew": self.scorer.skew(),
            "links": self._links_summary(),
            "steps": self._steps_summary(),
            "resources": self._resources_summary(),
            "memory": self._memory_summary(),
        }


# -- adaptation-facing accessors ---------------------------------------

_aggregator: Optional[TelemetryAggregator] = None
_agg_lock = threading.Lock()
# remote /cluster/health cache: "t" = monotonic time of the last
# SUCCESSFUL fetch (a failed refresh must NOT re-stamp stale flags as
# fresh), "attempt_t" rate-limits refresh attempts, "fetching" holds the
# single in-flight refresh thread flag
_remote_cache: dict = {
    "t": 0.0, "attempt_t": 0.0, "data": None, "url": "", "fetching": False,
}


def set_aggregator(agg: Optional[TelemetryAggregator]) -> None:
    """Install the process-wide aggregator (the elastic watcher does
    this; tests may too)."""
    global _aggregator
    with _agg_lock:
        _aggregator = agg


def get_aggregator() -> Optional[TelemetryAggregator]:
    with _agg_lock:
        return _aggregator


def _refresh_remote(url: str) -> None:
    try:
        with urllib.request.urlopen(url, timeout=2.0) as r:
            data = json.loads(r.read().decode())
        with _agg_lock:
            if _remote_cache["url"] == url:
                _remote_cache.update(t=time.monotonic(), data=data)
    except (OSError, ValueError):
        pass  # keep the old data AND its old timestamp: stale is stale
    finally:
        with _agg_lock:
            _remote_cache["fetching"] = False


def health_snapshot(max_age: float = 5.0, wait: bool = False) -> Optional[dict]:
    """The latest cluster-health dict, from the in-process aggregator
    when this process hosts one (the runner), else fetched from
    ``KF_CLUSTER_HEALTH_URL`` (workers; the watcher injects the env var
    pointing at its own /cluster/health).

    The remote path NEVER blocks the caller (it sits on the training-step
    path via PolicyRunner): it returns the cached snapshot immediately —
    possibly stale, possibly None on the very first call — and refreshes
    in a background thread at most every ``max_age`` seconds. A snapshot
    older than the last scrape keeps its original ``wall_time``, so
    debounced consumers (cluster/updated_at) never mistake a dead
    runner's last flags for news. ``wait=True`` (tests, one-shot CLIs)
    runs an overdue refresh inline instead."""
    agg = get_aggregator()
    if agg is not None:
        return agg.cluster_health()
    url = knobs.raw(HEALTH_URL_ENV)
    if not url:
        return None
    now = time.monotonic()
    with _agg_lock:
        if _remote_cache["url"] != url:
            _remote_cache.update(
                t=0.0, attempt_t=0.0, data=None, url=url, fetching=False
            )
        data = _remote_cache["data"]
        fresh = data is not None and now - _remote_cache["t"] < max_age
        due = (
            not fresh
            and not _remote_cache["fetching"]
            and now - _remote_cache["attempt_t"] >= max_age
        )
        if due:
            _remote_cache["fetching"] = True
            _remote_cache["attempt_t"] = now
    if due:
        if wait:
            _refresh_remote(url)
            with _agg_lock:
                return _remote_cache["data"]
        threading.Thread(
            target=_refresh_remote, args=(url,),
            name="kf-health-refresh", daemon=True,
        ).start()
    return data


def health_signals(
    max_age: float = 5.0, self_peer: str = "", wait: bool = False
) -> dict:
    """Flatten the health snapshot into the signal dict policies see in
    ``PolicyContext.metrics`` (namespaced ``cluster/``)."""
    snap = health_snapshot(max_age, wait=wait)
    if not snap:
        return {}
    me = self_peer or knobs.raw("KF_SELF_SPEC")
    stragglers = snap.get("stragglers", [])
    signals = {
        # refresh marker: consumers that must count SCRAPES (not steps)
        # key off this — flag lists are identical between refreshes for
        # a steady straggler
        "cluster/updated_at": snap.get("wall_time"),
        "cluster/stragglers": stragglers,
        "cluster/rtt_outliers": snap.get("rtt_outliers", []),
        "cluster/step_skew": snap.get("step_skew"),
        "cluster/step_time_ms": snap.get("cluster_step_time_ms"),
        "cluster/straggler_score": {
            p: info.get("straggler_score")
            for p, info in snap.get("peers", {}).items()
            if info.get("straggler_score") is not None
        },
        "cluster/self_straggler": me in stragglers if me else False,
    }
    links = snap.get("links") or {}
    if links.get("min_bw") is not None:
        signals["links/min_bw"] = links["min_bw"]
        signals["links/slowest_edge"] = links.get("slowest_edge")
    # step plane (ISSUE 13): the measured per-step attribution signals
    # re-planning and priority feedback consume — cluster-wide values
    # override the worker-local steptrace fallbacks on the shared keys
    steps = snap.get("steps") or {}
    if steps.get("steps"):
        signals["step/critical_peer"] = steps.get("critical_peer")
        signals["step/critical_edge"] = steps.get("critical_edge")
        if steps.get("overlap_frac") is not None:
            signals["step/overlap_frac"] = steps["overlap_frac"]
        if steps.get("queue_delay_frac") is not None:
            signals["step/queue_delay_frac"] = steps["queue_delay_frac"]
    # resource plane (ISSUE 16): the cluster view of MY OWN attribution
    # overrides the worker-local fallback on the shared resource/* keys
    # (same precedence as the step plane) — policies on any peer also
    # see the cluster-wide compute-bound election
    res = snap.get("resources") or {}
    mine = (res.get("peers") or {}).get(me) if me else None
    if mine:
        if mine.get("cpu_frac") is not None:
            signals["resource/cpu_frac"] = mine["cpu_frac"]
        if mine.get("engine_frac") is not None:
            signals["resource/engine_frac"] = mine["engine_frac"]
        signals["resource/saturated"] = bool(mine.get("saturated"))
    if res.get("saturated") is not None:
        signals["resource/saturated_peers"] = list(res["saturated"])
    # memory plane (ISSUE 17): the cluster view of MY OWN headroom
    # overrides the worker-local fallback on the shared memory/* keys;
    # policies on any peer also see the cluster's weakest-headroom
    # election — the grow-gate input
    mem = snap.get("memory") or {}
    mem_mine = (mem.get("peers") or {}).get(me) if me else None
    if mem_mine:
        if mem_mine.get("headroom_frac") is not None:
            signals["memory/headroom_frac"] = mem_mine["headroom_frac"]
            signals["memory/pressure"] = bool(mem_mine.get("pressure"))
    if mem.get("min_headroom_peer") is not None:
        signals["memory/min_headroom_peer"] = mem["min_headroom_peer"]
        signals["memory/min_headroom_frac"] = mem.get("min_headroom_frac")
    if mem.get("leak_suspects"):
        signals["memory/leak_suspect"] = True
    return signals
