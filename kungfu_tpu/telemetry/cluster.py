"""Cluster observability plane: runner-side telemetry aggregation.

ISSUE 2 tentpole. PR 1 gave every worker its own ``/metrics`` +
``/trace`` + ``/audit`` endpoint on ``peer_port + 10000``; this module
is the runner-side :class:`TelemetryAggregator` that periodically
scrapes every live worker (it learns the cluster from the elastic
watcher's Stages), merges the results into one cluster snapshot, and
serves it from the watcher's debug endpoint:

- ``/cluster/metrics`` — federated Prometheus exposition, every sample
  labelled ``peer="host:port"`` (collisions become ``exported_peer``,
  the Prometheus federation rule);
- ``/cluster/trace``   — all workers' Chrome traces merged onto the
  runner's timeline, per-peer clock offsets estimated NTP-style from
  the scrape round trip (each response carries the worker's monotonic
  clock in an ``X-KF-Perf-Now-Us`` header; offset error <= RTT/2, and
  the stored offset only improves as lower-RTT scrapes land);
- ``/cluster/health``  — JSON: per-peer step rate, step-time p50/p99,
  bytes tx/rx, last-scrape age, straggler score/flag;
- ``/cluster/links``   — the k×k link matrix (ISSUE 6): every worker's
  ``kungfu_link_*`` row (passive per-destination EWMA bandwidth/latency
  from real collective traffic) merged into one document, with the
  slowest edge called out — the input signal for straggler-adaptive
  topology re-planning;
- ``/cluster/steps``   — the step plane (ISSUE 13): every worker's
  ``/steptrace`` ring merged per (session_epoch, round) with the same
  clock offsets, each step carrying its elected critical (peer, bucket,
  edge) chain, overlap fraction and queue-delay fraction — "which
  bucket on which peer over which edge was the long pole" as data;
- ``/cluster/decisions`` — the decision plane (ISSUE 15): every
  worker's ``/decisions`` ledger merged into one NTP-aligned causal
  timeline — each adaptation (strategy/wire vote, re-plan, mode flip,
  resize) with its trigger, predicted gain and MEASURED outcome
  (realized gain, verdict, regression flag) — "the cluster adapted;
  did it help?" as data;
- ``/cluster/resources`` — the resource plane (ISSUE 16): every
  worker's ``/resources`` per-thread CPU attribution merged into one
  view with the saturated (compute-bound) peers elected — the input
  that lets straggler events carry ``cause=compute`` vs ``network``
  and lets re-planning clamp predicted gains by the compute floor;
- ``/cluster/memory`` — the memory plane (ISSUE 17): every worker's
  ``/memory`` bucket decomposition, headroom forecast and thrash flag
  merged into one view with the minimum-headroom peer elected — the
  grow-gate input the unattended autoscaler consults and the feed for
  ``cause=memory`` straggler attribution.

On top of the snapshot the aggregator runs straggler detection
(:mod:`~kungfu_tpu.telemetry.straggler`): rolling per-peer step-time
medians, robust-z flagging of slow peers and RTT outliers. Flags are
published three ways so every consumer sees the same truth:
``kungfu_cluster_*`` gauges (the aggregator's own registry, appended to
``/cluster/metrics``), ``telemetry.audit`` events on flag transitions,
and adaptation-facing signals (``monitor.cluster_health()`` →
``PolicyContext.metrics``) that let a ``BasePolicy`` trigger a resize
or strategy switch on skew.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
import urllib.request
import weakref
from urllib.parse import urlsplit
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import audit, log, metrics, promparse
from kungfu_tpu.telemetry import decisions as tdecisions
from kungfu_tpu.telemetry import link as tlink
from kungfu_tpu.telemetry import memory as tmemory
from kungfu_tpu.telemetry import resource as tresource
from kungfu_tpu.telemetry import steptrace as tstep
from kungfu_tpu.telemetry import straggler as tstraggler
from kungfu_tpu.telemetry.straggler import StragglerScorer

# metric families scraped off each worker's exposition
STEPS_TOTAL = "kungfu_steps_total"
STEP_SECONDS = "kungfu_step_duration_seconds"
COLLECTIVE_SECONDS = "kungfu_collective_latency_seconds"
EGRESS_BYTES = "kungfu_egress_bytes_total"
INGRESS_BYTES = "kungfu_ingress_bytes_total"
PEER_RTT = "kungfu_peer_rtt_seconds"
# link-plane families (ISSUE 6): each worker's exposition carries its
# own ROW of the link matrix; the aggregator assembles the k x k view
LINK_BW = "kungfu_link_bandwidth_bytes_per_second"
LINK_LAT = "kungfu_link_latency_seconds"
LINK_BYTES = "kungfu_link_tx_bytes_total"
LINK_MSGS = "kungfu_link_tx_messages_total"
# active-ring families (ISSUE 14): each worker exports its position in
# the current segmented-ring order and its successor edge, so
# /cluster/links can render the ACTIVE ring next to the measured matrix
RING_POS = "kungfu_topology_ring_position"
RING_NEXT = "kungfu_topology_ring_next"
# two-level plan role (ISSUE 19): each worker exports its level ("inter"
# head / "intra" member / "flat") and role, value = host-group index, so
# the links view can render the ACTIVE hierarchy (groups, heads, demoted)
RING_ROLE = "kungfu_topology_ring_role"
# active wire precision (ISSUE 20): each worker exports the RUNNING
# codec mode of its collective session (off/bf16/f16/int8/int4 — config
# + lockstep precision votes), so `info links` can render what the
# cluster's payloads actually cross the transport as
WIRE_MODE = "kungfu_collective_wire_mode"

CLOCK_HEADER = "X-KF-Perf-Now-Us"

# step plane (ISSUE 13): how many merged steps the aggregator retains
# for /cluster/steps and the info-top critical columns, and how many
# consecutive merged steps the same (peer, edge) must dominate before a
# `step_critical_path` audit event fires (matches StragglerPolicy's
# default patience — one noisy step is weather, three is a bottleneck)
STEP_KEEP = 64
STEP_CRIT_PATIENCE = 3

DEFAULT_INTERVAL = 5.0
INTERVAL_ENV = "KF_CLUSTER_SCRAPE_INTERVAL"
HEALTH_URL_ENV = "KF_CLUSTER_HEALTH_URL"

# lock hierarchy (KF201): the host sub-aggregator's serialization lock
# wraps its cache lock in digest(); never acquire them the other way
_KF_LOCK_ORDER = ("_refresh_lock", "_lock")

# the worker endpoint a host head's digest pre-merges (ISSUE 18)
HOST_DIGEST_PATH = "/host/telemetry"

# every /cluster/* route the watcher's debug server exposes, in one
# place: watch.py builds its dispatch from this and the endpoint-doc
# lint (KF606) checks docs/telemetry.md against it — a route added to
# the aggregator can't silently miss the server or the docs
CLUSTER_ROUTES = (
    "/cluster/metrics",
    "/cluster/trace",
    "/cluster/health",
    "/cluster/links",
    "/cluster/steps",
    "/cluster/decisions",
    "/cluster/resources",
    "/cluster/memory",
    "/cluster/audit",
    "/cluster/postmortem",
)


def scrape_interval() -> float:
    v = float(knobs.get(INTERVAL_ENV))
    return v if v > 0 else DEFAULT_INTERVAL


def hier_min_peers() -> int:
    """Scale-mode threshold (ISSUE 18): at or above this many scrape
    targets the aggregator switches to the hierarchical/sampled/delta
    plane; 0 disables scale mode entirely."""
    try:
        return int(knobs.get("KF_AGG_HIER_MIN_PEERS"))
    except (TypeError, ValueError):
        return 32


class _HistSnapshot:
    """Cumulative histogram state parsed from one exposition page."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds, counts, total_sum, count):
        self.bounds = bounds  # sorted finite bucket bounds
        self.counts = counts  # cumulative counts aligned to bounds + [+Inf]
        self.sum = total_sum
        self.count = count

    @classmethod
    def from_samples(cls, samples, family) -> Optional["_HistSnapshot"]:
        buckets = []
        total_sum = total_count = None
        for s in samples:
            if s.name == family + "_bucket":
                le = s.labels_dict().get("le", "")
                bound = math.inf if le == "+Inf" else float(le)
                buckets.append((bound, s.value))
            elif s.name == family + "_sum":
                total_sum = s.value
            elif s.name == family + "_count":
                total_count = s.value
        if not buckets or total_count is None:
            return None
        buckets.sort(key=lambda b: b[0])
        bounds = [b for b, _ in buckets if b != math.inf]
        counts = [c for _, c in buckets]
        return cls(bounds, counts, total_sum or 0.0, total_count)

    def delta(self, prev: Optional["_HistSnapshot"]) -> "_HistSnapshot":
        """Windowed histogram since `prev` (same buckets), or self."""
        if (
            prev is None
            or prev.bounds != self.bounds
            or prev.count > self.count  # worker restarted: counters reset
        ):
            return self
        return _HistSnapshot(
            self.bounds,
            [c - p for c, p in zip(self.counts, prev.counts)],
            self.sum - prev.sum,
            self.count - prev.count,
        )

    def quantile(self, q: float) -> float:
        """Interpolated quantile (histogram_quantile semantics)."""
        total = self.counts[-1] if self.counts else 0
        if total <= 0:
            return math.nan
        rank = q * total
        prev_cum = 0.0
        for i, cum in enumerate(self.counts):
            if cum >= rank and cum > prev_cum:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else math.inf
                if hi == math.inf:
                    return lo
                frac = (rank - prev_cum) / (cum - prev_cum)
                return lo + (hi - lo) * frac
            prev_cum = cum
        return self.bounds[-1] if self.bounds else math.nan

    def to_doc(self) -> dict:
        """JSON-portable form (the host digest ships pre-parsed
        histograms so the root never re-parses k exposition pages)."""
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }

    @classmethod
    def from_doc(cls, doc) -> Optional["_HistSnapshot"]:
        if not isinstance(doc, dict) or "counts" not in doc:
            return None
        bounds = [float(b) for b in doc.get("bounds") or []]
        counts = [float(c) for c in doc.get("counts") or []]
        if not counts:
            return None
        return cls(bounds, counts, float(doc.get("sum") or 0.0),
                   float(doc.get("count") or 0.0))


def parse_worker_page(text: str) -> dict:
    """One pass over a worker's /metrics exposition -> the derived
    fields the aggregator tracks per peer. Factored out of
    _scrape_peer (ISSUE 18) so a host head can pre-parse its local
    siblings' pages and ship the result in its digest: the root then
    ingests k summaries at C-speed JSON cost instead of running the
    pure-Python exposition parser k times per sweep."""
    samples = promparse.parse_text(text)
    tx = rx = None
    coll_sum = None
    rtts: List[float] = []
    links: Dict[str, dict] = {}
    ring_pos = None
    ring_next = None
    ring_role = None
    wire_mode = None
    _link_key = {
        LINK_BW: "bw", LINK_LAT: "latency_s",
        LINK_BYTES: "tx_bytes", LINK_MSGS: "tx_messages",
    }
    for s in samples:
        if s.name == EGRESS_BYTES:
            tx = (tx or 0.0) + s.value
        elif s.name == INGRESS_BYTES:
            rx = (rx or 0.0) + s.value
        elif s.name == COLLECTIVE_SECONDS + "_sum":
            # summed across the per-kind label children: total
            # seconds this worker has spent inside host collectives
            coll_sum = (coll_sum or 0.0) + s.value
        elif s.name == PEER_RTT and math.isfinite(s.value) and s.value > 0:
            rtts.append(s.value)
        elif s.name == RING_POS:
            ring_pos = int(s.value)
        elif s.name == RING_NEXT and s.value:
            ring_next = s.labels_dict().get("dst") or ring_next
        elif s.name == RING_ROLE:
            d = s.labels_dict()
            ring_role = {"level": d.get("level"), "role": d.get("role"),
                         "group": int(s.value)}
        elif s.name == WIRE_MODE and s.value:
            wire_mode = s.labels_dict().get("mode") or wire_mode
        elif s.name in _link_key:
            dst = s.labels_dict().get("dst")
            if dst:
                links.setdefault(dst, {})[_link_key[s.name]] = s.value
    return {
        "steps_total": promparse.sample_value(samples, STEPS_TOTAL),
        "step_hist": _HistSnapshot.from_samples(samples, STEP_SECONDS),
        "coll_sum": coll_sum,
        "bytes_tx": tx,
        "bytes_rx": rx,
        "reported_rtt": sorted(rtts)[len(rtts) // 2] if rtts else None,
        "links": links,
        "ring_pos": ring_pos,
        "ring_next": ring_next,
        "ring_role": ring_role,
        "wire_mode": wire_mode,
    }


def parsed_to_doc(parsed: dict) -> dict:
    """JSON-portable form of a parse_worker_page result (digest wire
    format)."""
    doc = dict(parsed)
    h = doc.get("step_hist")
    doc["step_hist"] = h.to_doc() if isinstance(h, _HistSnapshot) else None
    return doc


def parsed_from_doc(doc: dict) -> dict:
    parsed = dict(doc)
    h = parsed.get("step_hist")
    if not isinstance(h, _HistSnapshot):
        parsed["step_hist"] = _HistSnapshot.from_doc(h)
    parsed.setdefault("steps_total", None)
    parsed.setdefault("coll_sum", None)
    parsed.setdefault("bytes_tx", None)
    parsed.setdefault("bytes_rx", None)
    parsed.setdefault("reported_rtt", None)
    parsed.setdefault("links", {})
    parsed.setdefault("ring_pos", None)
    parsed.setdefault("ring_next", None)
    parsed.setdefault("ring_role", None)
    parsed.setdefault("wire_mode", None)
    return parsed


def _note_clock(
    st: "PeerState", rtt: float, clock: Optional[str],
    t0: float, t1: float,
) -> None:
    """NTP midpoint update shared by the root aggregator and a host
    head: assume the worker stamped its clock header halfway through
    the round trip. perf_counter epochs are fixed per process, so the
    TRUE offset is constant — keep the estimate from the lowest-RTT
    scrape ever seen (its error bound, RTT/2, is the tightest)."""
    if clock is None:
        return
    if rtt <= st.best_rtt_s or st.clock_offset_us is None:
        st.best_rtt_s = rtt
        mid_us = (t0 + t1) / 2.0 * 1e6
        try:
            st.clock_offset_us = mid_us - float(clock)
        except ValueError:
            pass


class _RefreshedPlane:
    """One serialized-refresh + staleness-cache unit (ISSUE 18
    satellite: the step/decision/resource/memory planes each carried a
    near-identical refresh lock, monotonic stamp and inline-staleness
    block — this is that block, once).

    `refresh()` runs the plane's refresh function under the plane's own
    lock (NOT the aggregator state lock: a refresh spans HTTP fetches)
    and stamps the monotonic refresh time on success — two concurrent
    runs would compute freshness against the same baseline and
    double-apply. `ensure_fresh()` is the inline path for one-shot
    consumers (`info X` without a runner loop): refresh when the cache
    is older than the scrape interval, serving the cache over a 500 if
    the refresh fails."""

    def __init__(self, name: str, refresh_fn: Callable[[], None],
                 interval_fn: Callable[[], float]):
        self.name = name
        self._refresh_fn = refresh_fn
        self._interval_fn = interval_fn
        self._lock = threading.Lock()
        self.at: Optional[float] = None  # monotonic, last completed refresh

    def refresh(self) -> None:
        with self._lock:
            try:
                self._refresh_fn()
            finally:
                # stamp even when the fetch round yielded nothing: an
                # empty cluster must not retry on every request
                self.at = time.monotonic()

    def age_s(self) -> Optional[float]:
        return None if self.at is None else time.monotonic() - self.at

    def stale(self) -> bool:
        age = self.age_s()
        return age is None or age >= self._interval_fn()

    def ensure_fresh(self) -> None:
        if not self.stale():
            return
        try:
            self.refresh()
        except Exception as e:  # noqa: BLE001 - serve the cache over a 500
            log.warn(
                "cluster: inline %s refresh failed: %s", self.name, e
            )


class PeerState:
    """Everything the aggregator knows about one scrape target."""

    def __init__(self, label: str, url: str):
        self.label = label
        self.url = url.rstrip("/")
        self.last_ok: Optional[float] = None  # monotonic
        self.last_error: str = ""
        self.scrapes = 0
        self.errors = 0
        # a scrape thread is working this peer; the next sweep skips it
        # rather than interleave prev/current swaps on the same state
        self.inflight = False
        self.rtt_s = math.inf  # last scrape round trip
        self.best_rtt_s = math.inf
        self.clock_offset_us: Optional[float] = None
        self.metrics_text = ""
        # step accounting across scrapes
        self.steps_total: Optional[float] = None
        self.step_hist: Optional[_HistSnapshot] = None
        self.prev_steps: Optional[float] = None
        self.prev_hist: Optional[_HistSnapshot] = None
        self.prev_t: Optional[float] = None
        self.step_rate: Optional[float] = None
        self.step_p50: Optional[float] = None
        self.step_p99: Optional[float] = None
        # collective-wait accounting: in SYNCHRONOUS training every
        # peer's wall-clock step converges to the straggler's (the fast
        # peers spend the difference waiting inside collectives), so the
        # straggler signal is compute time = step - collective wait
        self.coll_sum: Optional[float] = None
        self.coll_count: Optional[float] = None
        self.prev_coll_sum: Optional[float] = None
        self.compute_mean: Optional[float] = None
        self.bytes_tx: Optional[float] = None
        self.bytes_rx: Optional[float] = None
        self.reported_rtt: Optional[float] = None  # median of its probes
        # this peer's link-matrix row, parsed off its last exposition:
        # {dst: {"bw":, "latency_s":, "tx_bytes":, "tx_messages":}}
        self.links: Dict[str, dict] = {}
        # active-ring view (ISSUE 14): this peer's position in the
        # current ring order and its successor peer label
        self.ring_pos: Optional[int] = None
        self.ring_next: Optional[str] = None
        # two-level role (ISSUE 19): {"level","role","group"} or None
        self.ring_role: Optional[dict] = None
        # active wire precision (ISSUE 20): the RUNNING codec mode
        self.wire_mode: Optional[str] = None
        # per-(peer, endpoint) freshness (ISSUE 18 fix): a peer failing
        # ONE endpoint mid-sweep used to leave that plane's previous
        # payload silently current — last_ok only tracked /metrics.
        # endpoint_at maps "/steptrace" etc. -> monotonic stamp of the
        # last SUCCESSFUL fetch; endpoint_err keeps the last per-
        # endpoint error so health can say which plane went dark.
        self.endpoint_at: Dict[str, float] = {}
        self.endpoint_err: Dict[str, str] = {}
        # delta-scrape cursors (ISSUE 18): path -> last next_since (or
        # max useq for /audit) this aggregator has ingested from this
        # peer incarnation
        self.since: Dict[str, int] = {}


class TelemetryAggregator:
    """Scrapes every worker's telemetry endpoint, keeps the merged
    cluster snapshot, publishes straggler signals."""

    def __init__(
        self,
        interval: Optional[float] = None,
        timeout: float = 2.0,
        registry: Optional[metrics.Registry] = None,
        scorer: Optional[StragglerScorer] = None,
        rtt_scorer: Optional[StragglerScorer] = None,
        fetch: Optional[Callable[[str, str, float], Tuple[bytes, dict]]] = None,
    ):
        self.interval = interval if interval is not None else scrape_interval()
        self.timeout = timeout
        # injectable transport (ISSUE 18): fetch(base_url, path, timeout)
        # -> (body_bytes, headers_dict). The k=256 harness swaps in an
        # in-process hook (256 real HTTP servers per test is a fork
        # bomb); production uses urllib. RTT/clock/payload accounting
        # stays in _fetch either way.
        self._transport = fetch
        self._peers: Dict[str, PeerState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scorer = scorer or StragglerScorer()
        # RTT outliers: interconnect trouble shows up before step times
        # do; a laxer z with a hard ratio floor suits the heavier tail
        self.rtt_scorer = rtt_scorer or StragglerScorer(
            window=8, z_threshold=3.0, ratio_threshold=2.0
        )
        self._flagged: set = set()
        self._rtt_flagged: set = set()
        # the measured cause behind each currently-flagged straggler
        # (network/compute/unknown), classified once at the flag
        # transition — /cluster/health serves it so `info top` renders
        # the same cause the audit event recorded
        self._causes: Dict[str, str] = {}
        self._scraped_at: Optional[float] = None  # wall time of last sweep
        # crash forensics (ISSUE 3): postmortems harvested by the
        # watcher, served at /cluster/postmortem. Deliberately NOT keyed
        # off the scrape membership — dead peers leave the cluster, and
        # their postmortems are the entire point. Bounded overall.
        self._postmortems: "collections.deque" = collections.deque(maxlen=64)
        # a PRIVATE registry by default, not the process-global one: the
        # runner's own transport metrics carry peer labels that mean "a
        # remote peer of the runner" — mixing them into the federated
        # page (where peer means "the scraped worker") would make the
        # label ambiguous. /cluster/metrics appends this exposition.
        reg = registry if registry is not None else metrics.Registry()
        self.registry = reg
        self._g_step_rate = reg.gauge(
            "kungfu_cluster_step_rate",
            "Steps/sec per peer, from scrape-to-scrape deltas",
            ("peer",),
        )
        self._g_step_time = reg.gauge(
            "kungfu_cluster_step_time_seconds",
            "Windowed step-time quantiles per peer",
            ("peer", "quantile"),
        )
        self._g_score = reg.gauge(
            "kungfu_cluster_straggler_score",
            "Robust z-score of each peer's step time vs the cluster median",
            ("peer",),
        )
        self._g_stragglers = reg.gauge(
            "kungfu_cluster_stragglers",
            "Number of peers currently flagged as stragglers",
        )
        self._g_age = reg.gauge(
            "kungfu_cluster_scrape_age_seconds",
            "Seconds since the last successful scrape per peer",
            ("peer",),
        )
        self._c_scrapes = reg.counter(
            "kungfu_cluster_scrapes_total",
            "Aggregator scrape sweeps completed",
        )
        self._c_errors = reg.counter(
            "kungfu_cluster_scrape_errors_total",
            "Failed peer scrapes",
            ("peer",),
        )
        # aggregator self-observability (ISSUE 18): the telemetry plane
        # watches itself — at k=256 the aggregator is the next
        # bottleneck, and "the monitoring is down" must be a measured
        # fact, not a dashboard gap
        self._g_sweep_s = reg.gauge(
            "kungfu_aggregator_sweep_seconds",
            "Wall-clock duration of the last scrape sweep",
        )
        self._g_scraped = reg.gauge(
            "kungfu_aggregator_scraped_peers",
            "Peers successfully scraped in the last sweep",
        )
        self._g_stale = reg.gauge(
            "kungfu_aggregator_stale_peers",
            "Peers whose last successful scrape is older than twice the "
            "effective interval",
        )
        self._c_payload = reg.counter(
            "kungfu_aggregator_payload_bytes_total",
            "Bytes fetched from workers, by endpoint",
            ("endpoint",),
        )
        self._c_deadline = reg.counter(
            "kungfu_aggregator_deadline_misses_total",
            "Peer scrapes still in flight when their sweep deadline "
            "passed",
        )
        # step plane (ISSUE 13): merged per-step critical-path records,
        # refreshed from every worker's /steptrace on each sweep
        self._steps: "collections.deque" = collections.deque(maxlen=STEP_KEEP)
        self._steps_last: Optional[Tuple[int, int]] = None  # newest (e, r)
        self._crit_streak: Tuple[Optional[Tuple[str, str]], int] = (None, 0)
        # delta mode only (ISSUE 18): flushed-but-unpublished timelines
        # per peer — a ?since= scrape ships each timeline once, but the
        # merge holds the globally-newest round back, so held-back
        # deltas must pool here until a newer round releases them
        self._steps_pending: Dict[str, Dict[Tuple[int, int], dict]] = {}
        # decision plane (ISSUE 15): every worker's /decisions ledger
        # merged into one causal timeline, keyed (peer, seq, open wall
        # time) so a later scrape of the SAME record (now closed, or
        # regressed) updates it in place instead of duplicating it —
        # while a RESPAWNED worker's fresh ledger (seq restarting at 0
        # on the same label) cannot overwrite the dead incarnation's
        # records: its records carry new open stamps. Bounded like a
        # ring: oldest merged entries drop past KF_DECISION_KEEP.
        self._decisions: Dict[Tuple[str, int, float], dict] = {}
        _dkeep = int(knobs.get("KF_DECISION_KEEP"))
        self._decisions_keep = _dkeep if _dkeep > 0 else 64
        # resource plane (ISSUE 16): the latest merged cluster view of
        # every worker's /resources document — a CURRENT-STATE view
        # (like health), so each refresh REPLACES it wholesale: a dead
        # peer's frozen saturation flag steering straggler causes or
        # the replan clamp hours later would be worse than no data
        self._resources: dict = {}
        # memory plane (ISSUE 17): same current-state contract as the
        # resource plane — each refresh replaces the merged view
        self._memory: dict = {}
        # one refresh unit per merged plane (ISSUE 18 satellite): each
        # used to carry its own refresh lock + monotonic stamp + inline
        # staleness block; _RefreshedPlane is that block, once. The
        # plane names keep the historical log strings ("inline step
        # refresh failed").
        eff = self.effective_interval
        self._planes: Dict[str, _RefreshedPlane] = {
            "steps": _RefreshedPlane(
                "step", self._refresh_steps_locked, eff),
            "decisions": _RefreshedPlane(
                "decision", self._refresh_decisions_locked, eff),
            "resources": _RefreshedPlane(
                "resource", self._refresh_resources_locked, eff),
            "memory": _RefreshedPlane(
                "memory", self._refresh_memory_locked, eff),
        }
        # scale mode (ISSUE 18 tentpole): flat below KF_AGG_HIER_MIN_PEERS
        # (exact historical behavior), hierarchical/sampled/delta above
        self._scale = False
        self._hier_active = False
        self._last_sweep_s: Optional[float] = None
        self._sweep_mono: Optional[float] = None
        self._backoff = 1.0  # interval multiplier while the plane is hot
        # sampled link matrix (scale mode): src -> (row, monotonic_at,
        # wall_at); only the rotation slice + the retained slowest-edge
        # rows re-ingest per sweep, so merge cost is O(k), not O(k^2)
        self._link_cache: Dict[str, Tuple[dict, float, float]] = {}
        self._link_sweep = 0
        self._ingested_links: List[str] = []  # srcs refreshed this sweep
        self._slow_edges: List[dict] = []  # retained slowest edges
        # host digests (hier mode): plane path -> {label: doc} pulled
        # via the heads' /host/telemetry this sweep, consumed by the
        # plane refreshes in place of direct per-worker fetches
        self._digest_planes: Dict[str, Dict[str, dict]] = {}
        self._digest_at: Optional[float] = None
        # delta-audit cache (scale mode): (peer, kind, seq) -> record;
        # ?since= scrapes ship only new/annotated records, so the
        # merged view must accumulate (bounded below)
        self._audit_cache: Dict[Tuple, dict] = {}
        self._audit_cache_keep = 4096

        # the aggregator's own tracked state is a long-lived buffer
        # owner too: account it under the runner's `telemetry` bucket
        # (weakref — the registry must never pin a stopped aggregator)
        def _footprint(ref=weakref.ref(self)) -> Optional[int]:
            agg = ref()
            return agg.footprint_bytes() if agg is not None else None

        self._mem_acct = tmemory.register_accountant(
            "aggregator", "telemetry", _footprint
        )
        self._g_step_overlap = reg.gauge(
            "kungfu_step_overlap_ratio",
            "Latest merged step's overlap fraction: scheduler-busy comm "
            "time hidden under caller compute / total comm time",
        )
        self._g_step_critical = reg.gauge(
            "kungfu_step_critical_seconds",
            "Latest merged step's critical-path blocking seconds, "
            "labelled with the elected (peer, edge)",
            ("peer", "edge"),
        )

    # -- membership ----------------------------------------------------
    @staticmethod
    def targets_for_workers(workers) -> List[Tuple[str, str]]:
        """PeerIDs -> (label, telemetry base URL) on peer_port+10000."""
        out = []
        for w in workers:
            port = w.port + 10000
            if port > 65535:
                # mirror of the worker-side OverflowError guard in
                # peer.py — but say so: an invisible peer can never be
                # flagged, and a silent skip reads as a healthy cluster
                log.warn(
                    "cluster: %s has no telemetry port (peer_port+10000 "
                    "> 65535); excluded from the cluster plane", w,
                )
                continue
            out.append((str(w), f"http://{w.host}:{port}"))
        return out

    def set_peers(self, targets: Sequence[Tuple[str, str]]) -> None:
        """Replace the scrape set (the watcher calls this on every
        Stage). Surviving peers keep their scrape history and clock
        offsets; departed peers drop out of the scorers so they can't
        skew the population as ghosts."""
        with self._lock:
            fresh: Dict[str, PeerState] = {}
            for label, url in targets:
                st = self._peers.get(label)
                if st is None or st.url != url.rstrip("/"):
                    st = PeerState(label, url)
                fresh[label] = st
            self._peers = fresh
            # scale-mode caches follow the membership: a departed
            # peer's sampled row or pooled timelines must not survive
            # it (its audit history MAY — that log is the point)
            for cache in (self._link_cache, self._steps_pending):
                for label in list(cache):
                    if label not in fresh:
                        del cache[label]
            self._slow_edges = [
                e for e in self._slow_edges if e["src"] in fresh
            ]
        live = list(fresh)
        self.scorer.forget(live)
        self.rtt_scorer.forget(live)
        self._flagged &= set(live)
        self._rtt_flagged &= set(live)
        # per-peer gauge children follow the membership (bounded
        # cardinality across elastic resizes)
        for g in (self._g_step_rate, self._g_step_time, self._g_score,
                  self._g_age):
            g.clear_children()

    def peers(self) -> List[PeerState]:
        with self._lock:
            return list(self._peers.values())

    # -- scale mode ----------------------------------------------------
    def effective_interval(self) -> float:
        """The interval the plane is actually running at: the
        configured interval times the overload backoff multiplier."""
        return self.interval * self._backoff

    def _scale_mode(self, k: int) -> bool:
        thresh = hier_min_peers()
        return thresh > 0 and k >= thresh

    def _delta_enabled(self) -> bool:
        """Whether ring-backed endpoints scrape with ?since= cursors:
        KF_AGG_DELTA on/off forces it, auto (the default) follows
        scale mode — below the threshold the flat plane stays
        byte-identical to its historical behavior."""
        mode = str(knobs.get("KF_AGG_DELTA"))
        if mode == "on":
            return True
        if mode == "off":
            return False
        return self._scale

    # -- scraping ------------------------------------------------------
    def _fetch(
        self, st: PeerState, path: str, record_rtt: bool = True
    ) -> Tuple[bytes, dict]:
        """GET one peer endpoint. record_rtt=False for the on-demand
        trace/audit pulls: their multi-MB bodies measure transfer time,
        not the network — writing that into rtt_s would paint a phantom
        'network problem' in /cluster/health whenever someone looks at
        traces (the clock-offset update stays safe either way: it only
        accepts estimates that BEAT the best RTT seen)."""
        endpoint = path.split("?", 1)[0]
        t0 = time.perf_counter()
        try:
            if self._transport is not None:
                body, headers = self._transport(st.url, path, self.timeout)
                clock = headers.get(CLOCK_HEADER)
            else:
                with urllib.request.urlopen(
                    st.url + path, timeout=self.timeout
                ) as r:
                    body = r.read()
                    clock = r.headers.get(CLOCK_HEADER)
        except (OSError, ValueError) as e:
            st.endpoint_err[endpoint] = str(e)
            raise
        t1 = time.perf_counter()
        rtt = t1 - t0
        if record_rtt:
            st.rtt_s = rtt
        _note_clock(st, rtt, clock, t0, t1)
        st.endpoint_at[endpoint] = time.monotonic()
        st.endpoint_err.pop(endpoint, None)
        self._c_payload.labels(endpoint).inc(len(body))
        return body, {"rtt_s": rtt}

    def _mark_scrape_failed(self, st: PeerState, err) -> None:
        """Null a peer's derived state on scrape failure. A peer that
        stopped answering must not keep serving its last-known-healthy
        numbers: a dashboard or policy reading step_rate would see a
        live peer hours after it died. The delta baselines reset too,
        so a comeback doesn't compute a rate smeared across the outage
        — and its SCORER series goes with it: a frozen window would
        keep the peer flagged (or keep skewing the population) off
        hours-old data, and straggler_cleared would never fire. The
        window rebuilds within min_samples scrapes if the endpoint
        comes back."""
        st.last_error = str(err)
        st.errors += 1
        self._c_errors.labels(st.label).inc()
        st.step_rate = st.step_p50 = st.step_p99 = None
        st.compute_mean = None
        st.prev_steps = st.prev_t = None
        st.prev_hist = None
        st.prev_coll_sum = None
        # the CUMULATIVE snapshots go too, not just the prev_*
        # baselines: the success path copies current into prev_*
        # before overwriting, so a surviving pre-outage snapshot
        # would become the baseline for a possibly-restarted worker
        # — cross-epoch deltas (negative buckets, garbage quantiles)
        # once the new epoch's counts pass the old ones
        st.steps_total = None
        st.step_hist = None
        st.coll_sum = None
        # the frozen exposition page goes too: cluster_metrics()
        # federates whatever is stored, and a dead peer's last page
        # would keep it looking alive on the Prometheus view
        st.metrics_text = ""
        # and its link row: a dead peer's frozen bandwidth estimates
        # would keep steering topology re-planning hours later
        st.links = {}
        st.ring_pos = st.ring_next = st.ring_role = None
        st.wire_mode = None
        # scale mode: the sampled-matrix cache row too, for the same
        # reason (and a dead incarnation's delta cursors are garbage
        # to the respawn's restarted seq spaces)
        with self._lock:
            self._link_cache.pop(st.label, None)
        st.since.clear()
        self.scorer.drop(st.label)
        self.rtt_scorer.drop(st.label)

    def _apply_parsed(self, st: PeerState, parsed: dict, now: float) -> None:
        """Fold one parsed /metrics page (parse_worker_page output —
        local or shipped pre-parsed in a host digest) into the peer's
        derived state: scrape-to-scrape rates, windowed quantiles and
        the straggler scorers."""
        st.prev_steps, st.prev_hist = st.steps_total, st.step_hist
        st.prev_coll_sum = st.coll_sum
        st.steps_total = parsed.get("steps_total")
        st.step_hist = parsed.get("step_hist")
        st.links = parsed.get("links") or {}
        st.ring_pos = parsed.get("ring_pos")
        st.ring_next = parsed.get("ring_next")
        st.ring_role = parsed.get("ring_role")
        st.wire_mode = parsed.get("wire_mode")
        st.coll_sum = parsed.get("coll_sum")
        st.bytes_tx, st.bytes_rx = parsed.get("bytes_tx"), parsed.get("bytes_rx")
        st.reported_rtt = parsed.get("reported_rtt")
        # step rate + windowed quantiles from scrape-to-scrape deltas
        if (
            st.steps_total is not None
            and st.prev_steps is not None
            and st.prev_t is not None
            and now > st.prev_t
            and st.steps_total >= st.prev_steps  # restart resets to 0
        ):
            st.step_rate = (st.steps_total - st.prev_steps) / (now - st.prev_t)
        st.prev_t = now
        if st.step_hist is not None:
            window = st.step_hist.delta(st.prev_hist)
            if window.count > 0:
                st.step_p50 = window.quantile(0.50)
                st.step_p99 = window.quantile(0.99)
                step_mean = window.sum / window.count
                # score COMPUTE time (step minus collective wait) when
                # the worker publishes collective latencies: under
                # synchronous training wall-clock step times converge to
                # the slowest peer's, and the straggler is the one whose
                # time went to compute instead of waiting
                compute = step_mean
                if (
                    st.coll_sum is not None
                    and st.prev_coll_sum is not None
                    and st.coll_sum >= st.prev_coll_sum  # restart guard
                ):
                    wait = (st.coll_sum - st.prev_coll_sum) / window.count
                    compute = max(step_mean - wait, 0.0)
                st.compute_mean = compute
                self.scorer.observe(st.label, compute)
        # outlier scoring uses ONLY the worker-published probe RTTs
        # (kungfu_peer_rtt_seconds): the HTTP scrape duration measures
        # TCP setup + body transfer, an order of magnitude above a probe
        # RTT — mixing the two in one population would flag any peer
        # that simply hasn't probed yet. The scrape RTT stays visible in
        # health as rtt_ms, it just doesn't vote.
        if st.reported_rtt is not None:
            self.rtt_scorer.observe(st.label, st.reported_rtt)

    def _scrape_peer(self, st: PeerState) -> None:
        now = time.monotonic()
        try:
            body, _ = self._fetch(st, "/metrics")
        except (OSError, ValueError) as e:
            self._mark_scrape_failed(st, e)
            return
        st.scrapes += 1
        st.last_ok = now
        st.last_error = ""
        st.metrics_text = body.decode(errors="replace")
        self._apply_parsed(st, parse_worker_page(st.metrics_text), now)

    # -- hierarchical fan-in (ISSUE 18 tentpole) ------------------------
    @staticmethod
    def _host_groups(
        targets: Sequence[PeerState],
    ) -> Optional[Dict[str, List[PeerState]]]:
        """Group scrape targets by URL hostname — the same host grouping
        targets_for_workers encodes. None when any URL fails to parse
        (fall back to the flat sweep rather than sweep half a cluster
        hierarchically)."""
        groups: Dict[str, List[PeerState]] = {}
        for st in targets:
            host = urlsplit(st.url).hostname
            if not host:
                return None
            groups.setdefault(host, []).append(st)
        return groups

    def _sweep_host(
        self, sts: List[PeerState],
        digest_planes: Dict[str, Dict[str, dict]],
    ) -> None:
        """Sweep one host through its elected head's /host/telemetry
        digest: one fetch replaces len(sts) x len(planes) direct
        fetches, with the head's pre-parsed summaries saving the root
        the pure-Python exposition parse. Election is deterministic on
        both sides (lowest label on the host), so no coordination
        round: a head that isn't serving the role yet (or died) answers
        {"enabled": false} / an error, and the whole host falls back to
        direct scrapes this sweep."""
        head = min(sts, key=lambda s: s.label)
        doc = None
        if len(sts) > 1:
            try:
                body, _ = self._fetch(head, HOST_DIGEST_PATH)
                doc = json.loads(body.decode())
            except (OSError, ValueError):
                doc = None
        if not isinstance(doc, dict) or not doc.get("enabled") \
                or not isinstance(doc.get("workers"), dict):
            for st in sts:
                self._scrape_peer(st)
            return
        now = time.monotonic()
        head_off = head.clock_offset_us or 0.0
        workers = doc["workers"]
        by_label = {st.label: st for st in sts}
        for label, st in by_label.items():
            w = workers.get(label)
            if not isinstance(w, dict):
                # the head doesn't know this worker (membership skew
                # between root and head): scrape it directly rather
                # than black-hole it for a sweep
                self._scrape_peer(st)
                continue
            err = w.get("error")
            if err:
                self._mark_scrape_failed(st, err)
                continue
            st.scrapes += 1
            st.last_ok = now
            st.last_error = ""
            # two-hop NTP composition: offset(root->worker) =
            # offset(root->head) + offset(head->worker); each hop's
            # error is bounded by its RTT/2, so the composed error is
            # bounded by the SUM of the hop bounds
            off_hw = w.get("clock_offset_us")
            if isinstance(off_hw, (int, float)):
                st.clock_offset_us = head_off + off_hw
            rtt = w.get("rtt_s")
            if isinstance(rtt, (int, float)):
                st.rtt_s = rtt
            st.metrics_text = w.get("metrics_text") or ""
            if st.metrics_text:
                st.endpoint_at["/metrics"] = now
            self._apply_parsed(
                st, parsed_from_doc(w.get("parsed") or {}), now
            )
            for path, key in (
                ("/steptrace", "steptrace"),
                ("/decisions", "decisions"),
                ("/resources", "resources"),
                ("/memory", "memory"),
            ):
                pd = w.get(key)
                if isinstance(pd, dict):
                    digest_planes[path][label] = pd
                    st.endpoint_at[path] = now
                    st.endpoint_err.pop(path, None)
                else:
                    st.endpoint_err[path] = "missing from host digest"

    def _run_staggered(self, jobs: List[Tuple[str, Callable[[], None]]]) -> int:
        """Run scrape jobs in parallel with staggered per-job deadlines
        spread across the sweep budget (ISSUE 18): every job still gets
        at least the HTTP timeout, but the join points are spaced so
        one slow peer can't absorb the whole budget before the others
        are even checked. Returns the number of deadline misses (jobs
        still in flight when their deadline passed — the threads are
        daemons and finish on their own; the miss is counted and the
        peer reads as stale until it lands)."""
        budget = self.timeout + 1.0
        if self._scale and self.interval > 0:
            # scale mode budgets the sweep against the scrape interval:
            # at k=256 one unreachable host must not stall the plane
            # past its own cadence
            budget = min(budget, max(self.interval, 0.5))
        t0 = time.monotonic()
        threads = [
            threading.Thread(target=fn, name=f"kf-scrape-{label}",
                             daemon=True)
            for label, fn in jobs
        ]
        if not threads:
            return 0
        for t in threads:
            t.start()
        misses = 0
        n = len(threads)
        for i, t in enumerate(threads):
            deadline = t0 + budget * (i + 1) / n
            t.join(max(0.0, deadline - time.monotonic()))
        # one final grace pass at the full budget: the stagger bounds
        # the SWEEP, not any single fetch
        final = t0 + budget
        for t in threads:
            t.join(max(0.0, final - time.monotonic()))
            if t.is_alive():
                misses += 1
                self._c_deadline.inc()
        return misses

    def scrape_once(self) -> dict:
        """One sweep over every target (parallel, bounded by the HTTP
        timeout), then re-score stragglers and publish. Returns the
        fresh health snapshot. A peer whose previous scrape thread is
        still in flight (a server dripping bytes under the timeout) is
        skipped this sweep — two threads swapping the same peer's
        prev/current baselines would corrupt its rates.

        Scale mode (ISSUE 18, at or above KF_AGG_HIER_MIN_PEERS
        targets): hosts with an elected head are swept via ONE
        /host/telemetry digest each (O(hosts) fan-in, offsets composed
        across the two hops), the link matrix ingests only the rotation
        slice plus the retained slowest edges, and the sweep is
        budgeted against the scrape interval with the loop backing off
        when it runs hot. Below the threshold the flat sweep is the
        exact historical behavior."""
        t_start = time.perf_counter()
        targets = self.peers()
        self._scale = self._scale_mode(len(targets))
        groups = self._host_groups(targets) if self._scale else None
        hier = groups is not None and any(
            len(g) > 1 for g in groups.values()
        )

        def scrape_and_clear(st: PeerState) -> None:
            try:
                self._scrape_peer(st)
            finally:
                st.inflight = False

        jobs: List[Tuple[str, Callable[[], None]]] = []
        if hier:
            digest_planes: Dict[str, Dict[str, dict]] = {
                "/steptrace": {}, "/decisions": {},
                "/resources": {}, "/memory": {},
            }
            for host in sorted(groups):
                sts = [st for st in groups[host] if not st.inflight]
                if not sts:
                    continue
                for st in sts:
                    st.inflight = True

                def sweep_host(sts=sts):
                    try:
                        self._sweep_host(sts, digest_planes)
                    finally:
                        for st in sts:
                            st.inflight = False

                jobs.append((host, sweep_host))
        else:
            for st in targets:
                if st.inflight:
                    continue
                st.inflight = True
                jobs.append(
                    (st.label,
                     lambda st=st: scrape_and_clear(st))
                )
        misses = self._run_staggered(jobs)
        if hier:
            with self._lock:
                self._digest_planes = digest_planes
                self._digest_at = time.monotonic()
        self._hier_active = hier
        self._c_scrapes.inc()
        self._scraped_at = time.time()
        if self._scale:
            self._ingest_links_sampled(targets)
        for plane in self._planes.values():
            try:
                plane.refresh()
            except Exception as e:  # noqa: BLE001 - the sweep must outlive a bad merge
                log.warn(
                    "cluster: %s-plane refresh failed: %s", plane.name, e
                )
        self._publish()
        sweep_s = time.perf_counter() - t_start
        self._note_sweep(sweep_s, len(targets), misses)
        return self.cluster_health()

    def _note_sweep(self, sweep_s: float, k: int, misses: int) -> None:
        """Publish the aggregator's self-observability gauges and run
        the overload backoff: a sweep that overruns the interval means
        the plane can't keep up at this cadence — double the effective
        interval (audited, bounded by KF_AGG_MAX_BACKOFF) rather than
        let sweeps pile onto each other; recover by halving once
        sweeps drop under half the interval again."""
        self._last_sweep_s = sweep_s
        self._sweep_mono = time.monotonic()
        stale = self._stale_peers()
        self._g_sweep_s.set(round(sweep_s, 6))
        self._g_scraped.set(k - len(stale))
        self._g_stale.set(len(stale))
        # the backoff loop is a scale-mode behavior: flat test rigs run
        # millisecond intervals where any real sweep would read as an
        # overload, and flat mode's contract is exact historical
        # behavior
        if not self._scale or self.interval <= 0:
            return
        if sweep_s > self.interval or misses > 0 and sweep_s > 0.8 * self.interval:
            try:
                max_backoff = float(knobs.get("KF_AGG_MAX_BACKOFF"))
            except (TypeError, ValueError):
                max_backoff = 8.0
            nb = min(self._backoff * 2.0, max(1.0, max_backoff))
            if nb > self._backoff:
                self._backoff = nb
                audit.record_event(
                    "aggregator_overload",
                    trigger="cluster_scrape",
                    sweep_s=round(sweep_s, 3),
                    interval_s=self.interval,
                    effective_interval_s=round(self.interval * nb, 3),
                    peers=k,
                    deadline_misses=misses,
                )
        elif sweep_s < 0.5 * self.interval and self._backoff > 1.0:
            self._backoff = max(1.0, self._backoff / 2.0)

    def _stale_peers(self) -> List[str]:
        """Labels whose last successful scrape is older than twice the
        effective interval (or that never succeeded)."""
        now = time.monotonic()
        horizon = 2.0 * max(self.effective_interval(), 1e-9)
        return sorted(
            st.label for st in self.peers()
            if st.last_ok is None or now - st.last_ok > horizon
        )

    # -- sampled link matrix (ISSUE 18 tentpole) ------------------------
    def _ingest_links_sampled(self, targets: Sequence[PeerState]) -> None:
        """Scale-mode link ingest: refresh only a rotating slice of
        source rows per sweep (every row within KF_AGG_LINK_ROTATION_SWEEPS
        sweeps) PLUS the sources of the retained top-N slowest edges —
        the edges steering re-planning can never rotate out of
        freshness. Cache rows carry their ingest stamps, so consumers
        see per-row age instead of mistaking a sampled matrix for a
        fresh one."""
        labels = sorted(st.label for st in targets)
        by_label = {st.label: st for st in targets}
        k = len(labels)
        if k == 0:
            with self._lock:
                self._link_cache.clear()
                self._slow_edges = []
                self._ingested_links = []
            return
        try:
            rot = int(knobs.get("KF_AGG_LINK_ROTATION_SWEEPS"))
        except (TypeError, ValueError):
            rot = 8
        rot = max(1, rot)
        try:
            top_n = int(knobs.get("KF_AGG_LINK_TOP_EDGES"))
        except (TypeError, ValueError):
            top_n = 16
        rows_per = max(1, math.ceil(k / rot))
        start = (self._link_sweep * rows_per) % k
        chosen = {
            labels[(start + i) % k] for i in range(min(rows_per, k))
        }
        chosen |= {
            e["src"] for e in self._slow_edges if e["src"] in by_label
        }
        now_m = time.monotonic()
        now_w = time.time()
        ingested = []
        with self._lock:
            # departed peers' rows go first: a dead source must not
            # keep its frozen row in the election
            for src in list(self._link_cache):
                if src not in by_label:
                    del self._link_cache[src]
            for src in sorted(chosen):
                st = by_label[src]
                if st.links:
                    row = {dst: dict(info) for dst, info in st.links.items()}
                    self._link_cache[src] = (row, now_m, now_w)
                    ingested.append(src)
                elif st.last_error:
                    self._link_cache.pop(src, None)
            self._link_sweep += 1
            self._ingested_links = ingested
            # re-elect the retained slowest edges over the whole cache:
            # O(cached edges) = O(k x row), done once per sweep
            cand = []
            for src, (row, at, _) in self._link_cache.items():
                for dst, info in row.items():
                    bw = info.get("bw")
                    if isinstance(bw, (int, float)) and bw > 0:
                        cand.append(
                            {"src": src, "dst": dst, "bw": bw,
                             "at": at}
                        )
            cand.sort(key=lambda e: e["bw"])
            self._slow_edges = cand[:max(0, top_n)]

    def _link_cache_view(self) -> Tuple[Dict[str, dict], Dict[str, float]]:
        """(rows, per-row age seconds) snapshot of the sampled cache."""
        now_m = time.monotonic()
        with self._lock:
            rows = {src: row for src, (row, _, _) in self._link_cache.items()}
            ages = {
                src: round(now_m - at, 3)
                for src, (_, at, _) in self._link_cache.items()
            }
        return rows, ages

    def _publish(self) -> None:
        scores = self.scorer.scores()
        rtt_scores = self.rtt_scorer.scores()
        flagged = {p for p, s in scores.items() if s.flagged}
        rtt_flagged = {p for p, s in rtt_scores.items() if s.flagged}
        cluster_median = self.scorer.cluster_median()
        # rebuild the per-peer gauge children every sweep: set() without
        # a clear would leave a dead peer's last-known-healthy values
        # frozen in the exposition forever (the JSON view nulls them,
        # and the metrics view must agree)
        for g in (self._g_step_rate, self._g_step_time, self._g_score):
            g.clear_children()
        for st in self.peers():
            if st.step_rate is not None:
                self._g_step_rate.labels(st.label).set(st.step_rate)
            if st.step_p50 is not None:
                self._g_step_time.labels(st.label, "0.5").set(st.step_p50)
            if st.step_p99 is not None:
                self._g_step_time.labels(st.label, "0.99").set(st.step_p99)
            sc = scores.get(st.label)
            if sc is not None:
                self._g_score.labels(st.label).set(sc.score)
            if st.last_ok is not None:
                self._g_age.labels(st.label).set(
                    time.monotonic() - st.last_ok
                )
        self._g_stragglers.set(len(flagged))
        # audit on TRANSITIONS only: the log answers "when did peer X
        # become slow", not "is it still slow every 5 seconds"
        newly_flagged = sorted(flagged - self._flagged)
        links_doc = None
        steps: List[dict] = []
        resources: Optional[dict] = None
        memory: Optional[dict] = None
        if newly_flagged:
            # measured attribution for the event (ISSUE 13 satellite +
            # ISSUE 16/17 causes): the step plane's elected edge when
            # this peer was recently critical, else the memory plane's
            # thrash flag, the resource plane's saturation view, else
            # the slowest link touching it — all inputs computed once
            # per transition batch, never per peer
            links_doc = tlink.merge_matrix(
                {st.label: st.links for st in self.peers()},
                copy_edges=False,
            )
            with self._lock:
                steps = list(self._steps)
                resources = self._resources or None
                memory = self._memory or None
        for peer in newly_flagged:
            sc = scores[peer]
            cause, edge = tstraggler.classify_cause(
                peer, steps, links_doc, resources, memory
            )
            self._causes[peer] = cause
            log.warn(
                "cluster: straggler detected: %s step_time=%.1fms "
                "(cluster median %.1fms, z=%.1f, cause=%s, blocking edge %s)",
                peer, sc.value * 1e3, (cluster_median or 0) * 1e3, sc.score,
                cause,
                "->".join(str(e) for e in edge) if edge else "unknown",
            )
            audit.record_event(
                "straggler",
                peer=peer,
                trigger="cluster_scrape",
                score=round(sc.score, 2),
                step_time_ms=round(sc.value * 1e3, 3),
                cluster_median_ms=round((cluster_median or 0) * 1e3, 3),
                blocking_edge=edge,
                cause=cause,
            )
        for peer in sorted(self._flagged - flagged):
            self._causes.pop(peer, None)
            audit.record_event(
                "straggler_cleared", peer=peer, trigger="cluster_scrape"
            )
        for peer in sorted(rtt_flagged - self._rtt_flagged):
            sc = rtt_scores[peer]
            audit.record_event(
                "rtt_outlier",
                peer=peer,
                trigger="cluster_scrape",
                score=round(sc.score, 2),
                rtt_ms=round(sc.value * 1e3, 3),
            )
        self._flagged = flagged
        self._rtt_flagged = rtt_flagged

    # -- background loop -----------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            # wait the EFFECTIVE interval: the overload backoff slows
            # the loop down rather than queueing hot sweeps
            while not self._stop.wait(self.effective_interval()):
                try:
                    self.scrape_once()
                except Exception as e:  # noqa: BLE001 - the plane must outlive a bad sweep
                    log.warn("cluster: scrape sweep failed: %s", e)

        self._thread = threading.Thread(
            target=loop, name="kf-cluster-scrape", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(self.timeout + 1.0)
        self._mem_acct.close()

    # -- merged views ---------------------------------------------------
    def cluster_metrics(self) -> str:
        """Federated exposition of every worker's last-scraped /metrics,
        plus the aggregator's own registry (the kungfu_cluster_* gauges
        and scrape counters — already peer-labelled, no injection) so
        one Prometheus target sees the whole plane."""
        pages: List[Tuple[Optional[str], str]] = [
            (st.label, st.metrics_text)
            for st in self.peers()
            if st.metrics_text
        ]
        pages.append((None, self.registry.render()))
        return promparse.merge_expositions(pages)

    def _fetch_all(
        self, path: str, since_key: Optional[str] = None
    ) -> List[Tuple["PeerState", bytes]]:
        """Parallel fetch of one endpoint from every peer (the serial
        version made /cluster/trace block for N x timeout with a few
        unreachable workers — at exactly the moment an operator is
        debugging a sick cluster). Failures record last_error and drop
        out of the result. since_key appends each peer's stored delta
        cursor as ?since= (ISSUE 18) — callers pass it ONLY in delta
        mode, so flat-mode test stubs keep the historical
        one-positional-argument signature."""
        targets = sorted(self.peers(), key=lambda s: s.label)
        results: List[Optional[bytes]] = [None] * len(targets)

        def one(i: int, st: PeerState) -> None:
            p = path
            if since_key is not None:
                cur = st.since.get(since_key)
                if cur is not None:
                    p = f"{path}?since={cur}"
            try:
                body, _ = self._fetch(st, p, record_rtt=False)
                results[i] = body
            except (OSError, ValueError) as e:
                st.last_error = str(e)

        threads = [
            threading.Thread(target=one, args=(i, st), daemon=True)
            for i, st in enumerate(targets)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.timeout + 1.0)
        return [
            (st, body) for st, body in zip(targets, results) if body is not None
        ]

    def cluster_trace(self) -> dict:
        """Live-fetch every worker's /trace and merge onto the runner's
        monotonic timeline: each peer becomes a Chrome-trace process
        (pid = peer index, process_name metadata), and its timestamps
        shift by the estimated clock offset so cross-peer causality
        (e.g. "every peer's allreduce stalls when peer 3 is late") is
        visible in one view."""
        merged: List[dict] = []
        for idx, (st, body) in enumerate(self._fetch_all("/trace")):
            try:
                doc = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            offset = st.clock_offset_us or 0.0
            merged.append({
                "name": "process_name", "ph": "M", "pid": idx, "tid": 0,
                "args": {"name": st.label},
            })
            merged.append({
                "name": "process_sort_index", "ph": "M", "pid": idx,
                "tid": 0, "args": {"sort_index": idx},
            })
            for ev in doc.get("traceEvents", []):
                ev = dict(ev)
                ev["pid"] = idx
                if isinstance(ev.get("ts"), (int, float)):
                    ev["ts"] = ev["ts"] + offset
                merged.append(ev)
        return {"traceEvents": merged, "displayTimeUnit": "ms"}

    def cluster_audit(self) -> List[dict]:
        """Merged audit timeline: every worker's /audit plus the
        runner's own records, sorted by wall time. Delta mode (ISSUE
        18): each pull ships only records created or annotated past the
        per-peer cursor, accumulated in a bounded cache keyed (peer,
        kind, seq) — an annotated record (new useq, same seq) updates
        its cached copy in place."""
        if self._delta_enabled():
            for st, body in self._fetch_all("/audit", since_key="/audit"):
                try:
                    peer_records = json.loads(body.decode())
                except ValueError:
                    continue
                for rec in peer_records:
                    rec = dict(rec)
                    rec.setdefault("peer", st.label)
                    useq = rec.get("useq")
                    if isinstance(useq, (int, float)):
                        st.since["/audit"] = max(
                            st.since.get("/audit", 0), int(useq)
                        )
                    key = (
                        rec.get("peer", ""), rec.get("kind", ""),
                        rec.get("seq"), rec.get("wall_time"),
                    )
                    with self._lock:
                        self._audit_cache[key] = rec
            with self._lock:
                if len(self._audit_cache) > self._audit_cache_keep:
                    ordered = sorted(
                        self._audit_cache.items(),
                        key=lambda kv: kv[1].get("wall_time", 0.0),
                    )
                    for key, _ in ordered[:-self._audit_cache_keep]:
                        del self._audit_cache[key]
                records = list(audit.to_json()) + [
                    dict(r) for r in self._audit_cache.values()
                ]
            records.sort(key=lambda r: r.get("wall_time", 0.0))
            return records
        records = list(audit.to_json())
        for st, body in self._fetch_all("/audit"):
            try:
                peer_records = json.loads(body.decode())
            except ValueError:
                continue
            for rec in peer_records:
                rec = dict(rec)
                rec.setdefault("peer", st.label)
                records.append(rec)
        records.sort(key=lambda r: r.get("wall_time", 0.0))
        return records

    def add_postmortem(self, label: str, pm: dict) -> None:
        """Record a harvested worker postmortem (watcher calls this on
        every worker death it recovers from)."""
        with self._lock:
            self._postmortems.append((str(label), dict(pm)))

    def cluster_postmortem(self) -> dict:
        """The /cluster/postmortem view: every harvested death this
        run, newest last, grouped per peer."""
        with self._lock:
            items = list(self._postmortems)
        peers: Dict[str, List[dict]] = {}
        for label, pm in items:
            peers.setdefault(label, []).append(pm)
        return {
            "wall_time": time.time(),
            "deaths": len(items),
            "peers": peers,
        }

    def cluster_links(self) -> dict:
        """The /cluster/links view: the k×k link matrix assembled from
        every worker's exported row (no extra scrape — rows ride the
        /metrics pages the aggregator already holds), plus the per-peer
        clock offsets already estimated for /cluster/trace so offline
        tooling can align link events without re-deriving them.

        Scale mode (ISSUE 18): the full k×k document is replaced by a
        SAMPLED one — only the rows ingested this sweep ship as edges
        (payload O(k)/sweep instead of O(k²)), while min_bw and the
        slowest-edge election run over the whole row cache, every row
        carries its age and the retained slowest edges are listed with
        theirs. Consumers that vote on freshness (ReplanPolicy) gate on
        the ages instead of assuming a full fresh matrix."""
        if self._scale:
            return self._cluster_links_sampled()
        doc = tlink.merge_matrix({st.label: st.links for st in self.peers()})
        doc["wall_time"] = self._scraped_at
        doc["clock_offset_us"] = {
            st.label: st.clock_offset_us for st in self.peers()
        }
        # active-ring view (ISSUE 14): reconstruct the ring order the
        # workers are actually walking from their exported positions;
        # only published when every scraped peer reported a distinct
        # position (mid-re-plan or partially-scraped clusters return
        # null rather than a half-true ring)
        doc["ring"] = self._ring_doc()
        doc["plane"] = self.plane_envelope()
        return doc

    def _ring_doc(self) -> dict:
        """Active-ring reconstruction (ISSUE 14), shared by the flat and
        sampled links views: published only when every scraped peer
        reported a distinct position."""
        positions = {
            st.label: st.ring_pos for st in self.peers()
            if st.ring_pos is not None
        }
        order = None
        if positions and len(positions) == len(self.peers()):
            by_pos = sorted(positions.items(), key=lambda kv: kv[1])
            if [p for _, p in by_pos] == list(range(len(by_pos))):
                order = [label for label, _ in by_pos]
        return {
            "order": order,
            "position": positions,
            "next": {
                st.label: st.ring_next for st in self.peers()
                if st.ring_next is not None
            },
            # two-level roles (ISSUE 19): per-peer {level, role, group}
            # — "inter"/"head" marks a host head, "intra"/"demoted" a
            # demoted peer; all-"flat" (or absent) = no hierarchy
            "role": {
                st.label: st.ring_role for st in self.peers()
                if st.ring_role is not None
            },
            # active wire precision (ISSUE 20): cluster-agreed by the
            # lockstep votes, so these normally all match — a divergence
            # here is a scrape straddling a flip (or a real bug)
            "wire": {
                st.label: st.wire_mode for st in self.peers()
                if st.wire_mode is not None
            },
        }

    def _cluster_links_sampled(self) -> dict:
        """Scale-mode /cluster/links (see cluster_links)."""
        rows, ages = self._link_cache_view()
        with self._lock:
            slow = [dict(e) for e in self._slow_edges]
            ingested = list(self._ingested_links)
        # the ELECTION spans the whole cache (merge_matrix stays the
        # single election authority); only the shipped edges are the
        # sampled slice
        elected = tlink.merge_matrix(rows, copy_edges=False)
        now_m = time.monotonic()
        for e in slow:
            e["age_s"] = round(now_m - e.pop("at"), 3)
        k = len(self.peers())
        doc = {
            "mode": "sampled",
            "peers": sorted(st.label for st in self.peers()),
            # this sweep's rotation slice only — O(k) bytes per sweep
            "edges": {
                src: {dst: dict(info) for dst, info in rows[src].items()}
                for src in ingested if src in rows
            },
            "min_bw": elected["min_bw"],
            "slowest_edge": elected["slowest_edge"],
            "slowest_edges": slow,
            "row_age_s": ages,
            "oldest_row_age_s": max(ages.values()) if ages else None,
            "coverage": round(len(rows) / k, 4) if k else None,
            "wall_time": self._scraped_at,
            "clock_offset_us": {
                st.label: st.clock_offset_us for st in self.peers()
            },
            "ring": self._ring_doc(),
            "plane": self.plane_envelope(),
        }
        return doc

    # -- step plane (ISSUE 13) ------------------------------------------

    # merged step records older than this keep only their election; the
    # newest few retain the per-peer lanes `info steps` renders (full
    # lanes for all STEP_KEEP records would hold k x buckets dicts per
    # step on the runner forever)
    STEP_LANES_KEEP = 8

    def _plane_docs(
        self, path: str
    ) -> Tuple[Dict[str, dict], Dict[str, float]]:
        """Per-worker documents + clock offsets for one merged-plane
        refresh. Flat mode: direct parallel fetch of every worker (the
        historical path, via _fetch_all so tests can stub the
        transport). Hier mode: the sweep already pulled the documents
        through the host digests — consume that set while it's fresh,
        falling back to direct fetches when it isn't (inline refresh
        with no runner loop). Delta mode adds ?since= cursors to the
        direct fetches and advances them off each document's
        next_since."""
        if self._hier_active:
            with self._lock:
                cached = self._digest_planes.get(path)
                at = self._digest_at
                states = dict(self._peers)
            if cached and at is not None and (
                time.monotonic() - at < 2.0 * self.effective_interval()
            ):
                docs = {}
                offsets = {}
                for label, doc in cached.items():
                    st = states.get(label)
                    if st is None:
                        continue
                    docs[label] = doc
                    offsets[label] = st.clock_offset_us or 0.0
                return docs, offsets
        docs = {}
        offsets = {}
        delta = (
            path in ("/steptrace", "/decisions") and self._delta_enabled()
        )
        results = (
            self._fetch_all(path, since_key=path)
            if delta else self._fetch_all(path)
        )
        for st, body in results:
            try:
                doc = json.loads(body.decode())
            except ValueError as e:
                st.last_error = str(e)
                continue
            docs[st.label] = doc
            offsets[st.label] = st.clock_offset_us or 0.0
            if delta and isinstance(doc.get("next_since"), int):
                st.since[path] = doc["next_since"]
        return docs, offsets

    def _refresh_steps(self) -> None:
        """Pull every worker's /steptrace, align timelines with the
        clock offsets already estimated for /cluster/trace, merge into
        per-step critical-path records, publish the gauges and track the
        patience window behind `step_critical_path` audit events. Only
        steps NEWER than the last refresh append (workers keep a ring;
        re-reading it must not replay old steps into the streak), and
        whole refreshes serialize — the sweep thread and an HTTP
        handler's inline refresh racing here would append the same
        fresh steps twice."""
        self._planes["steps"].refresh()

    def _refresh_steps_locked(self) -> None:
        docs, offsets = self._plane_docs("/steptrace")
        # delta/hier scrapes ship each flushed timeline ONCE, but the
        # merge below holds the globally-newest round back — so shipped
        # timelines pool per peer until a newer round releases them.
        # Flat mode never pools: workers re-serve their whole ring, and
        # the pool would only duplicate state.
        delta = self._hier_active or self._delta_enabled()
        if not docs and not (delta and self._steps_pending):
            return
        # merge only FLUSHED timelines (an in-flight round's partial
        # lanes belong to the worker/postmortem views, not a cluster
        # election), and ALWAYS hold the globally-newest flushed round
        # back until a newer one exists: a step merges exactly once, so
        # electing it while some peer is still walking (or unscraped)
        # would freeze a half-flushed critical path into the ring
        # forever (seen live: edge=None, overlap=None). Cost: one
        # step of publication lag, and a fully-quiesced run never
        # publishes its final round — the price of never publishing a
        # partial election.
        for doc in docs.values():
            doc["timelines"] = [
                t for t in doc.get("timelines", [])
                if t.get("t_end_us") is not None
            ]
        if delta:
            with self._lock:
                pool = self._steps_pending
                for label, doc in docs.items():
                    per = pool.setdefault(label, {})
                    for t in doc["timelines"]:
                        key = (int(t.get("epoch", 0)),
                               int(t.get("round", 0)))
                        if (
                            self._steps_last is not None
                            and key <= self._steps_last
                        ):
                            continue
                        per[key] = t
                    # bounded like the worker rings: a peer that stops
                    # flushing must not pool forever
                    if len(per) > STEP_KEEP:
                        for k_ in sorted(per)[:-STEP_KEEP]:
                            del per[k_]
                live = {st.label for st in self._peers.values()}
                for label in list(pool):
                    if label not in live:
                        del pool[label]
                docs = {
                    label: {"timelines": list(per.values())}
                    for label, per in pool.items() if per
                }
                # offsets for ALL pooled peers, not just this round's
                # respondents: a pooled timeline from a peer that
                # failed this fetch still aligns with its last-known
                # offset
                offsets = {
                    st.label: st.clock_offset_us or 0.0
                    for st in self._peers.values()
                }
            if not docs:
                return
        keys = {
            (int(t.get("epoch", 0)), int(t.get("round", 0)))
            for doc in docs.values()
            for t in doc["timelines"]
        }
        merged = tstep.merge_steps(docs, offsets)
        if keys:
            newest = max(keys)
            merged = [
                s for s in merged if (s["epoch"], s["round"]) < newest
            ]
        fresh = [
            s for s in merged
            if self._steps_last is None
            or (s["epoch"], s["round"]) > self._steps_last
        ]
        if not fresh:
            return
        with self._lock:
            for s in fresh:
                rec = dict(s)
                rec["peer_count"] = len(s.get("peers", {}))
                self._steps.append(rec)
            # beyond the lane window, keep only the election (the full
            # lanes are bulky and already served by the workers)
            for old in list(self._steps)[:-self.STEP_LANES_KEEP]:
                old.pop("peers", None)
            self._steps_last = (fresh[-1]["epoch"], fresh[-1]["round"])
            # delta pool: published rounds are merged for good — only
            # the held-back tail stays pooled
            for per in self._steps_pending.values():
                for k_ in [k for k in per if k <= self._steps_last]:
                    del per[k_]
        latest = fresh[-1]
        if latest.get("overlap_frac") is not None:
            self._g_step_overlap.set(latest["overlap_frac"])
        crit = latest.get("critical")
        self._g_step_critical.clear_children()
        if crit:
            self._g_step_critical.labels(
                str(crit.get("peer")), str(crit.get("edge") or "?")
            ).set((crit.get("self_us") or 0.0) / 1e6)
        # patience window: the SAME (peer, edge) dominating consecutive
        # merged steps is a standing bottleneck, not weather — audit it
        # once per streak, at the moment patience fills
        for s in fresh:
            c = s.get("critical")
            key = (
                (str(c.get("peer")), str(c.get("edge") or ""))
                if c else None
            )
            streak_key, count = self._crit_streak
            count = count + 1 if key is not None and key == streak_key else 1
            self._crit_streak = (key, count)
            if key is not None and count == STEP_CRIT_PATIENCE:
                audit.record_event(
                    "step_critical_path",
                    peer=key[0],
                    edge=key[1] or None,
                    bucket=c.get("bucket"),
                    trigger="step_merge",
                    blocking_ms=round((c.get("self_us") or 0.0) / 1e3, 3),
                    steps=STEP_CRIT_PATIENCE,
                    epoch=s["epoch"],
                    round=s["round"],
                )

    def cluster_steps(self) -> dict:
        """The /cluster/steps view: recent merged per-step critical-path
        records, newest last — the newest STEP_LANES_KEEP still carry
        their per-peer lanes (the `info steps` rendering), older ones
        only the election. Refreshes inline when the cached merge is
        older than a scrape interval, so one-shot consumers (`info
        steps` without a runner loop) still see fresh steps."""
        self._planes["steps"].ensure_fresh()
        with self._lock:
            # shallow copies: a later refresh pops "peers" off aged
            # records in place, and serialization must not iterate a
            # dict mid-mutation
            steps = [dict(s) for s in self._steps]
        return {
            "wall_time": time.time(),
            "count": len(steps),
            "patience": STEP_CRIT_PATIENCE,
            "steps": steps,
            "plane": self.plane_envelope(),
        }

    # -- decision plane (ISSUE 15) --------------------------------------

    def _refresh_decisions(self) -> None:
        """Pull every worker's /decisions ledger, align the perf stamps
        with the clock offsets already estimated for /cluster/trace and
        merge keyed (peer, seq, open wall time): re-scraping an
        unchanged ledger is idempotent, a record that closed (or
        regressed) since the last sweep UPDATES its merged copy in
        place, and a respawned worker's restarted seq space cannot
        collide with its dead incarnation's records. Whole refreshes
        serialize like the step plane's. Delta scrapes (?since=) compose
        naturally with the keyed merge: an unshipped-because-unchanged
        record simply keeps its merged copy."""
        self._planes["decisions"].refresh()

    def _refresh_decisions_locked(self) -> None:
        docs, offsets = self._plane_docs("/decisions")
        if not docs:
            return
        merged = tdecisions.merge_decisions(docs, offsets)
        with self._lock:
            for rec in merged:
                self._decisions[(
                    rec.get("peer", ""),
                    int(rec.get("seq", 0)),
                    float(rec.get("wall_time") or 0.0),
                )] = rec
            if len(self._decisions) > self._decisions_keep:
                ordered = sorted(
                    self._decisions.items(),
                    key=lambda kv: kv[1].get("t_us") or 0.0,
                )
                for key, _ in ordered[:-self._decisions_keep]:
                    del self._decisions[key]

    def cluster_decisions(self) -> dict:
        """The /cluster/decisions view: the merged causal adaptation
        timeline, oldest first. Refreshes inline when the cached merge
        is older than a scrape interval, so one-shot consumers (`info
        decisions` without a runner loop) still see fresh outcomes."""
        self._planes["decisions"].ensure_fresh()
        with self._lock:
            recs = sorted(
                self._decisions.values(),
                key=lambda r: r.get("t_us") or r.get("wall_time") or 0.0,
            )
        return {
            "wall_time": time.time(),
            "count": len(recs),
            "open": sum(1 for r in recs if r.get("status") != "closed"),
            "regressed": sum(1 for r in recs if r.get("regressed")),
            "decisions": recs,
            "plane": self.plane_envelope(),
        }

    # -- resource plane (ISSUE 16) --------------------------------------

    def _refresh_resources(self) -> None:
        """Pull every worker's /resources document, align the perf
        anchors with the clock offsets already estimated for
        /cluster/trace and REPLACE the merged view (current state, not a
        log: a vanished peer's stale saturation flag must not keep
        classifying straggler causes). Whole refreshes serialize like
        the step plane's."""
        self._planes["resources"].refresh()

    def _refresh_resources_locked(self) -> None:
        docs, offsets = self._plane_docs("/resources")
        merged = tresource.merge_resources(docs, offsets)
        with self._lock:
            self._resources = merged

    def cluster_resources(self) -> dict:
        """The /cluster/resources view: every live worker's resource
        attribution document merged NTP-aligned, plus the cluster
        election (saturated peers, max CPU fraction). Refreshes inline
        when the cached merge is older than a scrape interval, so
        one-shot consumers (`info resources` without a runner loop)
        still see fresh attribution."""
        self._planes["resources"].ensure_fresh()
        with self._lock:
            merged = dict(self._resources)
        doc = {
            "wall_time": time.time(),
            "count": len(merged.get("peers") or {}),
        }
        doc.update(merged)
        doc["plane"] = self.plane_envelope()
        return doc

    def _resources_summary(self) -> Optional[dict]:
        """Compact resource signal for /cluster/health (the full
        documents stay on /cluster/resources): per peer the window CPU
        fraction, the training bucket's share of the busy window, the
        engine share and the saturation flag — exactly the columns
        `info top` renders."""
        with self._lock:
            merged = self._resources
            if not merged or not merged.get("peers"):
                return None
            peers = {}
            for label, doc in merged["peers"].items():
                buckets = doc.get("buckets") or {}
                peers[label] = {
                    "cpu_frac": doc.get("cpu_frac"),
                    "train_frac": (buckets.get("train") or {}).get("frac"),
                    "engine_frac": doc.get("engine_frac"),
                    "saturated": bool(doc.get("saturated")),
                }
            return {
                "peers": peers,
                "saturated": list(merged.get("saturated") or []),
                "max_cpu_frac": merged.get("max_cpu_frac"),
            }

    # -- memory plane (ISSUE 17) ----------------------------------------

    def _refresh_memory(self) -> None:
        """Pull every worker's /memory document, align the perf anchors
        with the clock offsets already estimated for /cluster/trace and
        REPLACE the merged view (current state, not a log: a vanished
        peer's stale pressure flag must not keep gating resizes).
        Whole refreshes serialize like the resource plane's."""
        self._planes["memory"].refresh()

    def _refresh_memory_locked(self) -> None:
        docs, offsets = self._plane_docs("/memory")
        merged = tmemory.merge_memory(docs, offsets)
        with self._lock:
            self._memory = merged

    def cluster_memory(self) -> dict:
        """The /cluster/memory view: every live worker's memory
        attribution document merged NTP-aligned, plus the cluster
        elections (minimum headroom + its peer, the pressure and
        thrashing sets, leak suspects). Refreshes inline when the
        cached merge is older than a scrape interval, so one-shot
        consumers (`info memory` without a runner loop) still see
        fresh attribution."""
        self._planes["memory"].ensure_fresh()
        with self._lock:
            merged = dict(self._memory)
        doc = {
            "wall_time": time.time(),
            "count": len(merged.get("peers") or {}),
        }
        doc.update(merged)
        doc["plane"] = self.plane_envelope()
        return doc

    def _memory_summary(self) -> Optional[dict]:
        """Compact memory signal for /cluster/health (the full
        documents stay on /cluster/memory): per peer the used fraction,
        headroom, thrash/pressure flags — exactly the columns `info
        top` renders — plus the cluster elections."""
        with self._lock:
            merged = self._memory
            if not merged or not merged.get("peers"):
                return None
            peers = {}
            for label, doc in merged["peers"].items():
                hf = doc.get("headroom_frac")
                peers[label] = {
                    "rss_bytes": doc.get("rss_bytes"),
                    "headroom_frac": hf,
                    "used_frac": (
                        round(1.0 - hf, 6)
                        if isinstance(hf, (int, float)) else None
                    ),
                    "pressure": bool(doc.get("pressure")),
                    "thrashing": bool(doc.get("thrashing")),
                }
            return {
                "peers": peers,
                "min_headroom_frac": merged.get("min_headroom_frac"),
                "min_headroom_peer": merged.get("min_headroom_peer"),
                "pressure": list(merged.get("pressure") or []),
                "thrashing": list(merged.get("thrashing") or []),
                "leak_suspects": dict(merged.get("leak_suspects") or {}),
            }

    def footprint_bytes(self) -> int:
        """The aggregator's OWN tracked-state footprint: deep size of
        the link matrix, step ring, decision log and the merged
        resource/memory views. This is the O(k^2)-worried state ROADMAP
        item 2 needs bounded at scale — measured, and registered under
        the `telemetry` bucket of the runner's own memory plane."""
        with self._lock:
            state = (
                {st.label: st.links for st in self._peers.values()},
                list(self._steps),
                dict(self._decisions),
                dict(self._resources),
                dict(self._memory),
                dict(self._link_cache),
                dict(self._steps_pending),
                dict(self._audit_cache),
            )
        return tmemory.deep_sizeof(state)

    def _steps_summary(self) -> Optional[dict]:
        """Compact step signal for /cluster/health (the full records
        stay on /cluster/steps): the latest step's election plus each
        peer's share of recent steps it was critical in."""
        with self._lock:
            steps = list(self._steps)
        if not steps:
            return None
        latest = steps[-1]
        crit_counts: Dict[str, int] = {}
        crit_edges: Dict[str, str] = {}
        for s in steps:
            c = s.get("critical")
            if not c or c.get("peer") is None:
                continue
            peer = str(c["peer"])
            crit_counts[peer] = crit_counts.get(peer, 0) + 1
            if c.get("edge"):
                crit_edges[peer] = str(c["edge"])
        n = len(steps)
        crit = latest.get("critical") or {}
        return {
            "steps": n,
            "critical_peer": crit.get("peer"),
            "critical_edge": crit.get("edge"),
            "critical_ms": (
                round((crit.get("self_us") or 0.0) / 1e3, 3)
                if crit else None
            ),
            "overlap_frac": latest.get("overlap_frac"),
            "queue_delay_frac": latest.get("queue_delay_frac"),
            "crit_frac": {
                p: round(c / n, 3) for p, c in sorted(crit_counts.items())
            },
            "crit_edge": crit_edges,
        }

    def _links_summary(self) -> dict:
        """Compact link signal for /cluster/health (the full matrix
        stays on /cluster/links): the slowest measured edge and how many
        edges have estimates at all. The election itself lives in ONE
        place — tlink.merge_matrix — so this summary can never disagree
        with /cluster/links about which edge is slowest. copy_edges=False:
        this runs on every /cluster/health request (polled by every
        worker), and a k=64 matrix is ~4k edge dicts we would copy only
        to throw away. Scale mode summarizes the SAMPLED cache instead
        and reports its coverage and oldest row age, so freshness-gated
        consumers (ReplanPolicy) can refuse to vote on stale rows."""
        if self._scale:
            rows, ages = self._link_cache_view()
            doc = tlink.merge_matrix(rows, copy_edges=False)
            edges = sum(
                1
                for row in doc["edges"].values()
                for info in row.values()
                if isinstance(info.get("bw"), (int, float))
                and info["bw"] > 0
            )
            k = len(self.peers())
            return {
                "min_bw": doc["min_bw"],
                "slowest_edge": doc["slowest_edge"],
                "edges": edges,
                "oldest_row_age_s": (
                    max(ages.values()) if ages else None
                ),
                "coverage": round(len(rows) / k, 4) if k else None,
            }
        doc = tlink.merge_matrix(
            {st.label: st.links for st in self.peers()}, copy_edges=False
        )
        edges = sum(
            1
            for row in doc["edges"].values()
            for info in row.values()
            if isinstance(info.get("bw"), (int, float)) and info["bw"] > 0
        )
        return {
            "min_bw": doc["min_bw"],
            "slowest_edge": doc["slowest_edge"],
            "edges": edges,
        }

    def plane_envelope(self) -> dict:
        """Telemetry-plane health (ISSUE 18): one shared envelope every
        /cluster/* JSON document carries as `plane`, so any consumer —
        `info top --json`, a policy, an operator — can tell "the
        cluster is fine" from "the MONITORING is behind" without
        cross-referencing endpoints."""
        now_m = time.monotonic()
        stale = self._stale_peers()
        k = len(self.peers())
        env = {
            "mode": (
                "hier" if self._hier_active
                else ("sampled" if self._scale else "flat")
            ),
            "interval_s": self.interval,
            "effective_interval_s": round(self.effective_interval(), 3),
            "sweep_seconds": (
                round(self._last_sweep_s, 6)
                if self._last_sweep_s is not None else None
            ),
            "sweep_age_s": (
                round(now_m - self._sweep_mono, 3)
                if self._sweep_mono is not None else None
            ),
            "scraped_peers": k - len(stale),
            "stale_peers": len(stale),
        }
        if self._scale:
            _, ages = self._link_cache_view()
            env["oldest_link_row_age_s"] = (
                max(ages.values()) if ages else None
            )
        return env

    def _stale_endpoints(self, st: PeerState, now: float) -> Optional[List[str]]:
        """Per-(peer, endpoint) staleness (ISSUE 18 fix): endpoints
        this peer HAS served whose last success is older than twice the
        effective interval — i.e. planes silently serving their
        previous payload. None when every known endpoint is fresh."""
        horizon = 2.0 * max(self.effective_interval(), 1e-9)
        out = sorted(
            ep for ep, at in st.endpoint_at.items()
            if now - at > horizon
        )
        out += sorted(
            ep for ep in st.endpoint_err
            if ep not in st.endpoint_at
        )
        return out or None

    def cluster_health(self) -> dict:
        """The JSON health snapshot behind /cluster/health and
        monitor.cluster_health()."""
        now = time.monotonic()
        scores = self.scorer.scores()
        rtt_scores = self.rtt_scorer.scores()
        peers = {}
        for st in self.peers():
            sc = scores.get(st.label)
            rsc = rtt_scores.get(st.label)
            peers[st.label] = {
                "url": st.url,
                "step_rate": st.step_rate,
                "step_time_p50_ms": (
                    round(st.step_p50 * 1e3, 3) if st.step_p50 is not None
                    else None
                ),
                "step_time_p99_ms": (
                    round(st.step_p99 * 1e3, 3) if st.step_p99 is not None
                    else None
                ),
                # the SCORED series' rolling median: compute time (step
                # minus collective wait) when the worker publishes
                # collective latencies, else wall-clock step time
                "step_time_ms": (
                    round(sc.value * 1e3, 3) if sc is not None else None
                ),
                "compute_time_ms": (
                    round(st.compute_mean * 1e3, 3)
                    if st.compute_mean is not None else None
                ),
                "bytes_tx": st.bytes_tx,
                "bytes_rx": st.bytes_rx,
                "rtt_ms": (
                    round(st.rtt_s * 1e3, 3)
                    if math.isfinite(st.rtt_s) else None
                ),
                "clock_offset_us": st.clock_offset_us,
                "last_scrape_age_s": (
                    round(now - st.last_ok, 3)
                    if st.last_ok is not None else None
                ),
                "error": st.last_error or None,
                "straggler": bool(sc.flagged) if sc is not None else False,
                "straggler_score": (
                    round(sc.score, 2) if sc is not None else None
                ),
                "rtt_outlier": bool(rsc.flagged) if rsc is not None else False,
                # the measured cause classified at the flag transition
                # (network/compute/unknown); None while unflagged
                "straggler_cause": self._causes.get(st.label),
                # endpoints whose last success predates the staleness
                # horizon — the plane is serving their previous payload
                "stale_endpoints": self._stale_endpoints(st, now),
            }
        med = self.scorer.cluster_median()
        return {
            # wall_time is the LAST SCRAPE's stamp, not request time:
            # consumers debounce refreshes on it (cluster/updated_at),
            # so re-reading an unchanged snapshot must not look fresh
            "wall_time": self._scraped_at,
            "interval_s": self.interval,
            "peers": peers,
            "stragglers": sorted(self._flagged),
            "rtt_outliers": sorted(self._rtt_flagged),
            "cluster_step_time_ms": (
                round(med * 1e3, 3) if med is not None else None
            ),
            "step_skew": self.scorer.skew(),
            "links": self._links_summary(),
            "steps": self._steps_summary(),
            "resources": self._resources_summary(),
            "memory": self._memory_summary(),
            "plane": self.plane_envelope(),
        }


# -- host sub-aggregator (ISSUE 18 tentpole) ---------------------------


class HostSubAggregator:
    """Per-host telemetry pre-merger: the worker elected head of its
    host scrapes its LOCAL siblings (loopback round trips, microsecond
    clock-offset error) and serves one ``/host/telemetry`` digest —
    every sibling's pre-parsed /metrics summary, raw exposition page
    (for federation) and delta-cursored plane documents. The root
    aggregator then sweeps O(hosts) digests instead of O(k) x O(planes)
    worker endpoints, composing clock offsets across the two hops.

    Election is deterministic (lowest peer label on the host, the same
    host grouping targets_for_workers encodes) and recomputed on every
    membership change — no coordination round, no extra process. The
    digest caches for half the scrape interval, so the root's poll
    cadence drives refreshes 1:1; delta cursors advance host-side, and
    the root's keyed/pooled merges make re-served digests idempotent.
    A digest the root never picks up (root died mid-sweep) loses those
    deltas to the root's view — the worker rings still hold them."""

    def __init__(
        self,
        host: str,
        timeout: float = 2.0,
        interval: Optional[float] = None,
        fetch: Optional[Callable[[str, str, float], Tuple[bytes, dict]]] = None,
    ):
        self.host = host
        self.timeout = timeout
        self.interval = (
            interval if interval is not None else scrape_interval()
        )
        self._transport = fetch
        self._lock = threading.Lock()  # targets/states + cache swap
        self._refresh_lock = threading.Lock()  # serialize whole sweeps
        self._states: Dict[str, PeerState] = {}
        self._cache: Optional[dict] = None
        self._cache_at: Optional[float] = None  # monotonic

    def set_targets(self, targets: Sequence[Tuple[str, str]]) -> None:
        """Replace the local scrape set (the election hook calls this
        on every membership change). Surviving siblings keep their
        clock offsets and delta cursors."""
        with self._lock:
            fresh: Dict[str, PeerState] = {}
            for label, url in targets:
                st = self._states.get(label)
                if st is None or st.url != url.rstrip("/"):
                    st = PeerState(label, url)
                fresh[label] = st
            self._states = fresh

    def _fetch(self, st: PeerState, path: str) -> bytes:
        t0 = time.perf_counter()
        if self._transport is not None:
            body, headers = self._transport(st.url, path, self.timeout)
            clock = headers.get(CLOCK_HEADER)
        else:
            with urllib.request.urlopen(
                st.url + path, timeout=self.timeout
            ) as r:
                body = r.read()
                clock = r.headers.get(CLOCK_HEADER)
        t1 = time.perf_counter()
        rtt = t1 - t0
        st.rtt_s = rtt
        _note_clock(st, rtt, clock, t0, t1)
        return body

    def _scrape_worker(self, st: PeerState) -> dict:
        try:
            body = self._fetch(st, "/metrics")
        except (OSError, ValueError) as e:
            return {"url": st.url, "error": str(e)}
        text = body.decode(errors="replace")
        entry: dict = {
            "url": st.url,
            "metrics_text": text,
            "parsed": parsed_to_doc(parse_worker_page(text)),
            "rtt_s": st.rtt_s,
            "clock_offset_us": st.clock_offset_us,
        }
        for path, key in (
            ("/steptrace", "steptrace"),
            ("/decisions", "decisions"),
            ("/resources", "resources"),
            ("/memory", "memory"),
        ):
            p = path
            cur = st.since.get(path)
            if cur is not None:
                p = f"{path}?since={cur}"
            try:
                doc = json.loads(self._fetch(st, p).decode())
            except (OSError, ValueError) as e:
                # a sibling failing ONE endpoint still ships the rest;
                # the root's per-(peer, endpoint) staleness surfaces it
                st.endpoint_err[path] = str(e)
                continue
            st.endpoint_err.pop(path, None)
            if isinstance(doc.get("next_since"), int):
                st.since[path] = doc["next_since"]
            entry[key] = doc
        return entry

    def refresh(self) -> None:
        """One parallel sweep over the local siblings, building the
        digest cache."""
        with self._lock:
            states = sorted(self._states.values(), key=lambda s: s.label)
        workers: Dict[str, dict] = {}
        threads = [
            threading.Thread(
                target=lambda st=st: workers.__setitem__(
                    st.label, self._scrape_worker(st)
                ),
                daemon=True,
            )
            for st in states
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.timeout + 1.0)
        doc = {
            "enabled": True,
            "host": self.host,
            "wall_time": time.time(),
            "workers": workers,
        }
        with self._lock:
            self._cache = doc
            self._cache_at = time.monotonic()

    def digest(self) -> dict:
        """The /host/telemetry document, refreshed when older than half
        the scrape interval — the root polling at its interval always
        gets a this-cycle sweep, and a double poll inside one window
        re-serves the cache (the root's keyed merges dedupe)."""
        with self._refresh_lock:
            with self._lock:
                at = self._cache_at
            if (
                at is None
                or time.monotonic() - at >= 0.5 * self.interval
            ):
                self.refresh()
        with self._lock:
            return self._cache or {
                "enabled": True, "host": self.host, "workers": {},
            }


_host_sub: Optional[HostSubAggregator] = None
_host_sub_lock = threading.Lock()


def set_host_sub(sub: Optional[HostSubAggregator]) -> None:
    """Install/clear this process's host sub-aggregator (the election
    hook does this; tests may too)."""
    global _host_sub
    with _host_sub_lock:
        _host_sub = sub


def get_host_sub() -> Optional[HostSubAggregator]:
    with _host_sub_lock:
        return _host_sub


def host_digest_doc() -> dict:
    """The /host/telemetry view: the digest when this worker holds the
    host-head role, {"enabled": false} otherwise (the root probes the
    role cheaply and falls back to direct scrapes)."""
    sub = get_host_sub()
    if sub is None:
        return {"enabled": False}
    return sub.digest()


def update_host_role(self_id, workers) -> None:
    """(Re-)elect this worker's host sub-aggregator role; the peer
    calls this on start and on every membership change. The role
    engages only at scale (>= KF_AGG_HIER_MIN_PEERS targets, matching
    the root's threshold), on the worker whose label sorts lowest among
    its host's >= 2 local targets — the same deterministic choice the
    root's _sweep_host makes, so both sides agree without a
    coordination round."""
    targets = TelemetryAggregator.targets_for_workers(workers)
    thresh = hier_min_peers()
    label = str(self_id)
    url_by_label = dict(targets)
    mine: Optional[List[Tuple[str, str]]] = None
    my_host = None
    if thresh > 0 and len(targets) >= thresh and label in url_by_label:
        my_host = urlsplit(url_by_label[label]).hostname
        if my_host:
            local = [
                (lab, url) for lab, url in targets
                if urlsplit(url).hostname == my_host
            ]
            if len(local) > 1 and min(lab for lab, _ in local) == label:
                mine = local
    global _host_sub
    with _host_sub_lock:
        if mine is None:
            _host_sub = None
        else:
            if _host_sub is None or _host_sub.host != my_host:
                _host_sub = HostSubAggregator(host=my_host)
            _host_sub.set_targets(mine)


# -- adaptation-facing accessors ---------------------------------------

_aggregator: Optional[TelemetryAggregator] = None
_agg_lock = threading.Lock()
# remote /cluster/health cache: "t" = monotonic time of the last
# SUCCESSFUL fetch (a failed refresh must NOT re-stamp stale flags as
# fresh), "attempt_t" rate-limits refresh attempts, "fetching" holds the
# single in-flight refresh thread flag
_remote_cache: dict = {
    "t": 0.0, "attempt_t": 0.0, "data": None, "url": "", "fetching": False,
}


def set_aggregator(agg: Optional[TelemetryAggregator]) -> None:
    """Install the process-wide aggregator (the elastic watcher does
    this; tests may too)."""
    global _aggregator
    with _agg_lock:
        _aggregator = agg


def get_aggregator() -> Optional[TelemetryAggregator]:
    with _agg_lock:
        return _aggregator


def _refresh_remote(url: str) -> None:
    try:
        with urllib.request.urlopen(url, timeout=2.0) as r:
            data = json.loads(r.read().decode())
        with _agg_lock:
            if _remote_cache["url"] == url:
                _remote_cache.update(t=time.monotonic(), data=data)
    except (OSError, ValueError):
        pass  # keep the old data AND its old timestamp: stale is stale
    finally:
        with _agg_lock:
            _remote_cache["fetching"] = False


def health_snapshot(max_age: float = 5.0, wait: bool = False) -> Optional[dict]:
    """The latest cluster-health dict, from the in-process aggregator
    when this process hosts one (the runner), else fetched from
    ``KF_CLUSTER_HEALTH_URL`` (workers; the watcher injects the env var
    pointing at its own /cluster/health).

    The remote path NEVER blocks the caller (it sits on the training-step
    path via PolicyRunner): it returns the cached snapshot immediately —
    possibly stale, possibly None on the very first call — and refreshes
    in a background thread at most every ``max_age`` seconds. A snapshot
    older than the last scrape keeps its original ``wall_time``, so
    debounced consumers (cluster/updated_at) never mistake a dead
    runner's last flags for news. ``wait=True`` (tests, one-shot CLIs)
    runs an overdue refresh inline instead."""
    agg = get_aggregator()
    if agg is not None:
        return agg.cluster_health()
    url = knobs.raw(HEALTH_URL_ENV)
    if not url:
        return None
    now = time.monotonic()
    with _agg_lock:
        if _remote_cache["url"] != url:
            _remote_cache.update(
                t=0.0, attempt_t=0.0, data=None, url=url, fetching=False
            )
        data = _remote_cache["data"]
        fresh = data is not None and now - _remote_cache["t"] < max_age
        due = (
            not fresh
            and not _remote_cache["fetching"]
            and now - _remote_cache["attempt_t"] >= max_age
        )
        if due:
            _remote_cache["fetching"] = True
            _remote_cache["attempt_t"] = now
    if due:
        if wait:
            _refresh_remote(url)
            with _agg_lock:
                return _remote_cache["data"]
        threading.Thread(
            target=_refresh_remote, args=(url,),
            name="kf-health-refresh", daemon=True,
        ).start()
    return data


def health_signals(
    max_age: float = 5.0, self_peer: str = "", wait: bool = False
) -> dict:
    """Flatten the health snapshot into the signal dict policies see in
    ``PolicyContext.metrics`` (namespaced ``cluster/``)."""
    snap = health_snapshot(max_age, wait=wait)
    if not snap:
        return {}
    me = self_peer or knobs.raw("KF_SELF_SPEC")
    stragglers = snap.get("stragglers", [])
    signals = {
        # refresh marker: consumers that must count SCRAPES (not steps)
        # key off this — flag lists are identical between refreshes for
        # a steady straggler
        "cluster/updated_at": snap.get("wall_time"),
        "cluster/stragglers": stragglers,
        "cluster/rtt_outliers": snap.get("rtt_outliers", []),
        "cluster/step_skew": snap.get("step_skew"),
        "cluster/step_time_ms": snap.get("cluster_step_time_ms"),
        "cluster/straggler_score": {
            p: info.get("straggler_score")
            for p, info in snap.get("peers", {}).items()
            if info.get("straggler_score") is not None
        },
        "cluster/self_straggler": me in stragglers if me else False,
        # the measured cause behind each flagged straggler (ISSUE 16
        # classification) — the demotion policy (ISSUE 19) only acts on
        # non-network causes: a slow LINK is the flat re-planner's job,
        # demotion is for peers that are themselves the bottleneck
        "cluster/straggler_causes": {
            p: info.get("straggler_cause")
            for p, info in snap.get("peers", {}).items()
            if info.get("straggler_cause")
        },
    }
    links = snap.get("links") or {}
    if links.get("min_bw") is not None:
        signals["links/min_bw"] = links["min_bw"]
        signals["links/slowest_edge"] = links.get("slowest_edge")
    # sampled-matrix freshness (ISSUE 18, scale mode only): consumers
    # voting on link data (ReplanPolicy) gate on row age — a rotation
    # that stopped refreshing must not keep steering re-plans
    if links.get("oldest_row_age_s") is not None:
        signals["links/oldest_row_age_s"] = links["oldest_row_age_s"]
    # telemetry-plane self-health (ISSUE 18): "the monitoring is
    # behind" as a signal, distinct from "the cluster is slow"
    plane = snap.get("plane") or {}
    if plane:
        signals["plane/mode"] = plane.get("mode")
        signals["plane/stale_peers"] = plane.get("stale_peers")
        if plane.get("sweep_seconds") is not None:
            signals["plane/sweep_seconds"] = plane["sweep_seconds"]
    # step plane (ISSUE 13): the measured per-step attribution signals
    # re-planning and priority feedback consume — cluster-wide values
    # override the worker-local steptrace fallbacks on the shared keys
    steps = snap.get("steps") or {}
    if steps.get("steps"):
        signals["step/critical_peer"] = steps.get("critical_peer")
        signals["step/critical_edge"] = steps.get("critical_edge")
        if steps.get("overlap_frac") is not None:
            signals["step/overlap_frac"] = steps["overlap_frac"]
        if steps.get("queue_delay_frac") is not None:
            signals["step/queue_delay_frac"] = steps["queue_delay_frac"]
    # resource plane (ISSUE 16): the cluster view of MY OWN attribution
    # overrides the worker-local fallback on the shared resource/* keys
    # (same precedence as the step plane) — policies on any peer also
    # see the cluster-wide compute-bound election
    res = snap.get("resources") or {}
    mine = (res.get("peers") or {}).get(me) if me else None
    if mine:
        if mine.get("cpu_frac") is not None:
            signals["resource/cpu_frac"] = mine["cpu_frac"]
        if mine.get("engine_frac") is not None:
            signals["resource/engine_frac"] = mine["engine_frac"]
        signals["resource/saturated"] = bool(mine.get("saturated"))
    if res.get("saturated") is not None:
        signals["resource/saturated_peers"] = list(res["saturated"])
    # memory plane (ISSUE 17): the cluster view of MY OWN headroom
    # overrides the worker-local fallback on the shared memory/* keys;
    # policies on any peer also see the cluster's weakest-headroom
    # election — the grow-gate input
    mem = snap.get("memory") or {}
    mem_mine = (mem.get("peers") or {}).get(me) if me else None
    if mem_mine:
        if mem_mine.get("headroom_frac") is not None:
            signals["memory/headroom_frac"] = mem_mine["headroom_frac"]
            signals["memory/pressure"] = bool(mem_mine.get("pressure"))
    if mem.get("min_headroom_peer") is not None:
        signals["memory/min_headroom_peer"] = mem["min_headroom_peer"]
        signals["memory/min_headroom_frac"] = mem.get("min_headroom_frac")
    if mem.get("leak_suspects"):
        signals["memory/leak_suspect"] = True
    return signals
