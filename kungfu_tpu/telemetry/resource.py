"""Resource attribution plane: per-thread CPU accounting + sampling
profiler (ISSUE 16).

Every plane so far measures the network side of the step — the link
matrix says which edge is slow, steptrace which bucket blocked, the
decision ledger whether an adaptation paid. None of them can answer
*"is this peer compute-bound or network-bound?"*: r12's re-plan
predictor was 86x optimistic precisely because CPU share is invisible
to a min-edge-bandwidth model, and a straggler flagged with no blocking
edge is a mystery. This module is the missing feed, two parts:

- **Per-thread CPU accounting** (:class:`CpuAccountant`): utime/stime
  deltas per sweep from ``/proc/self/task/*/stat`` (graceful no-op off
  Linux), attributed through the KF303-declared thread names onto
  subsystem buckets {train, walk_compute, codec, sched, telemetry,
  other} — every CPU-second the process burns lands in exactly one
  bucket, unknown names in ``other``, never dropped.
- **Sampling profiler** (:class:`SamplingProfiler`, optional):
  ``sys._current_frames()`` at ``KF_RESOURCE_SAMPLE_HZ`` into a bounded
  ring (``KF_RESOURCE_KEEP``), aggregated by module prefix, splitting
  the main thread into train-compute vs blocked-in-engine — the
  GIL-side cost the 1-core ceiling (ROADMAP item 5) needs measured.
  ``KF_RESOURCE_SAMPLE_HZ=0`` (the default) means the sampler thread is
  never started and allocates nothing (subprocess-asserted, like
  lockwatch and steptrace).

Sweeps are on-demand (no sweeper thread): ``export()`` / ``signals()``
trigger a sweep at most every ``KF_RESOURCE_INTERVAL`` seconds. Served
at worker ``/resources`` with perf-clock anchors; merged NTP-aligned by
the cluster aggregator at ``/cluster/resources``; rendered by
``python -m kungfu_tpu.info resources``. The plane's three consumers:
``PolicyContext.metrics`` (``resource/cpu_frac`` / ``engine_frac`` /
``saturated``), straggler cause classification (network vs compute),
and ``derive_plan``'s predicted-gain compute clamp.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref as _weakref
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import config as tconfig

_US = 1e6


def _now_us() -> float:
    return time.perf_counter() * _US


# ---------------------------------------------------------------------------
# thread-name -> subsystem bucket
# ---------------------------------------------------------------------------

BUCKETS = ("train", "walk_compute", "codec", "sched", "telemetry", "other")

# the saturation line: a peer whose window burned >= this fraction of
# its effective cores is compute-bound (adding network bandwidth cannot
# speed it up — the signal the replan clamp and straggler cause need)
SATURATION_FRAC = 0.9

# longest prefix wins; every name the package declares (KF303 names its
# threads so this table CAN exist):
#   kf-sched-walk     the walk engine's graph walks (reduce + transport)
#   kf-sched-unpack   walk-end decode/unpack — the codec's CPU
#   kf-sched-launch/gather  scheduler bookkeeping
#   kf-pool-*         cached-pool workers (chunked walk fan-outs)
#   kf-cluster/-health/-flight/-lockwatch/-resource  telemetry planes
_PREFIX_BUCKETS: Tuple[Tuple[str, str], ...] = (
    ("kf-sched-walk", "walk_compute"),
    ("kf-sched-unpack", "codec"),
    ("kf-sched-launch", "sched"),
    ("kf-sched-gather", "sched"),
    ("kf-pool", "walk_compute"),
    ("kf-cluster", "telemetry"),
    ("kf-health", "telemetry"),
    ("kf-flight", "telemetry"),
    ("kf-lockwatch", "telemetry"),
    ("kf-resource", "telemetry"),
)


def bucket_for(name: str, is_main: bool = False) -> str:
    """The subsystem bucket a thread's CPU time belongs to. The main
    thread is the training loop by definition; unknown names land in
    ``other`` — attributed somewhere, never dropped."""
    if is_main:
        return "train"
    for prefix, bucket in _PREFIX_BUCKETS:
        if name.startswith(prefix):
            return bucket
    return "other"


def effective_cores() -> float:
    """The cores this process can actually burn (affinity + cgroup
    quota aware) — lazy import: the telemetry layer must stay
    import-light and strategies pulls numpy."""
    from kungfu_tpu.collective.strategies import effective_cpu_count

    return float(effective_cpu_count())


# ---------------------------------------------------------------------------
# per-thread CPU accounting (/proc/self/task/*/stat)
# ---------------------------------------------------------------------------


def _default_names() -> Dict[int, str]:
    """native_id -> thread name for every live Python thread."""
    out: Dict[int, str] = {}
    for t in threading.enumerate():
        tid = getattr(t, "native_id", None)
        if tid is not None:
            out[int(tid)] = t.name
    return out


def _default_main_tid() -> Optional[int]:
    tid = getattr(threading.main_thread(), "native_id", None)
    return int(tid) if tid is not None else None


def parse_stat(line: str, clk_tck: float) -> Optional[float]:
    """Cumulative CPU seconds (utime+stime) from one task stat line.
    The comm field may contain spaces and parens, so split after the
    LAST ')': fields 14/15 of the full line are 12/13 of the tail."""
    end = line.rfind(")")
    if end < 0:
        return None
    rest = line[end + 1:].split()
    if len(rest) < 13:
        return None
    try:
        return (int(rest[11]) + int(rest[12])) / clk_tck
    except ValueError:
        return None


class CpuAccountant:
    """Delta accounting of per-thread CPU seconds onto buckets.

    Injectable taskdir/clk_tck/name sources keep the delta math testable
    on fake /proc fixtures; the default reads the live process. Off
    Linux (no taskdir) every sweep is a graceful no-op and the exported
    document says ``supported: false``.
    """

    def __init__(
        self,
        taskdir: str = "/proc/self/task",
        clk_tck: Optional[float] = None,
        names_fn: Callable[[], Dict[int, str]] = _default_names,
        main_tid_fn: Callable[[], Optional[int]] = _default_main_tid,
    ):
        self.taskdir = taskdir
        if clk_tck is None:
            try:
                clk_tck = float(os.sysconf("SC_CLK_TCK"))
            except (AttributeError, ValueError, OSError):
                clk_tck = 100.0
        self.clk_tck = clk_tck or 100.0
        self._names_fn = names_fn
        self._main_tid_fn = main_tid_fn
        self._lock = threading.Lock()
        self._prev: Dict[int, float] = {}  # tid -> cumulative cpu_s
        self._prev_at: Optional[float] = None  # perf seconds
        self._totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._window: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._window_s = 0.0
        self._sweeps = 0
        self._threads = 0

    def supported(self) -> bool:
        return os.path.isdir(self.taskdir)

    def _read(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        try:
            tids = os.listdir(self.taskdir)
        except OSError:
            return out
        for tid in tids:
            try:
                with open(os.path.join(self.taskdir, tid, "stat")) as f:
                    cpu = parse_stat(f.read(), self.clk_tck)
            except (OSError, ValueError):
                continue  # the thread exited between listdir and open
            if cpu is not None:
                try:
                    out[int(tid)] = cpu
                except ValueError:
                    continue
        return out

    def sweep(self) -> None:
        """One accounting pass: read every task's cumulative CPU time,
        attribute the delta since the previous sweep to its thread's
        bucket. A first-seen tid contributes its full history to the
        bucket TOTALS (CPU burned before the plane came up is still
        attributed) but not to the window — window fractions only ever
        compare like-for-like intervals."""
        if not self.supported():
            return
        now = time.perf_counter()
        cur = self._read()
        names = self._names_fn()
        main_tid = self._main_tid_fn()
        with self._lock:
            window: Dict[str, float] = {b: 0.0 for b in BUCKETS}
            for tid, cpu in cur.items():
                bucket = bucket_for(names.get(tid, ""), tid == main_tid)
                prev = self._prev.get(tid)
                if prev is None:
                    self._totals[bucket] += cpu
                else:
                    d = max(0.0, cpu - prev)
                    self._totals[bucket] += d
                    window[bucket] += d
            if self._prev_at is not None:
                self._window = window
                self._window_s = max(1e-9, now - self._prev_at)
            self._prev = cur
            self._prev_at = now
            self._sweeps += 1
            self._threads = len(cur)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "totals": dict(self._totals),
                "window": dict(self._window),
                "window_s": self._window_s,
                "sweeps": self._sweeps,
                "threads": self._threads,
            }


# ---------------------------------------------------------------------------
# sampling profiler (KF_RESOURCE_SAMPLE_HZ > 0 only)
# ---------------------------------------------------------------------------

_ENGINE_PREFIX = "kungfu_tpu"


def classify_main_frame(frame) -> str:
    """'engine' when the main thread is anywhere inside kungfu_tpu
    (blocked in a collective, flushing the scheduler), else
    'train_compute' — user model code, input pipeline, optimizer."""
    f = frame
    while f is not None:
        mod = f.f_globals.get("__name__", "")
        if isinstance(mod, str) and mod.startswith(_ENGINE_PREFIX):
            return "engine"
        f = f.f_back
    return "train_compute"


class SamplingProfiler:
    """Bounded-ring stack sampler. Only ever constructed when the HZ
    knob is positive — with ``KF_RESOURCE_SAMPLE_HZ=0`` the plane
    allocates NO profiler object and starts no thread (the class-level
    ``allocations`` counter is subprocess-asserted to stay 0, the
    lockwatch/steptrace overhead-guard contract)."""

    allocations = 0

    def __init__(
        self,
        hz: float,
        keep: int,
        main_tid_fn: Callable[[], Optional[int]] = None,
    ):
        SamplingProfiler.allocations += 1
        self.hz = max(0.01, float(hz))
        self._ring: "deque[Tuple[str, Tuple[str, ...]]]" = deque(
            maxlen=max(1, int(keep))
        )
        self._lock = threading.Lock()
        self._main_tid_fn = main_tid_fn or (
            lambda: getattr(threading.main_thread(), "ident", None)
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="kf-resource-sample", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _loop(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
            # kfcheck: disable=KF400 — the sampler thread must survive
            # any race with interpreter/thread teardown; a lost sample
            # is invisible, a dead sampler thread silently ends the
            # profile
            except BaseException:  # noqa: BLE001
                pass

    def sample_once(self, frames: Optional[dict] = None) -> None:
        """One sample (injectable frames make the classification
        deterministic under test): classify the main thread, aggregate
        every thread's top-of-stack module prefix."""
        if frames is None:
            frames = sys._current_frames()
        main_ident = self._main_tid_fn()
        main_class = ""
        prefixes: List[str] = []
        for ident, frame in frames.items():
            if ident == main_ident:
                main_class = classify_main_frame(frame)
            mod = frame.f_globals.get("__name__", "") or "?"
            prefixes.append(".".join(str(mod).split(".")[:2]))
        with self._lock:
            self._ring.append((main_class, tuple(sorted(prefixes))))

    def profile(self) -> dict:
        """Ring aggregation: main-thread split + module-prefix counts."""
        with self._lock:
            samples = list(self._ring)
        main: Dict[str, int] = {"train_compute": 0, "engine": 0}
        mods: Dict[str, int] = {}
        for main_class, prefixes in samples:
            if main_class in main:
                main[main_class] += 1
            for p in prefixes:
                mods[p] = mods.get(p, 0) + 1
        n = len(samples)
        return {
            "hz": self.hz,
            "samples": n,
            "main": main,
            "main_engine_frac": (main["engine"] / n) if n else None,
            "modules": dict(
                sorted(mods.items(), key=lambda kv: -kv[1])[:16]
            ),
        }


# ---------------------------------------------------------------------------
# the plane: accountant + optional profiler + metrics + signals
# ---------------------------------------------------------------------------


class ResourcePlane:
    """One worker's resource attribution plane (the /resources doc)."""

    def __init__(
        self,
        interval: Optional[float] = None,
        sample_hz: Optional[float] = None,
        keep: Optional[int] = None,
        accountant: Optional[CpuAccountant] = None,
        cores_fn: Callable[[], float] = effective_cores,
    ):
        self.interval = (
            interval if interval is not None
            else max(0.1, float(knobs.get("KF_RESOURCE_INTERVAL")))
        )
        hz = (
            sample_hz if sample_hz is not None
            else float(knobs.get("KF_RESOURCE_SAMPLE_HZ"))
        )
        keep = (
            keep if keep is not None
            else max(1, int(knobs.get("KF_RESOURCE_KEEP")))
        )
        self.acct = accountant if accountant is not None else CpuAccountant()
        self._cores_fn = cores_fn
        self._cores: Optional[float] = None
        self._sweep_lock = threading.Lock()
        self._last_sweep: Optional[float] = None
        self._published: Dict[str, float] = {}
        # hz=0: no profiler OBJECT, no thread, no ring — the zero-cost
        # default (subprocess-asserted)
        self.profiler: Optional[SamplingProfiler] = None
        if hz > 0:
            self.profiler = SamplingProfiler(hz, keep)
            self.profiler.start()
        # memory plane (ISSUE 17): the resource plane itself is a
        # long-lived buffer owner (profiler ring + per-thread CPU
        # tables) — accounted under `telemetry` like the other rings.
        # Weakref so reset_plane() doesn't pin the old instance.
        try:
            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=_weakref.ref(self)):
                plane = ref()
                return (
                    plane.footprint_bytes() if plane is not None else None
                )

            _tmem.register_accountant("resource_plane", "telemetry", _acct)
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the resource plane
        except Exception:  # noqa: BLE001
            pass

    def footprint_bytes(self) -> int:
        """Bytes held by the plane's bounded state (memory plane
        `telemetry` bucket): profiler ring at CAP plus CPU tables."""
        from kungfu_tpu.telemetry import memory as _tmem

        with self.acct._lock:
            acct_state = (
                dict(self.acct._prev),
                dict(self.acct._totals),
                dict(self.acct._window),
            )
        total = _tmem.deep_sizeof((acct_state, dict(self._published)))
        prof = self.profiler
        if prof is not None:
            with prof._lock:
                ring = deque(prof._ring, maxlen=prof._ring.maxlen)
            total += _tmem.ring_cap_bytes(ring)
        return total

    def cores(self) -> float:
        if self._cores is None:
            try:
                self._cores = max(1.0, self._cores_fn())
            # kfcheck: disable=KF400 — an unreadable affinity/cgroup
            # surface degrades to 1 core (fractions stay defined);
            # telemetry never kills training
            except BaseException:  # noqa: BLE001
                self._cores = 1.0
        return self._cores

    def maybe_sweep(self, force: bool = False) -> None:
        """Throttled on-demand sweep — every reader path funnels here,
        so the plane needs no sweeper thread of its own."""
        now = time.perf_counter()
        with self._sweep_lock:
            if (
                not force
                and self._last_sweep is not None
                and now - self._last_sweep < self.interval
            ):
                return
            self._last_sweep = now
        self.acct.sweep()
        self._publish_metrics()

    def _publish_metrics(self) -> None:
        if not tconfig.metrics_enabled():
            return
        try:
            from kungfu_tpu.telemetry import metrics as tmetrics

            snap = self.acct.snapshot()
            ctr = tmetrics.counter(
                "kungfu_resource_cpu_seconds_total",
                "CPU seconds burned by this worker, attributed to "
                "subsystem buckets via per-thread accounting",
                ("bucket",),
            )
            g_frac = tmetrics.gauge(
                "kungfu_resource_cpu_frac",
                "Fraction of this worker's effective cores each bucket "
                "burned over the last accounting window",
                ("bucket",),
            )
            cores = self.cores()
            win_s = snap["window_s"]
            for bucket in BUCKETS:
                total = snap["totals"].get(bucket, 0.0)
                prev = self._published.get(bucket, 0.0)
                if total > prev:
                    ctr.labels(bucket=bucket).inc(total - prev)
                    self._published[bucket] = total
                frac = (
                    snap["window"].get(bucket, 0.0) / win_s / cores
                    if win_s > 0 else 0.0
                )
                g_frac.labels(bucket=bucket).set(frac)
            tmetrics.gauge(
                "kungfu_resource_cores_available",
                "Effective cores available to this worker "
                "(affinity + cgroup quota aware)",
            ).set(cores)
        # kfcheck: disable=KF400 — gauge publication rides the sweep
        # path; a registry hiccup (cardinality guard, teardown race)
        # must cost one publication, not the accounting loop
        except BaseException:  # noqa: BLE001
            pass

    # -- derived fractions ----------------------------------------------
    def _fractions(self, snap: dict) -> dict:
        win_s = snap["window_s"]
        busy = sum(snap["window"].values())
        cores = self.cores()
        cpu_frac = busy / win_s / cores if win_s > 0 else 0.0
        engine = sum(
            snap["window"].get(b, 0.0)
            for b in ("walk_compute", "codec", "sched")
        )
        return {
            "cpu_frac": cpu_frac,
            "engine_frac": (engine / busy) if busy > 0 else 0.0,
            "saturated": cpu_frac >= SATURATION_FRAC,
        }

    def export(self, peer: str = "") -> dict:
        """The /resources document (perf-clock anchors match the
        X-KF-Perf-Now-Us header timebase, like /steptrace)."""
        self.maybe_sweep()
        snap = self.acct.snapshot()
        fr = self._fractions(snap)
        busy = sum(snap["window"].values())
        buckets = {}
        for b in BUCKETS:
            buckets[b] = {
                "cpu_s": round(snap["totals"].get(b, 0.0), 6),
                "window_s": round(snap["window"].get(b, 0.0), 6),
                "frac": (
                    round(snap["window"].get(b, 0.0) / busy, 6)
                    if busy > 0 else 0.0
                ),
            }
        doc = {
            "peer": peer or knobs.raw("KF_SELF_SPEC"),
            "perf_now_us": _now_us(),
            "wall_time_s": time.time(),
            "supported": self.acct.supported(),
            "cores": self.cores(),
            "interval_s": self.interval,
            "sweeps": snap["sweeps"],
            "threads": snap["threads"],
            "window_s": round(snap["window_s"], 6),
            "cpu_frac": round(fr["cpu_frac"], 6),
            "engine_frac": round(fr["engine_frac"], 6),
            "saturated": fr["saturated"],
            "buckets": buckets,
        }
        if self.profiler is not None:
            doc["profile"] = self.profiler.profile()
        return doc

    def signals(self) -> Dict[str, object]:
        """Worker-local adaptation signals (PolicyContext.metrics):
        how much of this peer's CPU capacity the window burned, the
        engine's share of that burn, and the compute-bound flag."""
        if not self.acct.supported():
            return {}
        self.maybe_sweep()
        snap = self.acct.snapshot()
        if snap["sweeps"] < 2:
            return {}  # no window yet — never fabricate a fraction
        fr = self._fractions(snap)
        return {
            "resource/cpu_frac": fr["cpu_frac"],
            "resource/engine_frac": fr["engine_frac"],
            "resource/saturated": fr["saturated"],
        }

    def compute_frac(self) -> float:
        """The measured compute floor derive_plan's gain clamp consumes:
        this peer's window CPU fraction, 0.0 when unmeasured (an
        unmeasured peer must never clamp the cluster's prediction)."""
        sig = self.signals()
        v = sig.get("resource/cpu_frac")
        return float(v) if isinstance(v, (int, float)) else 0.0

    def close(self) -> None:
        if self.profiler is not None:
            self.profiler.stop()


_plane: Optional[ResourcePlane] = None
_plane_lock = threading.Lock()


def get_plane() -> ResourcePlane:
    global _plane
    with _plane_lock:
        if _plane is None:
            _plane = ResourcePlane()
        return _plane


def reset_plane() -> None:
    """Drop the process plane (tests flip knobs at runtime)."""
    global _plane
    with _plane_lock:
        if _plane is not None:
            _plane.close()
        _plane = None


# ---------------------------------------------------------------------------
# merge math (pure: the aggregator and tests drive it)
# ---------------------------------------------------------------------------


def merge_resources(
    peer_docs: Dict[str, dict],
    offsets_us: Dict[str, float],
) -> dict:
    """Merge every peer's /resources document into one cluster view:
    per-peer rows with their anchors aligned onto the merger's clock,
    plus the cluster-wide election (max CPU fraction, saturated peers —
    the compute-bound set straggler classification consults)."""
    peers: Dict[str, dict] = {}
    saturated: List[str] = []
    max_cpu = None
    for peer, doc in sorted(peer_docs.items()):
        if not doc:
            continue
        off = offsets_us.get(peer) or 0.0
        row = dict(doc)
        if isinstance(row.get("perf_now_us"), (int, float)):
            row["perf_now_us"] = row["perf_now_us"] + off
        peers[peer] = row
        cf = row.get("cpu_frac")
        if isinstance(cf, (int, float)):
            max_cpu = cf if max_cpu is None else max(max_cpu, cf)
        if row.get("saturated"):
            saturated.append(peer)
    return {
        "peers": peers,
        "saturated": sorted(saturated),
        "max_cpu_frac": max_cpu,
    }


def peer_saturated(merged: Optional[dict], peer: str) -> bool:
    """Does the merged cluster view say this peer is compute-bound?
    False on no data — the caller must never fabricate a cause."""
    if not merged:
        return False
    row = (merged.get("peers") or {}).get(str(peer))
    return bool(row and row.get("saturated"))


# ---------------------------------------------------------------------------
# rendering (info resources + the flight postmortem's final attribution)
# ---------------------------------------------------------------------------

_COLS = ("PEER", "CPU%", "CORES", "TRAIN%", "WALK%", "CODEC%", "SCHED%",
         "TELEM%", "OTHER%", "FLAGS")
_BUCKET_COLS = ("train", "walk_compute", "codec", "sched", "telemetry",
                "other")


def _pct(v) -> str:
    return f"{v * 100:.0f}" if isinstance(v, (int, float)) else "-"


def render_resources(merged: dict) -> List[str]:
    """The merged cluster view as a table: per peer the window CPU
    fraction, cores, the per-bucket busy shares and the saturation
    flag."""
    peers = merged.get("peers") or {}
    rows = []
    for peer, doc in sorted(peers.items()):
        if not doc.get("supported", True):
            rows.append((peer,) + ("-",) * 8 + ("unsupported",))
            continue
        buckets = doc.get("buckets") or {}
        flags = "SATURATED" if doc.get("saturated") else ""
        prof = doc.get("profile") or {}
        ef = prof.get("main_engine_frac")
        if isinstance(ef, (int, float)):
            flags = (flags + " " if flags else "") + f"main-eng {ef:.0%}"
        rows.append((
            peer,
            _pct(doc.get("cpu_frac")),
            f"{doc.get('cores'):.0f}" if isinstance(
                doc.get("cores"), (int, float)) else "-",
            *(
                _pct((buckets.get(b) or {}).get("frac"))
                for b in _BUCKET_COLS
            ),
            flags,
        ))
    widths = [
        max(len(_COLS[i]), max((len(r[i]) for r in rows), default=0))
        for i in range(len(_COLS))
    ]
    lines = ["  ".join(c.ljust(widths[i]) for i, c in enumerate(_COLS))]
    for r in rows:
        lines.append("  ".join(c.ljust(widths[i]) for i, c in enumerate(r)))
    sat = merged.get("saturated") or []
    summary = f"{len(peers)} peers"
    if sat:
        summary += f", compute-saturated: {', '.join(sat)}"
    if isinstance(merged.get("max_cpu_frac"), (int, float)):
        summary += f", max cpu {merged['max_cpu_frac']:.0%}"
    lines.append(summary)
    return lines


def render_worker_resources(doc: dict) -> List[str]:
    """One UNMERGED worker document (the postmortem's final CPU
    attribution: no cluster view exists for a dead worker)."""
    if not doc:
        return ["no resource data"]
    if not doc.get("supported", True):
        return ["resource accounting unsupported on this platform"]
    lines = []
    head = (
        f"cpu {_pct(doc.get('cpu_frac'))}% of "
        f"{doc.get('cores')} cores"
    )
    if doc.get("saturated"):
        head += "  SATURATED (compute-bound at death)"
    lines.append(head)
    buckets = doc.get("buckets") or {}
    for b in _BUCKET_COLS:
        info = buckets.get(b) or {}
        total = info.get("cpu_s")
        if not isinstance(total, (int, float)) or total <= 0:
            continue
        lines.append(
            f"  {b:<14} {total:8.1f}s total"
            f"  {_pct(info.get('frac')):>4}% of recent busy"
        )
    prof = doc.get("profile") or {}
    ef = prof.get("main_engine_frac")
    if isinstance(ef, (int, float)):
        lines.append(
            f"  main thread: {ef:.0%} of samples blocked in the engine"
        )
    return lines
