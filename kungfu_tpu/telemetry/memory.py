"""Memory attribution plane: per-subsystem byte accounting, leak
watchdog and OOM-headroom forecasting (ISSUE 17).

PR 16's resource plane closed the CPU side of "why is this peer slow?"
but the axis that actually KILLS workers stayed dark: an OOM death
harvests as an unexplained exit -9, ZeRO-1 (PR 9) trades communication
for optimizer-state memory without the trade ever being measured live,
and ROADMAP item 3's unattended autoscaler cannot safely grow without
a measured headroom signal. This module is the missing feed, three
parts:

- **RSS decomposition**: long-lived buffer owners (shm arenas, the
  scratch buffer pool, ZeRO mirrors + f32 shard masters, the
  scheduler's in-flight units, the bounded telemetry rings) register
  byte accountants via :func:`register_accountant`; every sweep sums
  them into buckets {arena, pool, zero_state, sched_inflight,
  telemetry} and reports ``untracked = RSS - sum(tracked)`` as a
  first-class bucket — the unexplained share is surfaced, never
  hidden. Bounded rings report their CAP (mean item size x maxlen),
  so ring fill-up is exempt from leak detection by construction.
- **Headroom forecasting**: a cgroup-aware :func:`effective_mem_limit`
  (v2 ``memory.max``, v1 hierarchical fallback — the memory mirror of
  ``effective_cpu_count``) plus a windowed linear RSS trend yield
  ``memory/headroom_frac`` and an honest steps-to-exhaustion estimate
  that is ``None`` whenever the trend is flat or noisy — never
  fabricated.
- **Leak watchdog**: a bucket whose tracked bytes grow STRICTLY for
  ``KF_MEMORY_WINDOWS`` consecutive sweeps fires a one-shot
  ``memory_leak_suspect`` audit event naming the bucket. Streaks only
  arm after ``KF_MEMORY_WARMUP`` seconds: a booting process's RSS
  grows monotonically by nature (imports, first allocations), and a
  real leak outlives any boot transient.

Sweeps are on-demand (no sweeper thread): ``export()`` / ``signals()``
trigger a sweep at most every ``KF_MEMORY_INTERVAL`` seconds. Served
at worker ``/memory`` with perf-clock anchors; merged NTP-aligned at
``/cluster/memory``; rendered by ``python -m kungfu_tpu.info memory``.
Consumers: ``PolicyContext.metrics`` (``memory/headroom_frac`` /
``pressure`` / ``leak_suspect``), straggler cause classification
(major-fault rate -> STRAGGLER(memory)), the elastic grow gate
(:meth:`MemoryPlane.grow_ok`) and the flight recorder's OOM
forensics.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import config as tconfig

_US = 1e6


def _now_us() -> float:
    return time.perf_counter() * _US


# ---------------------------------------------------------------------------
# buckets and thresholds
# ---------------------------------------------------------------------------

BUCKETS = ("arena", "pool", "zero_state", "sched_inflight", "telemetry",
           "untracked")

# the pressure line: a peer whose measured headroom fraction is at or
# below this is under memory pressure — the grow gate defers resize
# proposals and `info top` flags the peer
PRESSURE_FRAC = 0.15

# the thrashing line: sustained major faults per second above this mean
# the peer is paging its working set off disk/swap — the memory cause
# the straggler classifier ranks between network and compute
THRASH_FAULTS_PER_S = 10.0


# ---------------------------------------------------------------------------
# effective memory limit (cgroup v2 -> v1 -> physical RAM)
# ---------------------------------------------------------------------------

# module constants so tests can point them at fixture files (the
# effective_cpu_count idiom from collective/strategies.py)
CGROUP_V2_MEM_MAX = "/sys/fs/cgroup/memory.max"
CGROUP_V1_MEM_LIMIT = "/sys/fs/cgroup/memory/memory.limit_in_bytes"
CGROUP_V1_MEM_STAT = "/sys/fs/cgroup/memory/memory.stat"

# v1 reports "unlimited" as a huge page-rounded sentinel (commonly
# 0x7ffffffffffff000); anything this large is not a real limit
_V1_UNLIMITED = 1 << 60


def _cgroup_mem_limit() -> int:
    """Memory limit in bytes from the cgroup, or 0 when unlimited or
    unreadable. v2: ``memory.max`` is bytes or "max"; v1:
    ``memory.limit_in_bytes`` (huge sentinel meaning unlimited) with
    ``memory.stat``'s hierarchical_memory_limit as the fallback — a
    child cgroup may be "unlimited" while an ancestor is not."""
    try:
        with open(CGROUP_V2_MEM_MAX) as f:
            raw = f.read().strip()
        if raw != "max":
            limit = int(raw)
            if 0 < limit < _V1_UNLIMITED:
                return limit
    except (OSError, ValueError):
        pass
    for path, key in (
        (CGROUP_V1_MEM_LIMIT, None),
        (CGROUP_V1_MEM_STAT, "hierarchical_memory_limit"),
    ):
        try:
            with open(path) as f:
                if key is None:
                    limit = int(f.read().strip())
                else:
                    limit = 0
                    for line in f:
                        name, _, val = line.partition(" ")
                        if name == key:
                            limit = int(val)
                            break
            if 0 < limit < _V1_UNLIMITED:
                return limit
        except (OSError, ValueError):
            pass
    return 0


def _phys_mem_bytes() -> int:
    try:
        return int(os.sysconf("SC_PHYS_PAGES")) * int(os.sysconf("SC_PAGE_SIZE"))
    except (AttributeError, ValueError, OSError):
        return 0


def effective_mem_limit() -> int:
    """The bytes this process can actually allocate before the OOM
    killer visits: `KF_MEMORY_LIMIT` override first (rehearse a tight
    limit without a real cgroup), else the cgroup limit, else physical
    RAM. 0 means unknowable — headroom is then undefined, not faked."""
    override = int(knobs.get("KF_MEMORY_LIMIT"))
    if override > 0:
        return override
    limit = _cgroup_mem_limit()
    if limit > 0:
        return limit
    return _phys_mem_bytes()


# ---------------------------------------------------------------------------
# bounded deep sizeof + ring-cap measurement
# ---------------------------------------------------------------------------


def deep_sizeof(obj, max_nodes: int = 100_000) -> int:
    """Recursive ``sys.getsizeof`` over containers, bounded by
    ``max_nodes`` visited objects (telemetry must never spend unbounded
    CPU measuring itself). numpy arrays contribute ``nbytes`` without
    recursion; shared objects count once (id-visited)."""
    seen = set()
    total = 0
    stack = [obj]
    nodes = 0
    while stack and nodes < max_nodes:
        o = stack.pop()
        oid = id(o)
        if oid in seen:
            continue
        seen.add(oid)
        nodes += 1
        nbytes = getattr(o, "nbytes", None)
        if isinstance(nbytes, int):
            total += nbytes
            continue
        try:
            total += sys.getsizeof(o)
        except TypeError:
            total += 64
        if isinstance(o, dict):
            stack.extend(o.keys())
            stack.extend(o.values())
        elif isinstance(o, (list, tuple, set, frozenset, deque)):
            stack.extend(o)
        elif hasattr(o, "__dict__") and not callable(o):
            stack.append(o.__dict__)
    return total


def ring_cap_bytes(ring) -> int:
    """A bounded ring's CAPACITY estimate in bytes: mean measured item
    size x maxlen, rounded UP to 1 KiB. Constant from the first item
    on, so a filling ring never looks like monotone growth to the leak
    watchdog — the "exempt by construction" contract. The quantization
    matters: the sampled mean jitters by a few bytes as items rotate
    (e.g. ``sys.getsizeof(0)`` is smaller than other small ints), and
    without it that jitter can drift monotonically across a fill and
    fake a streak. Unbounded containers (maxlen None) report their
    actual deep size: their growth is real."""
    try:
        items = list(ring)
    except TypeError:
        return deep_sizeof(ring)
    maxlen = getattr(ring, "maxlen", None)
    if not items:
        return 0
    if maxlen is None:
        return deep_sizeof(items)
    step = max(1, len(items) // 8)
    sample = items[::step][:8]
    mean = sum(deep_sizeof(i, max_nodes=2_000) for i in sample) / len(sample)
    return -(-int(mean * maxlen) // 1024) * 1024


# ---------------------------------------------------------------------------
# the accountant registry (module-level: owners register before the
# plane exists and survive plane resets)
# ---------------------------------------------------------------------------

_acct_lock = threading.Lock()
_accountants: Dict[int, Tuple[str, str, Callable[[], Optional[int]]]] = {}
_acct_seq = 0


class Accountant:
    """Handle returned by :func:`register_accountant`; ``close()``
    unregisters. Owners that cannot call close (e.g. weakref-tracked
    sessions) may instead return None from their fn — the registry
    drops the entry on the next sweep."""

    def __init__(self, key: int, name: str, bucket: str):
        self.key = key
        self.name = name
        self.bucket = bucket

    def close(self) -> None:
        with _acct_lock:
            _accountants.pop(self.key, None)


def register_accountant(
    name: str, bucket: str, fn: Callable[[], Optional[int]]
) -> Accountant:
    """Register a byte accountant: ``fn`` returns the owner's currently
    held bytes, or None when the owner is gone (the entry is then
    dropped — weakref-friendly, so the registry never pins a ZeRO
    session across an elastic resize). An fn that raises is dropped
    too: telemetry never kills training, and a broken accountant must
    not poison every future sweep."""
    global _acct_seq
    if bucket not in BUCKETS or bucket == "untracked":
        raise ValueError(f"unknown accountant bucket {bucket!r}")
    with _acct_lock:
        _acct_seq += 1
        key = _acct_seq
        _accountants[key] = (name, bucket, fn)
    return Accountant(key, name, bucket)


def tracked_bytes() -> Tuple[Dict[str, int], Dict[str, int]]:
    """One registry pass: (per-bucket totals, per-accountant bytes).
    Dead accountants (fn returned None or raised) are dropped."""
    with _acct_lock:
        entries = list(_accountants.items())
    per_bucket: Dict[str, int] = {b: 0 for b in BUCKETS if b != "untracked"}
    per_name: Dict[str, int] = {}
    dead: List[int] = []
    for key, (name, bucket, fn) in entries:
        try:
            v = fn()
        # kfcheck: disable=KF400 — a raising accountant is dropped, not
        # retried forever and never allowed to break the sweep
        except BaseException:  # noqa: BLE001
            v = None
        if v is None:
            dead.append(key)
            continue
        v = max(0, int(v))
        per_bucket[bucket] += v
        per_name[name] = per_name.get(name, 0) + v
    if dead:
        with _acct_lock:
            for key in dead:
                _accountants.pop(key, None)
    return per_bucket, per_name


# ---------------------------------------------------------------------------
# process-level readers (injectable for tests)
# ---------------------------------------------------------------------------


def _default_rss(statm_path: str = "/proc/self/statm") -> Optional[int]:
    """Resident set size in bytes from /proc/self/statm field 1."""
    try:
        with open(statm_path) as f:
            parts = f.read().split()
        return int(parts[1]) * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError, AttributeError):
        return None


def parse_majflt(line: str) -> Optional[int]:
    """Cumulative major page faults from a /proc/<pid>/stat line. The
    comm field may contain spaces and parens, so split after the LAST
    ')': majflt is field 12 of the full line, index 9 of the tail."""
    end = line.rfind(")")
    if end < 0:
        return None
    rest = line[end + 1:].split()
    if len(rest) < 10:
        return None
    try:
        return int(rest[9])
    except ValueError:
        return None


def _default_majflt(stat_path: str = "/proc/self/stat") -> Optional[int]:
    try:
        with open(stat_path) as f:
            return parse_majflt(f.read())
    except OSError:
        return None


def _default_steps() -> Optional[float]:
    """The training step counter, for the steps-to-exhaustion estimate
    (same read the flight recorder uses for its step anchor)."""
    try:
        from kungfu_tpu.telemetry import metrics as tmetrics

        m = tmetrics.get_registry().get("kungfu_steps_total")
        return m.value if m is not None else None
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------


class MemoryPlane:
    """One worker's memory attribution plane (the /memory doc)."""

    def __init__(
        self,
        interval: Optional[float] = None,
        windows: Optional[int] = None,
        warmup: Optional[float] = None,
        trend_keep: Optional[int] = None,
        rss_fn: Callable[[], Optional[int]] = _default_rss,
        limit_fn: Callable[[], int] = effective_mem_limit,
        majflt_fn: Callable[[], Optional[int]] = _default_majflt,
        steps_fn: Callable[[], Optional[float]] = _default_steps,
    ):
        self.interval = (
            interval if interval is not None
            else max(0.1, float(knobs.get("KF_MEMORY_INTERVAL")))
        )
        self.windows = (
            windows if windows is not None
            else max(2, int(knobs.get("KF_MEMORY_WINDOWS")))
        )
        self.warmup = (
            warmup if warmup is not None
            else max(0.0, float(knobs.get("KF_MEMORY_WARMUP")))
        )
        self._born = time.perf_counter()
        trend_keep = (
            trend_keep if trend_keep is not None
            else max(4, int(knobs.get("KF_MEMORY_TREND")))
        )
        self._rss_fn = rss_fn
        self._limit_fn = limit_fn
        self._majflt_fn = majflt_fn
        self._steps_fn = steps_fn
        self._lock = threading.Lock()
        self._sweep_lock = threading.Lock()
        self._last_sweep: Optional[float] = None
        self._limit: Optional[int] = None
        self._trend: "deque[Tuple[float, int]]" = deque(maxlen=trend_keep)
        # watchdog state: last seen bytes + strict-growth streak per
        # bucket, and the one-shot fired set
        self._prev_bytes: Dict[str, int] = {}
        self._streak: Dict[str, int] = {}
        self._fired: List[str] = []
        # thrash state
        self._prev_majflt: Optional[int] = None
        self._prev_majflt_at: Optional[float] = None
        self._majflt_rate: Optional[float] = None
        # step-rate state
        self._prev_steps: Optional[float] = None
        self._steps_rate: Optional[float] = None
        # last sweep snapshot
        self._rss: Optional[int] = None
        self._buckets: Dict[str, int] = {}
        self._per_name: Dict[str, int] = {}
        self._sweeps = 0

    # -- limit (cached: cgroup files don't change under us) -------------
    def limit_bytes(self) -> int:
        if self._limit is None:
            try:
                self._limit = max(0, int(self._limit_fn()))
            # kfcheck: disable=KF400 — an unreadable cgroup surface
            # degrades to "no limit known" (headroom undefined);
            # telemetry never kills training
            except BaseException:  # noqa: BLE001
                self._limit = 0
        return self._limit

    def supported(self) -> bool:
        return self._rss is not None or self._rss_fn() is not None

    # -- sweeping --------------------------------------------------------
    def maybe_sweep(self, force: bool = False) -> None:
        """Throttled on-demand sweep — every reader path funnels here,
        so the plane needs no sweeper thread of its own."""
        now = time.perf_counter()
        with self._sweep_lock:
            if (
                not force
                and self._last_sweep is not None
                and now - self._last_sweep < self.interval
            ):
                return
            self._last_sweep = now
        self._sweep(now)
        self._publish_metrics()

    def _sweep(self, now: float) -> None:
        rss = self._rss_fn()
        per_bucket, per_name = tracked_bytes()
        fired_now: List[str] = []
        with self._lock:
            self._sweeps += 1
            self._per_name = per_name
            if rss is not None:
                tracked = sum(per_bucket.values())
                per_bucket["untracked"] = max(0, rss - tracked)
                self._rss = rss
                self._trend.append((now, rss))
            self._buckets = per_bucket
            # leak watchdog: strict growth streak per bucket. Bounded
            # rings report their cap, so ring fill never streaks; and
            # nothing streaks before the warmup grace elapses — boot
            # growth (imports, first allocations) is expected, and a
            # real leak keeps growing long after the transient.
            armed = self.warmup <= 0 or now - self._born >= self.warmup
            for bucket, nbytes in per_bucket.items():
                prev = self._prev_bytes.get(bucket)
                if armed and prev is not None and nbytes > prev:
                    self._streak[bucket] = self._streak.get(bucket, 0) + 1
                else:
                    self._streak[bucket] = 0
                self._prev_bytes[bucket] = nbytes
                if (
                    self._streak[bucket] >= self.windows
                    and bucket not in self._fired
                ):
                    self._fired.append(bucket)
                    fired_now.append(bucket)
            # thrash rate: major faults per second over the window
            mf = self._majflt_fn()
            if mf is not None and self._prev_majflt is not None:
                dt = now - (self._prev_majflt_at or now)
                if dt > 0 and mf >= self._prev_majflt:
                    self._majflt_rate = (mf - self._prev_majflt) / dt
            if mf is not None:
                self._prev_majflt = mf
                self._prev_majflt_at = now
            # step rate (for steps-to-exhaustion)
            steps = self._steps_fn()
            if (
                steps is not None
                and self._prev_steps is not None
                and self._last_window_s() > 0
                and steps >= self._prev_steps  # restart resets to 0
            ):
                self._steps_rate = (
                    (steps - self._prev_steps) / self._last_window_s()
                )
            self._prev_steps = steps
        for bucket in fired_now:
            self._fire_leak(bucket)

    def _last_window_s(self) -> float:
        if len(self._trend) < 2:
            return 0.0
        return max(0.0, self._trend[-1][0] - self._trend[-2][0])

    def _fire_leak(self, bucket: str) -> None:
        try:
            from kungfu_tpu.telemetry import audit

            audit.record_event(
                "memory_leak_suspect",
                trigger="leak_watchdog",
                bucket=bucket,
                windows=self.windows,
                bytes=self._buckets.get(bucket, 0),
            )
        # kfcheck: disable=KF400 — the watchdog verdict must not kill
        # the sweep if the audit ring is mid-teardown
        except BaseException:  # noqa: BLE001
            pass

    # -- trend / forecast ------------------------------------------------
    def trend_bytes_per_s(self) -> Optional[float]:
        """Least-squares RSS slope over the trend window, or None when
        there are too few samples or the fit is noise (fitted growth
        under 2x the RMS residual) — an honest None, never a fabricated
        forecast."""
        with self._lock:
            pts = list(self._trend)
        if len(pts) < 4:
            return None
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [float(r) for _, r in pts]
        n = len(pts)
        mx = sum(xs) / n
        my = sum(ys) / n
        den = sum((x - mx) ** 2 for x in xs)
        if den <= 0:
            return None
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / den
        b = my - slope * mx
        rms = (
            sum((y - (slope * x + b)) ** 2 for x, y in zip(xs, ys)) / n
        ) ** 0.5
        span = xs[-1] - xs[0]
        if abs(slope) * span <= 2.0 * rms:
            return None  # flat or noisy — no trend
        return slope

    def headroom_frac(self) -> Optional[float]:
        limit = self.limit_bytes()
        with self._lock:
            rss = self._rss
        if limit <= 0 or rss is None:
            return None
        return max(0.0, (limit - rss) / limit)

    def forecast(self) -> Tuple[Optional[float], Optional[float]]:
        """(seconds, steps) to exhaustion at the current trend, both
        None unless the trend is a real positive slope AND the limit is
        known (steps additionally needs a measured step rate)."""
        slope = self.trend_bytes_per_s()
        limit = self.limit_bytes()
        with self._lock:
            rss = self._rss
            steps_rate = self._steps_rate
        if slope is None or slope <= 0 or limit <= 0 or rss is None:
            return None, None
        secs = max(0.0, (limit - rss) / slope)
        steps = (
            secs * steps_rate
            if steps_rate is not None and steps_rate > 0 else None
        )
        return secs, steps

    # -- metrics ---------------------------------------------------------
    def _publish_metrics(self) -> None:
        if not tconfig.metrics_enabled():
            return
        try:
            from kungfu_tpu.telemetry import metrics as tmetrics

            g_bytes = tmetrics.gauge(
                "kungfu_memory_bytes",
                "Resident bytes attributed to each subsystem bucket "
                "(untracked = RSS minus everything the accountants "
                "explain)",
                ("bucket",),
            )
            with self._lock:
                buckets = dict(self._buckets)
            for bucket, nbytes in buckets.items():
                g_bytes.labels(bucket=bucket).set(float(nbytes))
            limit = self.limit_bytes()
            tmetrics.gauge(
                "kungfu_memory_limit_bytes",
                "Effective memory limit (KF_MEMORY_LIMIT override, "
                "cgroup v2/v1, or physical RAM); 0 when unknowable",
            ).set(float(limit))
            hf = self.headroom_frac()
            if hf is not None:
                tmetrics.gauge(
                    "kungfu_memory_headroom_frac",
                    "Fraction of the effective memory limit still free "
                    "(limit - rss) / limit",
                ).set(hf)
        # kfcheck: disable=KF400 — gauge publication rides the sweep
        # path; a registry hiccup must cost one publication, not the
        # accounting loop
        except BaseException:  # noqa: BLE001
            pass

    # -- export / signals ------------------------------------------------
    def export(self, peer: str = "") -> dict:
        """The /memory document (perf-clock anchors match the
        X-KF-Perf-Now-Us header timebase, like /resources)."""
        self.maybe_sweep()
        with self._lock:
            rss = self._rss
            buckets = dict(self._buckets)
            per_name = dict(self._per_name)
            sweeps = self._sweeps
            majflt_rate = self._majflt_rate
            fired = list(self._fired)
        limit = self.limit_bytes()
        hf = self.headroom_frac()
        secs, steps = self.forecast()
        bucket_docs = {}
        for b in BUCKETS:
            nbytes = buckets.get(b, 0)
            bucket_docs[b] = {
                "bytes": nbytes,
                "frac": round(nbytes / rss, 6) if rss else 0.0,
            }
        thrashing = (
            majflt_rate is not None and majflt_rate >= THRASH_FAULTS_PER_S
        )
        return {
            "peer": peer or knobs.raw("KF_SELF_SPEC"),
            "perf_now_us": _now_us(),
            "wall_time_s": time.time(),
            "supported": rss is not None,
            "rss_bytes": rss,
            "limit_bytes": limit,
            "headroom_frac": round(hf, 6) if hf is not None else None,
            "trend_bytes_per_s": self.trend_bytes_per_s(),
            "exhaustion_s": round(secs, 3) if secs is not None else None,
            "steps_to_exhaustion": (
                round(steps, 1) if steps is not None else None
            ),
            "majflt_rate": (
                round(majflt_rate, 3) if majflt_rate is not None else None
            ),
            "thrashing": thrashing,
            "pressure": hf is not None and hf <= PRESSURE_FRAC,
            "interval_s": self.interval,
            "sweeps": sweeps,
            "buckets": bucket_docs,
            "accountants": per_name,
            "leak_suspects": fired,
        }

    def signals(self) -> Dict[str, object]:
        """Worker-local adaptation signals (PolicyContext.metrics).
        Empty until two sweeps exist; headroom/pressure only when a
        limit is actually known — never fabricate."""
        self.maybe_sweep()
        with self._lock:
            sweeps = self._sweeps
            rss = self._rss
            fired = bool(self._fired)
        if rss is None or sweeps < 2:
            return {}
        out: Dict[str, object] = {"memory/leak_suspect": fired}
        hf = self.headroom_frac()
        if hf is not None:
            out["memory/headroom_frac"] = hf
            out["memory/pressure"] = hf <= PRESSURE_FRAC
        return out

    def grow_ok(self) -> Tuple[bool, str]:
        """The elastic grow gate: may this worker's cluster safely grow
        right now? (True, "unmeasured") when headroom is unknown — an
        unmeasured peer must never block a resize — and (False, why)
        only under MEASURED pressure."""
        sig = self.signals()
        hf = sig.get("memory/headroom_frac")
        if not isinstance(hf, (int, float)):
            return True, "unmeasured"
        if hf <= PRESSURE_FRAC:
            return False, (
                f"headroom {hf:.0%} <= pressure line {PRESSURE_FRAC:.0%}"
            )
        return True, f"headroom {hf:.0%}"

    def close(self) -> None:
        pass  # the plane owns no threads and no accountants


_plane: Optional[MemoryPlane] = None
_plane_lock = threading.Lock()


def get_plane() -> MemoryPlane:
    global _plane
    with _plane_lock:
        if _plane is None:
            _plane = MemoryPlane()
        return _plane


def reset_plane() -> None:
    """Drop the process plane (tests flip knobs at runtime). The
    accountant registry is module-level and survives: owners register
    once at construction, not per plane."""
    global _plane
    with _plane_lock:
        if _plane is not None:
            _plane.close()
        _plane = None


# ---------------------------------------------------------------------------
# merge math (pure: the aggregator and tests drive it)
# ---------------------------------------------------------------------------


def merge_memory(
    peer_docs: Dict[str, dict],
    offsets_us: Dict[str, float],
) -> dict:
    """Merge every peer's /memory document into one cluster view:
    per-peer rows with their anchors aligned onto the merger's clock,
    plus the cluster-wide elections the autoscaler and the straggler
    classifier consult (minimum headroom + its peer, the
    under-pressure and thrashing sets, who suspects a leak)."""
    peers: Dict[str, dict] = {}
    pressure: List[str] = []
    thrashing: List[str] = []
    leaks: Dict[str, List[str]] = {}
    min_hf = None
    min_peer = None
    for peer, doc in sorted(peer_docs.items()):
        if not doc:
            continue
        off = offsets_us.get(peer) or 0.0
        row = dict(doc)
        if isinstance(row.get("perf_now_us"), (int, float)):
            row["perf_now_us"] = row["perf_now_us"] + off
        peers[peer] = row
        hf = row.get("headroom_frac")
        if isinstance(hf, (int, float)):
            if min_hf is None or hf < min_hf:
                min_hf, min_peer = hf, peer
        if row.get("pressure"):
            pressure.append(peer)
        if row.get("thrashing"):
            thrashing.append(peer)
        if row.get("leak_suspects"):
            leaks[peer] = list(row["leak_suspects"])
    return {
        "peers": peers,
        "min_headroom_frac": min_hf,
        "min_headroom_peer": min_peer,
        "pressure": sorted(pressure),
        "thrashing": sorted(thrashing),
        "leak_suspects": leaks,
    }


def peer_thrashing(merged: Optional[dict], peer: str) -> bool:
    """Does the merged cluster view say this peer is paging? False on
    no data — the caller must never fabricate a cause."""
    if not merged:
        return False
    row = (merged.get("peers") or {}).get(str(peer))
    return bool(row and row.get("thrashing"))


# ---------------------------------------------------------------------------
# rendering (info memory + the flight postmortem's final attribution)
# ---------------------------------------------------------------------------

_COLS = ("PEER", "RSS", "LIMIT", "MEM%", "HEADROOM", "TREND/S", "ARENA",
         "POOL", "ZERO", "SCHED", "TELEM", "UNTRK%", "FLAGS")


def fmt_bytes(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    v = float(v)
    for unit in ("B", "K", "M", "G", "T"):
        if abs(v) < 1024 or unit == "T":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return "-"


def _pct(v) -> str:
    return f"{v * 100:.0f}" if isinstance(v, (int, float)) else "-"


def _row_flags(doc: dict) -> str:
    flags = []
    if doc.get("pressure"):
        flags.append("PRESSURE")
    if doc.get("thrashing"):
        flags.append("THRASHING")
    if doc.get("leak_suspects"):
        flags.append("leak:" + ",".join(doc["leak_suspects"]))
    secs = doc.get("exhaustion_s")
    if isinstance(secs, (int, float)):
        flags.append(f"oom~{secs:.0f}s")
    return " ".join(flags)


def render_memory(merged: dict) -> List[str]:
    """The merged cluster view as a table: per peer the RSS, limit,
    used/headroom fractions, RSS trend and the bucket decomposition
    (untracked as a share of RSS — the honesty column)."""
    peers = merged.get("peers") or {}
    rows = []
    for peer, doc in sorted(peers.items()):
        if not doc.get("supported", True):
            rows.append((peer,) + ("-",) * 11 + ("unsupported",))
            continue
        buckets = doc.get("buckets") or {}
        rss = doc.get("rss_bytes")
        limit = doc.get("limit_bytes")
        hf = doc.get("headroom_frac")
        used = (
            1.0 - hf if isinstance(hf, (int, float)) else None
        )
        trend = doc.get("trend_bytes_per_s")
        rows.append((
            peer,
            fmt_bytes(rss),
            fmt_bytes(limit) if limit else "-",
            _pct(used),
            _pct(hf),
            fmt_bytes(trend) if trend is not None else "-",
            *(
                fmt_bytes((buckets.get(b) or {}).get("bytes"))
                for b in ("arena", "pool", "zero_state", "sched_inflight",
                          "telemetry")
            ),
            _pct((buckets.get("untracked") or {}).get("frac")),
            _row_flags(doc),
        ))
    widths = [
        max(len(_COLS[i]), max((len(r[i]) for r in rows), default=0))
        for i in range(len(_COLS))
    ]
    lines = ["  ".join(c.ljust(widths[i]) for i, c in enumerate(_COLS))]
    for r in rows:
        lines.append("  ".join(c.ljust(widths[i]) for i, c in enumerate(r)))
    summary = f"{len(peers)} peers"
    if isinstance(merged.get("min_headroom_frac"), (int, float)):
        summary += (
            f", min headroom {merged['min_headroom_frac']:.0%}"
            f" ({merged.get('min_headroom_peer')})"
        )
    if merged.get("pressure"):
        summary += f", pressure: {', '.join(merged['pressure'])}"
    if merged.get("thrashing"):
        summary += f", thrashing: {', '.join(merged['thrashing'])}"
    if merged.get("leak_suspects"):
        summary += ", leaks: " + ", ".join(
            f"{p}({','.join(bs)})"
            for p, bs in sorted(merged["leak_suspects"].items())
        )
    lines.append(summary)
    return lines


def render_worker_memory(doc: dict) -> List[str]:
    """One UNMERGED worker document (the postmortem's final memory
    attribution: no cluster view exists for a dead worker)."""
    if not doc:
        return ["no memory data"]
    if not doc.get("supported", True):
        return ["memory accounting unsupported on this platform"]
    lines = []
    head = f"rss {fmt_bytes(doc.get('rss_bytes'))}"
    limit = doc.get("limit_bytes")
    if limit:
        head += f" of {fmt_bytes(limit)} limit"
    hf = doc.get("headroom_frac")
    if isinstance(hf, (int, float)):
        head += f"  ({hf:.0%} headroom)"
    trend = doc.get("trend_bytes_per_s")
    if isinstance(trend, (int, float)):
        head += f"  trend {fmt_bytes(trend)}/s"
    lines.append(head)
    buckets = doc.get("buckets") or {}
    for b in BUCKETS:
        info = buckets.get(b) or {}
        nbytes = info.get("bytes")
        if not isinstance(nbytes, (int, float)) or nbytes <= 0:
            continue
        lines.append(
            f"  {b:<14} {fmt_bytes(nbytes):>8}"
            f"  {_pct(info.get('frac')):>4}% of rss"
        )
    flags = _row_flags(doc)
    if flags:
        lines.append(f"  flags: {flags}")
    return lines
