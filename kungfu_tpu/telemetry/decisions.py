"""Decision ledger: the causal adaptation timeline (ISSUE 15 tentpole).

The engine adapts five independent ways — strategy/wire votes, measured
ring re-planning, async/ZeRO mode flips at session epochs, elastic
resizes — and each flip stamps a fire-and-forget audit event carrying a
*prediction* (``predicted_gain``, a trigger). Nothing ever measured
whether an adaptation actually helped: ``plan/replan.py`` predicts a
throughput ratio, no code computed the realized one. This module closes
that loop per worker:

- every adaptation becomes an open :class:`DecisionRecord` — decision
  kind, trigger, signal snapshot, predicted gain, session epoch — with
  a **baseline window** captured at the flip (the last
  ``KF_DECISION_WINDOW`` step durations the training loop fed via
  :func:`note_step`);
- after a settle period (``KF_DECISION_SETTLE`` steps, letting caches /
  pools / estimators re-warm under the new configuration) the next
  window of step durations closes the record with a **realized_gain**
  (= baseline mean step time / after mean step time; >1 means the
  cluster got faster) and a verdict — ``delivered`` / ``neutral`` /
  ``regressed`` — guarded against window noise (a gain inside the
  windows' own variance band is ``neutral``, never ``delivered``);
- a closed record emits a ``decision_outcome`` audit event plus
  ``kungfu_decision_realized_gain{kind}`` /
  ``kungfu_decisions_total{kind,verdict}`` metrics, and a **regression
  watchdog** keeps watching a ``regressed`` close: when the realized
  gain stays under ``KF_DECISION_REGRESS_RATIO`` for
  ``KF_DECISION_PATIENCE`` consecutive windows it fires an
  ``adaptation_regressed`` audit event — the rollback signal future
  policies (and the unattended autoscaler, ROADMAP item 4) key off.

Served at the worker's ``/decisions`` endpoint with perf-clock anchors
(the /steptrace discipline) so the cluster aggregator can merge every
worker's ledger NTP-aligned at ``/cluster/decisions``; journaled by the
flight recorder so a postmortem names the adaptation the cluster was
mid-flip on at death (an unclosed record with no outcome IS that
answer); rendered by ``python -m kungfu_tpu.info decisions``.

A run that never adapts opens no records, feeds only a small rolling
deque, and emits zero ``decision_outcome`` events — the ledger is
silent by construction. ``KF_DECISION_KEEP=0`` disables it entirely
(:func:`open_decision` returns None and allocates nothing).

This module must stay import-light (telemetry-only imports): the
decision sites live on the session-epoch and vote paths.
"""

from __future__ import annotations

import math
import threading
import time
import weakref as _weakref
from collections import deque
from typing import Dict, List, Optional

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import config as tconfig

_US = 1e6

# realized-gain changes inside this relative band can never be called
# `delivered`/`regressed` on variance alone — the floor under the
# window-noise guard (two quiet windows still jitter a percent or two
# on a shared box)
NOISE_FLOOR = 0.02


def _now_us() -> float:
    return time.perf_counter() * _US


class _Window:
    """Summary of one measurement window of step durations."""

    __slots__ = ("mean_s", "rel_sd", "n")

    def __init__(self, samples: List[float]):
        self.n = len(samples)
        self.mean_s = sum(samples) / self.n if self.n else 0.0
        if self.n >= 2 and self.mean_s > 0:
            var = sum((s - self.mean_s) ** 2 for s in samples) / (self.n - 1)
            self.rel_sd = math.sqrt(var) / self.mean_s
        else:
            self.rel_sd = 0.0

    def to_json(self) -> dict:
        return {
            "mean_ms": round(self.mean_s * 1e3, 3),
            "rel_sd": round(self.rel_sd, 4),
            "n": self.n,
        }


class DecisionRecord:
    """One adaptation, from flip to measured outcome."""

    __slots__ = (
        "seq", "kind", "peer", "epoch", "trigger", "signals",
        "predicted_gain", "detail", "wall_time", "t_us",
        "status", "baseline", "after", "realized_gain", "verdict",
        "regressed", "closed_wall_time", "t_closed_us",
        # delta-scrape cursor (ISSUE 18): bumped on every visible
        # mutation (open, close, watchdog updates) so `?since=` ships a
        # record again whenever its merged copy needs updating
        "useq",
        # measurement state (never serialized)
        "_settle_left", "_samples", "_watch_below",
    )

    def __init__(self, seq: int, kind: str, *, peer: str, epoch: int,
                 trigger: str, signals: Optional[dict],
                 predicted_gain: Optional[float], detail: Optional[dict],
                 baseline: Optional[_Window], settle: int):
        self.seq = seq
        self.kind = kind
        self.peer = str(peer)
        self.epoch = int(epoch)
        self.trigger = trigger
        self.signals = dict(signals or {})
        self.predicted_gain = (
            float(predicted_gain) if predicted_gain is not None else None
        )
        self.detail = dict(detail or {})
        self.wall_time = time.time()
        self.t_us = _now_us()
        self.status = "open"
        self.baseline = baseline
        self.after: Optional[_Window] = None
        self.realized_gain: Optional[float] = None
        self.verdict: Optional[str] = None
        self.regressed = False
        self.closed_wall_time: Optional[float] = None
        self.t_closed_us: Optional[float] = None
        self.useq = 0
        self._settle_left = settle
        self._samples: List[float] = []
        self._watch_below = 0

    def to_json(self) -> dict:
        d = {
            "seq": self.seq,
            "kind": self.kind,
            "peer": self.peer,
            "epoch": self.epoch,
            "trigger": self.trigger,
            "wall_time": self.wall_time,
            "t_us": self.t_us,
            "status": self.status,
            "predicted_gain": self.predicted_gain,
            "useq": self.useq,
        }
        # copies, not references: the watchdog mutates detail (and the
        # measurement fields) under the ledger lock while HTTP scrapes /
        # flight snapshots serialize earlier to_json output — a shared
        # dict would grow mid-json.dumps (the steptrace lane-copy
        # lesson). Serialization itself runs under the ledger lock
        # (export/tail), so these copies are taken race-free.
        if self.signals:
            d["signals"] = dict(self.signals)
        if self.detail:
            d["detail"] = dict(self.detail)
        if self.baseline is not None:
            d["baseline"] = self.baseline.to_json()
        if self.after is not None:
            d["after"] = self.after.to_json()
        if self.realized_gain is not None:
            d["realized_gain"] = round(self.realized_gain, 4)
        if self.verdict is not None:
            d["verdict"] = self.verdict
        if self.regressed:
            d["regressed"] = True
        if self.closed_wall_time is not None:
            d["closed_wall_time"] = self.closed_wall_time
            d["t_closed_us"] = self.t_closed_us
        return d


class DecisionLedger:
    """Per-worker bounded ring of decision records plus the rolling
    step-duration window that measures them. Thread-safe: the training
    loop feeds :meth:`note_step`, decision sites call :meth:`open`,
    HTTP scrapes and flight snapshots read."""

    def __init__(self, keep: Optional[int] = None,
                 window: Optional[int] = None,
                 settle: Optional[int] = None,
                 regress_ratio: Optional[float] = None,
                 patience: Optional[int] = None):
        self.keep = keep if keep is not None else max(
            0, int(knobs.get("KF_DECISION_KEEP"))
        )
        self.window = max(2, int(
            window if window is not None else knobs.get("KF_DECISION_WINDOW")
        ))
        self.settle = max(0, int(
            settle if settle is not None else knobs.get("KF_DECISION_SETTLE")
        ))
        self.regress_ratio = float(
            regress_ratio if regress_ratio is not None
            else knobs.get("KF_DECISION_REGRESS_RATIO")
        )
        self.patience = max(1, int(
            patience if patience is not None
            else knobs.get("KF_DECISION_PATIENCE")
        ))
        self._ring: "deque[DecisionRecord]" = deque(maxlen=max(1, self.keep))
        self._recent: "deque[float]" = deque(maxlen=self.window)
        self._open: List[DecisionRecord] = []
        self._seq = 0
        # delta-scrape cursor space (ISSUE 18): a record's useq is
        # re-stamped on every visible mutation, so `export(since=N)`
        # ships exactly the records whose merged copies are out of date
        self._useq = 0
        self._lock = threading.Lock()
        self._g_gain = self._c_total = None
        if tconfig.metrics_enabled():
            from kungfu_tpu.telemetry import metrics as tm

            self._g_gain = tm.gauge(
                "kungfu_decision_realized_gain",
                "Measured outcome of the most recently closed adaptation "
                "of each kind: baseline mean step time / post-settle mean "
                "step time (>1 = the adaptation made steps faster)",
                ("kind",),
            )
            self._c_total = tm.counter(
                "kungfu_decisions_total",
                "Adaptation decisions closed with a measured outcome, by "
                "decision kind and verdict (delivered/neutral/regressed)",
                ("kind", "verdict"),
            )
        # memory plane (ISSUE 17): rings report their cap so filling up
        # never reads as a leak; only _open (unbounded until closed) can
        # legitimately streak. Weakref — tests build throwaway ledgers.
        try:
            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=_weakref.ref(self)):
                led = ref()
                return led.footprint_bytes() if led is not None else None

            _tmem.register_accountant("decisions", "telemetry", _acct)
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the ledger
        except Exception:  # noqa: BLE001
            pass

    def footprint_bytes(self) -> int:
        """Capacity estimate of the ledger's state in bytes (memory
        plane `telemetry` bucket): ring caps plus live open records."""
        from kungfu_tpu.telemetry import memory as _tmem

        with self._lock:
            ring = deque(self._ring, maxlen=self._ring.maxlen)
            recent = deque(self._recent, maxlen=self._recent.maxlen)
            open_ = list(self._open)
        return (
            _tmem.ring_cap_bytes(ring)
            + _tmem.ring_cap_bytes(recent)
            + _tmem.deep_sizeof(open_)
        )

    # -- decision sites -------------------------------------------------

    def open(self, kind: str, *, peer: str = "", epoch: int = 0,
             trigger: str = "", signals: Optional[dict] = None,
             predicted_gain: Optional[float] = None,
             **detail) -> Optional[DecisionRecord]:
        """Record one adaptation the moment it lands. The baseline is
        whatever step history the rolling window holds RIGHT NOW (the
        steps walked under the old configuration); with fewer than 2
        fed steps the record has no baseline and stays open forever —
        an honest 'never measured', never a fabricated gain."""
        if self.keep <= 0:
            return None
        with self._lock:
            base = (
                _Window(list(self._recent)) if len(self._recent) >= 2
                else None
            )
            rec = DecisionRecord(
                self._seq, kind, peer=peer, epoch=epoch, trigger=trigger,
                signals=signals, predicted_gain=predicted_gain,
                detail=detail or None, baseline=base, settle=self.settle,
            )
            self._seq += 1
            self._useq += 1
            rec.useq = self._useq
            self._ring.append(rec)
            if base is not None:
                self._open.append(rec)
        return rec

    # -- measurement feed ----------------------------------------------

    def note_step(self, seconds: float) -> None:
        """One training step's wall-clock duration (the PolicyRunner
        feeds this; benches and tests may too). Advances every open
        record's settle/measurement window; closing and the watchdog
        run inline — the work is a handful of floats per step."""
        if self.keep <= 0 or not (seconds > 0):
            return
        closed: List[DecisionRecord] = []
        fired: List[DecisionRecord] = []
        with self._lock:
            self._recent.append(float(seconds))
            still_open: List[DecisionRecord] = []
            for rec in self._open:
                if rec._settle_left > 0:
                    rec._settle_left -= 1
                    still_open.append(rec)
                    continue
                rec._samples.append(float(seconds))
                if len(rec._samples) < self.window:
                    still_open.append(rec)
                    continue
                win = _Window(rec._samples)
                rec._samples = []
                # every branch below mutates the record (close, watchdog
                # gain update, regress, recovery note) — re-stamp its
                # delta cursor so `?since=` re-ships the merged update
                self._useq += 1
                rec.useq = self._useq
                if rec.status == "open":
                    self._close_locked(rec, win)
                    closed.append(rec)
                    if rec.verdict == "regressed":
                        rec._watch_below = 1
                        if rec._watch_below >= self.patience:
                            rec.regressed = True
                            fired.append(rec)
                        else:
                            still_open.append(rec)
                    continue
                # watchdog: a regressed close keeps measuring until the
                # gain recovers past the floor or patience runs out
                gain = (
                    rec.baseline.mean_s / win.mean_s
                    if win.mean_s > 0 else None
                )
                rec.after = win
                if gain is not None:
                    rec.realized_gain = gain
                if gain is not None and gain <= self.regress_ratio:
                    rec._watch_below += 1
                    if rec._watch_below >= self.patience:
                        rec.regressed = True
                        fired.append(rec)
                    else:
                        still_open.append(rec)
                else:
                    rec.detail["recovered_after_windows"] = rec._watch_below
            self._open = still_open
        # emit outside the lock: audit/metrics take locks of their own
        for rec in closed:
            self._emit_outcome(rec)
        for rec in fired:
            self._emit_regressed(rec)

    def _close_locked(self, rec: DecisionRecord, win: _Window) -> None:
        rec.after = win
        rec.status = "closed"
        rec.closed_wall_time = time.time()
        rec.t_closed_us = _now_us()
        if win.mean_s <= 0 or rec.baseline is None:
            return
        gain = rec.baseline.mean_s / win.mean_s
        rec.realized_gain = gain
        # noise guard: the windows' own relative variance bounds what a
        # mean shift can prove — two std errors of the noisier window,
        # at the SMALLER window's actual sample count (a baseline
        # captured after only 3 fed steps must widen the band, not
        # borrow the configured window's sqrt), floored so quiet
        # windows still don't call percent-level drift
        n_eff = max(2, min(rec.baseline.n, win.n))
        band = max(
            NOISE_FLOOR,
            2.0 * max(rec.baseline.rel_sd, win.rel_sd) / math.sqrt(n_eff),
        )
        if gain >= 1.0 + band:
            rec.verdict = "delivered"
        elif gain <= min(self.regress_ratio, 1.0 - band):
            rec.verdict = "regressed"
        else:
            rec.verdict = "neutral"

    def _emit_outcome(self, rec: DecisionRecord) -> None:
        from kungfu_tpu.telemetry import audit

        audit.record_event(
            "decision_outcome",
            peer=rec.peer,
            trigger=rec.trigger,
            decision=rec.kind,
            epoch=rec.epoch,
            predicted_gain=rec.predicted_gain,
            realized_gain=(
                round(rec.realized_gain, 4)
                if rec.realized_gain is not None else None
            ),
            verdict=rec.verdict,
            baseline_ms=(
                round(rec.baseline.mean_s * 1e3, 3)
                if rec.baseline is not None else None
            ),
            after_ms=(
                round(rec.after.mean_s * 1e3, 3)
                if rec.after is not None else None
            ),
            window=self.window,
        )
        if self._g_gain is not None and rec.realized_gain is not None:
            self._g_gain.labels(rec.kind).set(rec.realized_gain)
        if self._c_total is not None and rec.verdict is not None:
            self._c_total.labels(rec.kind, rec.verdict).inc()

    def _emit_regressed(self, rec: DecisionRecord) -> None:
        from kungfu_tpu.telemetry import audit, log

        log.warn(
            "decision ledger: adaptation REGRESSED: %s (trigger %s) "
            "realized %.2fx, floor %.2f — consider rolling back",
            rec.kind, rec.trigger, rec.realized_gain or 0.0,
            self.regress_ratio,
        )
        audit.record_event(
            "adaptation_regressed",
            peer=rec.peer,
            trigger=rec.trigger,
            decision=rec.kind,
            epoch=rec.epoch,
            realized_gain=(
                round(rec.realized_gain, 4)
                if rec.realized_gain is not None else None
            ),
            floor=self.regress_ratio,
            windows=rec._watch_below,
        )

    # -- views ----------------------------------------------------------

    def records(self) -> List[DecisionRecord]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int = 8) -> List[dict]:
        # to_json UNDER the ledger lock: note_step mutates the records'
        # measurement fields under it, so snapshots taken here are
        # consistent and the returned dicts are never mutated again
        with self._lock:
            recs = [r.to_json() for r in list(self._ring)[-max(0, n):]]
        return recs

    def export(self, peer: str = "", since: Optional[int] = None) -> dict:
        """The /decisions document: the ring plus the clock anchors the
        aggregator aligns on (the /steptrace contract). ``since`` is
        the delta-scrape cursor (ISSUE 18): only records whose useq
        moved past it ship — new records AND records that mutated
        (closed, regressed) since the last scrape, so the aggregator's
        update-in-place merge stays correct on deltas."""
        with self._lock:
            recs = [
                r.to_json() for r in self._ring
                if since is None or r.useq > since
            ]
            next_since = self._useq
        return {
            "peer": peer or knobs.raw("KF_SELF_SPEC"),
            "perf_now_us": _now_us(),
            "wall_time_s": time.time(),
            "keep": self.keep,
            "window": self.window,
            "settle": self.settle,
            "regress_ratio": self.regress_ratio,
            "next_since": next_since,
            "decisions": recs,
        }

    def signals(self) -> Dict[str, object]:
        """Adaptation-facing policy signals (PolicyContext.metrics):
        the latest closed decision's kind and realized gain, plus the
        kinds the watchdog currently flags as regressed."""
        with self._lock:
            recs = list(self._ring)
        out: Dict[str, object] = {}
        closed = [r for r in recs if r.status == "closed"]
        if closed:
            last = closed[-1]
            out["decision/last_kind"] = last.kind
            if last.realized_gain is not None:
                out["decision/last_realized_gain"] = last.realized_gain
        regressed = sorted({r.kind for r in recs if r.regressed})
        if regressed:
            out["decision/regressed"] = regressed
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recent.clear()
            self._open = []


_ledger: Optional[DecisionLedger] = None
_ledger_lock = threading.Lock()


def get_ledger() -> DecisionLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = DecisionLedger()
        return _ledger


def reset_ledger() -> None:
    """Drop the process ledger (tests flip knobs at runtime)."""
    global _ledger
    with _ledger_lock:
        _ledger = None


def open_decision(kind: str, **kw) -> Optional[DecisionRecord]:
    """Fire-and-forget decision-site entry point: never raises (a
    broken ledger must not break the adaptation it observes)."""
    try:
        return get_ledger().open(kind, **kw)
    except Exception as e:  # noqa: BLE001 - telemetry must never kill adaptation
        from kungfu_tpu.telemetry import log

        log.debug("decision ledger: open(%s) failed: %s", kind, e)
        return None


def note_step(seconds: float) -> None:
    """Fire-and-forget step feed (the PolicyRunner's hook)."""
    try:
        get_ledger().note_step(seconds)
    except Exception as e:  # noqa: BLE001 - telemetry must never kill training
        from kungfu_tpu.telemetry import log

        log.debug("decision ledger: note_step failed: %s", e)


# ---------------------------------------------------------------------------
# merge math (pure: the aggregator and tests drive it)
# ---------------------------------------------------------------------------


def merge_decisions(peer_docs: Dict[str, dict],
                    offsets_us: Dict[str, float]) -> List[dict]:
    """Merge every peer's /decisions document into one timeline, oldest
    first: each record keyed by its reporting peer, perf stamps shifted
    by that peer's NTP-style clock offset onto the merger's timeline
    (the /cluster/steps discipline — wall clocks across VMs drift, the
    aligned perf stamps order causally)."""
    out: List[dict] = []
    for peer, doc in peer_docs.items():
        off = offsets_us.get(peer) or 0.0
        for rec in (doc or {}).get("decisions", []):
            rec = dict(rec)
            rec.setdefault("peer", peer)
            for key in ("t_us", "t_closed_us"):
                if isinstance(rec.get(key), (int, float)):
                    rec[key] = rec[key] + off
            out.append(rec)
    out.sort(key=lambda r: (
        r.get("t_us") if isinstance(r.get("t_us"), (int, float))
        else r.get("wall_time", 0.0),
        r.get("peer", ""), r.get("seq", 0),
    ))
    return out


# ---------------------------------------------------------------------------
# rendering (info decisions + the flight postmortem's final adaptations)
# ---------------------------------------------------------------------------


def _fmt_gain(v: Optional[float]) -> str:
    return f"{v:.2f}x" if isinstance(v, (int, float)) else "—"


def render_record(rec: dict) -> str:
    """One ledger entry as a timeline line: decision → trigger →
    predicted vs realized, the regressed flag loud."""
    when = rec.get("wall_time")
    ts = (
        time.strftime("%H:%M:%S", time.localtime(when))
        if isinstance(when, (int, float)) else "?"
    )
    head = (
        f"{ts}  {rec.get('peer') or '?'}  e{rec.get('epoch', 0)}  "
        f"{rec.get('kind', '?')}"
    )
    trigger = rec.get("trigger")
    if trigger:
        head += f"  [{trigger}]"
    head += (
        f"  predicted {_fmt_gain(rec.get('predicted_gain'))}"
        f" → realized {_fmt_gain(rec.get('realized_gain'))}"
    )
    if rec.get("status") != "closed":
        head += (
            "  OPEN (outcome pending)" if rec.get("baseline")
            else "  OPEN (no step feed — never measured)"
        )
    else:
        head += f"  {str(rec.get('verdict', '?')).upper()}"
    if rec.get("regressed"):
        head += "  ⚠ REGRESSED"
    return head


def render_decisions(doc: dict, limit: int = 16) -> str:
    """One frame of `info decisions`: the merged causal timeline,
    newest last, regressed entries flagged."""
    recs = doc.get("decisions") or []
    if not recs:
        return (
            "no adaptation decisions on record — the cluster has not "
            "adapted (strategy/wire vote, re-plan, mode flip, resize), "
            "or the ledger is off (KF_DECISION_KEEP=0)"
        )
    shown = recs[-limit:]
    n_open = sum(1 for r in recs if r.get("status") != "closed")
    n_reg = sum(1 for r in recs if r.get("regressed"))
    head = (
        f"{len(recs)} adaptation decision(s) on record, showing "
        f"{len(shown)} (open: {n_open}"
        + (f", REGRESSED: {n_reg}" if n_reg else "")
        + ") — realized gain = baseline mean step time / post-settle "
        "mean step time"
    )
    lines = [head]
    for rec in shown:
        lines.append(render_record(rec))
        det = rec.get("detail") or {}
        if det:
            pairs = ", ".join(f"{k}={v}" for k, v in sorted(det.items()))
            lines.append(f"          {pairs[:110]}")
    return "\n".join(lines)
