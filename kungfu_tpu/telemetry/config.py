"""Telemetry feature gating + shared env parsing.

One place answers "is telemetry on?" for the whole host plane:

- ``KF_TELEMETRY`` selects features by name (``metrics``, ``trace``,
  ``audit``; ``all``/any truthy value enables everything).
- ``truthy()`` is the single truthy-string parser — the reference
  accepted only ``"1"``/``"true"`` for KF_CONFIG_ENABLE_MONITORING and
  silently dropped ``"yes"``/``"on"`` variants; every boolean env knob
  now goes through here.

Feature lookups are cached (they sit near hot paths); tests that flip
the environment at runtime must call :func:`refresh`.
"""

from __future__ import annotations

from typing import FrozenSet, Optional

from kungfu_tpu import knobs

TELEMETRY_ENV = "KF_TELEMETRY"
KNOWN_FEATURES = frozenset({"metrics", "trace", "audit"})

_TRUTHY = frozenset({"1", "true", "yes", "on", "y", "enabled"})
_FALSY = frozenset({"", "0", "false", "no", "off", "n", "disabled", "none"})


def truthy(value) -> bool:
    """Normalize a boolean-ish env value ("1"/"true"/"yes"/"on"/...)."""
    return str(value).strip().lower() in _TRUTHY


def env_truthy(name: str, default: str = "") -> bool:
    """Truthiness of a DECLARED boolean knob (see kungfu_tpu/knobs.py;
    undeclared names are an error — declare before use)."""
    try:
        raw = knobs.raw(name)
    except KeyError:
        raise KeyError(
            f"{name} is not a declared knob — declare it in "
            "kungfu_tpu/knobs.py (name, default, parser, doc) before "
            "reading it; kfcheck rule KF100 enforces this for KF_* names"
        ) from None
    return truthy(raw or default)


_cache: dict = {"features": None, "forced": None}


def _parse_features(raw: str) -> FrozenSet[str]:
    raw = raw.strip().lower()
    if not raw or raw in _FALSY:
        return frozenset()
    if raw in ("all", "*") or raw in _TRUTHY:
        return KNOWN_FEATURES
    out = set()
    unknown = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if part in ("all", "*"):
            return KNOWN_FEATURES
        if part in KNOWN_FEATURES:
            out.add(part)
        else:
            unknown.append(part)
    if unknown:
        # a typo'd feature must not silently disable telemetry
        from kungfu_tpu.telemetry import log

        log.warn(
            "%s: unknown feature(s) %s (known: %s)",
            TELEMETRY_ENV, ",".join(unknown), ",".join(sorted(KNOWN_FEATURES)),
        )
    return frozenset(out)


def features() -> FrozenSet[str]:
    """Enabled telemetry features (cached; see refresh())."""
    if _cache["forced"] is not None:
        return _cache["forced"]
    if _cache["features"] is None:
        _cache["features"] = _parse_features(knobs.raw(TELEMETRY_ENV))
    return _cache["features"]


def enabled(feature: str) -> bool:
    return feature in features()


def metrics_enabled() -> bool:
    """Metrics are on under KF_TELEMETRY=metrics OR the reference's
    KF_CONFIG_ENABLE_MONITORING knob (capability parity both ways)."""
    return "metrics" in features() or env_truthy("KF_CONFIG_ENABLE_MONITORING")


def trace_enabled() -> bool:
    return "trace" in features()


# Per-step walk spans (host.rs.step / host.ag.step) are O(k * buckets)
# per training iteration — at k=64 with a bucketed bert set that is
# thousands of ring-buffer appends a step, evicting everything else from
# the trace window on long runs. KF_TELEMETRY_SPAN_SAMPLE keeps one walk
# in 1/rate fully annotated (deterministic, not random — resumable and
# identical across reruns); the default 1.0 keeps current behavior.
SPAN_SAMPLE_ENV = "KF_TELEMETRY_SPAN_SAMPLE"


def span_sample() -> float:
    """Fraction of walks whose per-step spans are emitted, in [0, 1].
    Read per session epoch (not import time); the registry's lenient
    parse warns and falls back to 1.0 on malformed values — a typo must
    not silently blind the trace."""
    return min(max(knobs.get(SPAN_SAMPLE_ENV), 0.0), 1.0)


def enable(*names: str) -> None:
    """Force features on programmatically (tests / embedding)."""
    cur = _cache["forced"] or features()
    _cache["forced"] = frozenset(cur) | frozenset(
        n for n in names if n in KNOWN_FEATURES
    )


def refresh(forced: Optional[FrozenSet[str]] = None) -> None:
    """Drop caches and re-read the environment (tests flip env at runtime)."""
    _cache["features"] = None
    _cache["forced"] = forced
