"""kungfu_tpu.telemetry — unified observability for the host plane.

One subsystem, three surfaces (ISSUE 1 tentpole):

- :mod:`~kungfu_tpu.telemetry.metrics` — process-wide registry of
  counters/gauges/histograms with labels, Prometheus text exposition;
- :mod:`~kungfu_tpu.telemetry.tracing` — span tracing (ring buffer,
  nesting, Chrome-trace/Perfetto JSON export);
- :mod:`~kungfu_tpu.telemetry.audit` — structured resize/strategy audit
  log for every elastic membership change.

Plus :mod:`~kungfu_tpu.telemetry.log` (structured rank-prefixed logger,
the repo-wide replacement for bare ``print()``) and
:mod:`~kungfu_tpu.telemetry.http` (the per-worker ``/metrics`` +
``/trace`` + ``/audit`` endpoint).

The cluster plane (ISSUE 2) builds on those per-worker endpoints:
:mod:`~kungfu_tpu.telemetry.cluster` is the runner-side aggregator
(scrape, merge, ``/cluster/*`` views), with
:mod:`~kungfu_tpu.telemetry.promparse` (exposition parsing/federation)
and :mod:`~kungfu_tpu.telemetry.straggler` (robust skew detection)
underneath — all lazily imported, since every worker imports this
package on the transport path.

Feature selection: ``KF_TELEMETRY=metrics,trace`` (see
:mod:`~kungfu_tpu.telemetry.config`). ``dump()`` snapshots everything
for ad-hoc inspection; see docs/telemetry.md for naming conventions.
"""

from __future__ import annotations

from kungfu_tpu.telemetry import audit, config, log, metrics, tracing
from kungfu_tpu.telemetry.config import (
    enable,
    enabled,
    env_truthy,
    features,
    metrics_enabled,
    refresh,
    trace_enabled,
    truthy,
)
from kungfu_tpu.telemetry.metrics import get_registry

__all__ = [
    "audit",
    "config",
    "log",
    "metrics",
    "tracing",
    "enable",
    "enabled",
    "env_truthy",
    "features",
    "metrics_enabled",
    "refresh",
    "trace_enabled",
    "truthy",
    "get_registry",
    "dump",
    "serve",
    "cluster",
    "promparse",
    "straggler",
    "flight",
    "steptrace",
    "decisions",
]

_LAZY_MODULES = (
    "cluster", "promparse", "straggler", "flight", "steptrace", "decisions",
)


def __getattr__(name):
    # the cluster plane is runner-side machinery; workers importing
    # telemetry on the transport hot path must not pay for it
    if name in _LAZY_MODULES:
        import importlib

        return importlib.import_module(f"kungfu_tpu.telemetry.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def dump(prefix: str = "") -> dict:
    """Snapshot every telemetry surface of this process:

    ``metrics``  Prometheus text exposition,
    ``trace``    Chrome-trace JSON object (``traceEvents`` with
                 ``ph``/``ts``/``dur``),
    ``audit``    resize/strategy audit records as dicts,
    ``spans``    total-ms-per-span summary (quick look).
    """
    metrics.update_process_health()
    return {
        "features": sorted(features()),
        "metrics": metrics.render(),
        "trace": tracing.chrome_trace(prefix),
        "audit": audit.to_json(),
        "spans": tracing.summary_ms(prefix),
    }


def serve(port: int = 0, host: str = "0.0.0.0"):
    """Start a standalone telemetry endpoint (started+returned); workers
    under a Peer get one automatically on peer_port+10000."""
    from kungfu_tpu.telemetry.http import TelemetryServer

    srv = TelemetryServer(port, host=host)
    srv.start()
    return srv
