"""Per-link network observability: passive {src,dst} transport accounting.

ISSUE 6 tentpole, part (a). The transport's existing counters aggregate
over all peers (``kungfu_egress_bytes_total`` is per-peer *totals*, the
send histogram is peer-blind), so "the allreduce is slow" could never
become "the 2→3 edge is the bottleneck". This module gives every worker
a **link table**: one estimator per destination peer, fed passively by
the real collective traffic as it crosses :meth:`Client.send` — no
probe rounds, no extra messages (arXiv:1810.11112 shows per-link
attribution is what localizes collective slowdowns; arXiv:1909.09756
motivates measuring continuously rather than one-shot).

Per destination it keeps:

- monotonic ``tx_bytes`` / ``tx_messages`` counters,
- an **EWMA bandwidth** estimate from large sends (payload ≥
  ``KF_LINK_BW_MIN_BYTES``, default 64 KiB — small frames measure
  per-message overhead, not the pipe),
- an **EWMA latency** estimate from ping round trips.

The worker's row exports through the ordinary metrics registry
(``kungfu_link_*`` families, ``dst``-labelled, cardinality-bounded both
by the registry guard and by ``KF_LINK_MAX_PEERS``), so the cluster
aggregator assembles the k×k matrix from the pages it already scrapes —
:func:`merge_matrix` — and serves it at ``/cluster/links``.

Estimation notes: EWMA (``KF_LINK_EWMA_ALPHA``, default 0.2) tracks a
drifting link within ~5-10 observations while riding out single-send
jitter; bandwidth samples are payload/send-time where send time covers
frame + flush into the kernel buffer (or the shm-ring memcpy for
colocated peers) — it measures the link *as the engine experiences it*,
which is exactly the signal topology re-planning needs. Sends that had
to (re)dial the peer are counted for bytes but skipped as bandwidth
samples (connection setup is not link speed).
"""

from __future__ import annotations

import os
import threading
import weakref as _weakref
from typing import Dict, List, Optional, Sequence, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import metrics as tmetrics

# minimum payload for a bandwidth sample: below this the send time is
# dominated by per-message fixed cost (framing, syscall, rendezvous).
# Read at table construction like the other knobs, not at import — the
# api imports this module transitively, so an import-time read would
# freeze the default for embedders that set the env programmatically.
def _bw_min_bytes() -> int:
    return int(knobs.get("KF_LINK_BW_MIN_BYTES"))

# EWMA smoothing factor for bandwidth/latency estimates
def _alpha() -> float:
    return min(max(float(knobs.get("KF_LINK_EWMA_ALPHA")), 0.01), 1.0)


# destination cap for the table itself (the registry's cardinality guard
# backstops the exported families independently)
def _max_peers() -> int:
    return max(1, int(knobs.get("KF_LINK_MAX_PEERS")))


def enabled() -> bool:
    """The link plane rides the metrics gate (same as the net monitor):
    its feed sits on the per-message send path."""
    return tconfig.metrics_enabled()


class LinkEstimator:
    """Passive estimator for one directed edge (this peer → dst)."""

    __slots__ = (
        "tx_bytes", "tx_messages", "bw", "bw_samples", "latency",
        "latency_samples",
    )

    def __init__(self):
        self.tx_bytes = 0
        self.tx_messages = 0
        self.bw: Optional[float] = None  # bytes/sec, EWMA
        self.bw_samples = 0
        self.latency: Optional[float] = None  # seconds, EWMA
        self.latency_samples = 0

    def observe_send(
        self, nbytes: int, seconds: float, alpha: float, min_bytes: int
    ) -> None:
        self.tx_bytes += nbytes
        self.tx_messages += 1
        if seconds > 0.0 and nbytes >= min_bytes:
            sample = nbytes / seconds
            self.bw = (
                sample if self.bw is None
                else alpha * sample + (1.0 - alpha) * self.bw
            )
            self.bw_samples += 1

    def observe_latency(self, seconds: float, alpha: float) -> None:
        if seconds <= 0.0:
            return
        self.latency = (
            seconds if self.latency is None
            else alpha * seconds + (1.0 - alpha) * self.latency
        )
        self.latency_samples += 1


class LinkTable:
    """This worker's row of the cluster link matrix: one
    :class:`LinkEstimator` per destination, mirrored into ``dst``-labelled
    registry families so the row travels on the existing /metrics page."""

    def __init__(
        self,
        registry: Optional[tmetrics.Registry] = None,
        alpha: Optional[float] = None,
        max_peers: Optional[int] = None,
        bw_min_bytes: Optional[int] = None,
    ):
        self._lock = threading.Lock()
        self._links: Dict[str, LinkEstimator] = {}
        self._alpha = alpha if alpha is not None else _alpha()
        self._max_peers = max_peers if max_peers is not None else _max_peers()
        self._bw_min = (
            bw_min_bytes if bw_min_bytes is not None else _bw_min_bytes()
        )
        self._registry = registry
        self._reg_children: Dict[str, tuple] = {}
        if registry is not None:
            self._fam_bytes = registry.counter(
                "kungfu_link_tx_bytes_total",
                "Bytes sent over each outgoing link (this peer → dst)",
                ("dst",),
            )
            self._fam_msgs = registry.counter(
                "kungfu_link_tx_messages_total",
                "Messages sent over each outgoing link (this peer → dst)",
                ("dst",),
            )
            self._fam_bw = registry.gauge(
                "kungfu_link_bandwidth_bytes_per_second",
                "EWMA link bandwidth from passive large-send timing",
                ("dst",),
            )
            self._fam_lat = registry.gauge(
                "kungfu_link_latency_seconds",
                "EWMA link latency from ping round trips",
                ("dst",),
            )
        # memory plane (ISSUE 17): the table is bounded by max_peers, so
        # its report plateaus once the cluster is fully discovered —
        # growth past that is a real leak. Weakref: tests build many
        # throwaway tables; dead entries drop from the registry.
        try:
            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=_weakref.ref(self)):
                tbl = ref()
                return tbl.footprint_bytes() if tbl is not None else None

            _tmem.register_accountant("link_table", "telemetry", _acct)
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the link table
        except Exception:  # noqa: BLE001
            pass

    def footprint_bytes(self) -> int:
        """Deep size of the per-destination estimator map (memory plane
        `telemetry` bucket; bounded by KF_LINK_MAX_PEERS)."""
        from kungfu_tpu.telemetry import memory as _tmem

        with self._lock:
            snap = dict(self._links)
        return _tmem.deep_sizeof(snap)

    def _est(self, dst: str) -> Optional[LinkEstimator]:
        """Get-or-create under the table lock; None past the peer cap
        (the drop is visible in the registry's dropped-series counter,
        attributed to the tx-bytes family)."""
        est = self._links.get(dst)
        if est is not None:
            return est
        if len(self._links) >= self._max_peers:
            if self._registry is not None:
                # route the drop through the same visible counter the
                # registry guard uses
                self._fam_bytes._count_drop()
            return None
        est = self._links[dst] = LinkEstimator()
        return est

    def _children(self, dst: str) -> Optional[tuple]:
        if self._registry is None:
            return None
        kids = self._reg_children.get(dst)
        if kids is None:
            kids = (
                self._fam_bytes.labels(dst),
                self._fam_msgs.labels(dst),
                self._fam_bw.labels(dst),
                self._fam_lat.labels(dst),
            )
            self._reg_children[dst] = kids
        return kids

    def observe_send(self, dst, nbytes: int, seconds: float) -> None:
        """One completed transport send to `dst` taking `seconds`
        (pass seconds<=0 to count bytes without a bandwidth sample,
        e.g. when the send included a connection dial)."""
        key = str(dst)
        with self._lock:
            est = self._est(key)
            if est is None:
                return
            est.observe_send(nbytes, seconds, self._alpha, self._bw_min)
            kids = self._children(key)
            if kids is not None:
                c_bytes, c_msgs, g_bw, _ = kids
                c_bytes.inc(nbytes)
                c_msgs.inc()
                if est.bw is not None:
                    g_bw.set(est.bw)

    def observe_latency(self, dst, seconds: float) -> None:
        key = str(dst)
        with self._lock:
            est = self._est(key)
            if est is None:
                return
            est.observe_latency(seconds, self._alpha)
            kids = self._children(key)
            if kids is not None and est.latency is not None:
                kids[3].set(est.latency)

    def bandwidth(self, dst) -> Optional[float]:
        with self._lock:
            est = self._links.get(str(dst))
            return est.bw if est is not None else None

    def latency(self, dst) -> Optional[float]:
        with self._lock:
            est = self._links.get(str(dst))
            return est.latency if est is not None else None

    def min_bandwidth(
        self, dsts: Optional[Sequence] = None
    ) -> Tuple[Optional[str], Optional[float]]:
        """(dst, bw) of the slowest estimated outgoing link, optionally
        restricted to `dsts`; (None, None) when nothing is estimated."""
        keys = None if dsts is None else {str(d) for d in dsts}
        worst: Tuple[Optional[str], Optional[float]] = (None, None)
        with self._lock:
            for dst, est in self._links.items():
                if est.bw is None or (keys is not None and dst not in keys):
                    continue
                if worst[1] is None or est.bw < worst[1]:
                    worst = (dst, est.bw)
        return worst

    def row(self) -> Dict[str, dict]:
        """This peer's link-matrix row (the JSON shape merge_matrix and
        /cluster/links use per edge)."""
        with self._lock:
            return {
                dst: {
                    "bw": est.bw,
                    "latency_s": est.latency,
                    "tx_bytes": est.tx_bytes,
                    "tx_messages": est.tx_messages,
                    "bw_samples": est.bw_samples,
                }
                for dst, est in self._links.items()
            }

    def signals(self) -> Dict[str, object]:
        """Worker-local adaptation signals (namespaced like the cluster
        plane's; the cluster-wide values override these when a runner
        aggregator is live). ``links/slowest_edge`` is always the
        ``[src, dst]`` shape the cluster plane uses — src is None here
        because the local view only knows its own outgoing row — so
        policies can unpack it regardless of which plane supplied it."""
        dst, bw = self.min_bandwidth()
        if bw is None:
            return {}
        return {"links/min_bw": bw, "links/slowest_edge": [None, dst]}

    def prune(self, keep: Sequence) -> None:
        """Drop estimators for destinations outside `keep` (called at
        every membership change): a departed peer's frozen EWMA must not
        keep winning :meth:`min_bandwidth` — and through it the
        ``links/*`` adaptation signals and walk-efficiency scoring — nor
        keep exporting stale gauges, after the peer is gone. The
        aggregator clears a dead peer's own ROW on scrape failure; this
        is the matching guard for every other peer's edge TOWARD it."""
        keep_keys = {str(d) for d in keep}
        with self._lock:
            for dst in [d for d in self._links if d not in keep_keys]:
                del self._links[dst]
                self._reg_children.pop(dst, None)
                if self._registry is not None:
                    self._fam_bytes.remove(dst)
                    self._fam_msgs.remove(dst)
                    self._fam_bw.remove(dst)
                    self._fam_lat.remove(dst)

    def clear(self) -> None:
        with self._lock:
            self._links.clear()
            self._reg_children.clear()


def merge_matrix(
    rows: Dict[str, Dict[str, dict]], copy_edges: bool = True
) -> dict:
    """Merge per-peer link rows into the cluster's k×k matrix document.

    `rows` maps a source peer label to its row (``{dst: {bw, latency_s,
    tx_bytes, ...}}``) — exactly what each worker's exposition carries.
    Tolerant by design: peers with no row yet (fresh joiner, scrape
    error) contribute no edges but still appear in ``peers`` when some
    other peer has an edge toward them; a degenerate single-peer cluster
    yields an edgeless 1×1 matrix with ``min_bw: null``.

    ``copy_edges=False`` references the caller's edge dicts instead of
    copying the full k² of them — for read-and-discard consumers (the
    /cluster/health summary) that only want the election this function
    is the single source of; anything that hands the document onward
    (e.g. /cluster/links serialization) keeps the default copy."""
    peers = set(rows)
    for row in rows.values():
        peers.update(row)
    edges: Dict[str, Dict[str, dict]] = {}
    min_bw: Optional[float] = None
    slowest: Optional[List[str]] = None
    for src in sorted(rows):
        row = rows[src]
        if not row:
            continue
        edges[src] = {
            dst: (dict(info) if copy_edges else info)
            for dst, info in sorted(row.items())
        }
        for dst, info in row.items():
            bw = info.get("bw")
            if isinstance(bw, (int, float)) and bw > 0:
                if min_bw is None or bw < min_bw:
                    min_bw = float(bw)
                    slowest = [src, dst]
    return {
        "peers": sorted(peers),
        "edges": edges,
        "min_bw": min_bw,
        "slowest_edge": slowest,
    }


_table: Optional[LinkTable] = None
_table_lock = threading.Lock()


def get_table() -> LinkTable:
    """The process-wide link table (registry-backed)."""
    global _table
    with _table_lock:
        if _table is None:
            _table = LinkTable(registry=tmetrics.get_registry())
        return _table
