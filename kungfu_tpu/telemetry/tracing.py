"""Span-based tracing with Chrome-trace/Perfetto JSON export.

The successor of the old ``kungfu_tpu.utils.trace`` scoped tracer (which
now re-exports this module): named spans carried in a bounded ring
buffer — recording is always-on because a span is two perf_counter
calls, a small tuple and a deque append — plus:

- nesting: each thread keeps a span stack, so events know their depth
  and parent (tested by the collective-step nesting test);
- attributes: ``span("allreduce", bytes=n)`` attaches args that survive
  into the Chrome trace's ``args`` field;
- export: :func:`chrome_trace` renders the buffer as a Chrome
  ``traceEvents`` JSON object (``ph``/``ts``/``dur`` complete events,
  ``i`` instants) loadable by chrome://tracing and ui.perfetto.dev.

Capability parity: the reference compiles TRACE_SCOPE into its hot paths
(srcs/cpp/include/kungfu/utils/trace.hpp); the ring-buffer + JSON export
follows the standard Chrome trace-event format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple

from kungfu_tpu import knobs

# malformed values warn and keep the default inside the registry, so a
# typo cannot kill worker startup
MAX_EVENTS = int(knobs.get("KF_TRACE_BUFFER"))


class TraceEvent(NamedTuple):
    name: str
    start: float  # perf_counter seconds
    duration: float  # seconds; 0.0 for instants
    tid: int
    depth: int  # nesting depth at record time (0 = top level)
    phase: str  # "X" complete | "i" instant
    args: Optional[dict]


_lock = threading.Lock()
_events: "deque[TraceEvent]" = deque(maxlen=MAX_EVENTS)
_tls = threading.local()
# every thread's live span stack, keyed by thread ident — the flight
# recorder snapshots these so a postmortem can say what each thread was
# INSIDE when the process died (a completed-span ring can't)
_all_stacks: Dict[int, list] = {}


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
        # registration is once per thread: prune dead threads' entries
        # here too, so processes that never call open_spans() (flight
        # recorder off) don't leak an entry per short-lived thread
        live = {t.ident for t in threading.enumerate()}
        me = threading.get_ident()
        with _lock:
            for tid in list(_all_stacks):
                if tid not in live:
                    del _all_stacks[tid]
            _all_stacks[me] = st
    return st


def open_spans() -> Dict[str, List[str]]:
    """Currently-open (entered, not yet exited) span stacks per live
    thread: ``{"MainThread(140003...)": ["policy.step", "allreduce"]}``.
    Dead threads' stacks are pruned as a side effect."""
    live = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    with _lock:
        for tid in list(_all_stacks):
            if tid not in live:
                del _all_stacks[tid]
                continue
            st = list(_all_stacks[tid])
            if st:
                out[f"{live[tid]}({tid})"] = st
    return out


def _append(ev: TraceEvent) -> None:
    with _lock:
        _events.append(ev)


# ---------------------------------------------------------------------------
# step context (ISSUE 13): spans recorded while a (session_epoch, round)
# scope is active carry it as a `step` arg, so a cross-peer trace merge
# can group every peer's sched.*/host.*/zero.* spans by training step.
# Per-thread — the scheduler's worker threads each enter the scope of
# the round they are executing, which may differ from the round the
# submitting thread is already producing.
# ---------------------------------------------------------------------------

_step_tls = threading.local()


class _StepScope:
    __slots__ = ("step", "prev")

    def __init__(self, epoch: int, round_: int):
        self.step = (int(epoch), int(round_))

    def __enter__(self):
        self.prev = getattr(_step_tls, "cur", None)
        _step_tls.cur = self.step
        return self

    def __exit__(self, *exc):
        _step_tls.cur = self.prev
        return False


def step_scope(epoch: int, round_: int) -> _StepScope:
    """Stamp every span/record/instant on this thread with
    ``step=[epoch, round]`` until exit: ``with step_scope(3, 17): ...``."""
    return _StepScope(epoch, round_)


def current_step() -> Optional[Tuple[int, int]]:
    """The thread's active (session_epoch, round), or None."""
    return getattr(_step_tls, "cur", None)


def _step_args(args: Optional[dict]) -> Optional[dict]:
    cur = getattr(_step_tls, "cur", None)
    if cur is None:
        return args
    d = dict(args) if args else {}
    d.setdefault("step", list(cur))
    return d


class _Span:
    """Class-based context manager (NOT @contextmanager: spans sit on
    every collective/transport call and generator CMs cost ~3x more to
    enter). Records a complete event on exit; nesting depth comes from a
    per-thread stack."""

    __slots__ = ("name", "args", "t0", "depth")

    def __init__(self, name: str, args: Optional[dict]):
        self.name = name
        self.args = args

    def __enter__(self):
        st = _stack()
        self.depth = len(st)
        st.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        _stack().pop()
        _append(
            TraceEvent(
                self.name, self.t0, dt, threading.get_ident(), self.depth,
                "X", _step_args(self.args),
            )
        )
        return False


def span(name: str, **args) -> _Span:
    """Time a scope: ``with span("allreduce", bytes=n): ...``."""
    return _Span(name, args or None)


def record(name: str, duration_s: float, **args) -> None:
    """Record an externally-timed span ending now (back-compat with the
    old trace.record call sites)."""
    _append(
        TraceEvent(
            name,
            time.perf_counter() - duration_s,
            duration_s,
            threading.get_ident(),
            len(_stack()),
            "X",
            _step_args(args or None),
        )
    )


def instant(name: str, **args) -> None:
    """Record a point-in-time event (resize, strategy switch, ...)."""
    _append(
        TraceEvent(
            name, time.perf_counter(), 0.0, threading.get_ident(),
            len(_stack()), "i", _step_args(args or None),
        )
    )


def events(prefix: str = "") -> List[Tuple[str, float, float]]:
    """(name, start, duration) tuples — the legacy utils.trace shape."""
    return [
        (e.name, e.start, e.duration) for e in full_events(prefix)
    ]


def full_events(prefix: str = "") -> List[TraceEvent]:
    with _lock:
        evs = list(_events)
    if prefix:
        evs = [e for e in evs if e.name.startswith(prefix)]
    return evs


def clear() -> None:
    with _lock:
        _events.clear()


def summary_ms(prefix: str = "") -> Dict[str, float]:
    """Total duration per span name (ms), filtered by prefix."""
    out: Dict[str, float] = {}
    for e in full_events(prefix):
        out[e.name] = out.get(e.name, 0.0) + e.duration * 1e3
    return {k: round(v, 1) for k, v in out.items()}


def chrome_trace(prefix: str = "") -> dict:
    """The buffer as a Chrome trace-event JSON object.

    Timestamps are perf_counter microseconds (a process-relative
    monotonic epoch — exactly what the trace viewers expect).
    """
    pid = os.getpid()
    trace_events = []
    for e in full_events(prefix):
        ev = {
            "name": e.name,
            "ph": e.phase,
            "ts": e.start * 1e6,
            "pid": pid,
            "tid": e.tid,
            "cat": "kungfu",
        }
        if e.phase == "X":
            ev["dur"] = e.duration * 1e6
        else:
            ev["s"] = "t"  # thread-scoped instant
        args = dict(e.args) if e.args else {}
        args["depth"] = e.depth
        ev["args"] = args
        trace_events.append(ev)
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        # clock anchors for offline cross-process merges: ts values are
        # perf_counter us, rendered at perf_now_us == wall_time_s
        "metadata": {
            "pid": pid,
            "perf_now_us": time.perf_counter() * 1e6,
            "wall_time_s": time.time(),
        },
    }


def chrome_trace_json(prefix: str = "") -> str:
    return json.dumps(chrome_trace(prefix))


def export_chrome(path: str, prefix: str = "") -> str:
    """Write the Chrome trace JSON to `path`; returns the path."""
    with open(path, "w") as f:
        f.write(chrome_trace_json(prefix))
    return path
