"""Steptrace: per-step cross-peer critical-path timelines (ISSUE 13).

After the link table (per-edge bandwidth), the walk profiler (per-walk
wait/compute/send) and the straggler scorer (per-peer z-scores), the
question every adaptation policy actually asks was still unanswerable:
*"for step N, which bucket on which peer over which edge was the long
pole, and how much of the step did overlap hide?"* This module is that
plane:

- worker side, a bounded ring (``KF_STEP_TIMELINE_KEEP``) of
  :class:`StepRecorder` timelines, one per scheduler round, fed by the
  async collective scheduler (submit → launch queue delay per bucket,
  walk wall/wait/send with the successor-edge attribution the walk
  engine already computes for the profiler, unpack, the ZeRO weight
  all-gather tail) and served at ``/steptrace``;
- pure merge math (:func:`merge_steps`, :func:`critical_path`) the
  cluster aggregator applies over every worker's timelines, aligned by
  the NTP-style clock offsets it already estimates for /cluster/trace —
  electing each step's **critical (peer, bucket, edge)** chain and its
  overlap fraction (comm hidden under compute / total comm);
- lane rendering (:func:`render_step`, :func:`render_timeline`) shared
  by ``python -m kungfu_tpu.info steps`` and the flight recorder's
  postmortem view.

Sampling: ``KF_TELEMETRY_SPAN_SAMPLE`` thins recording with the same
deterministic evenly-spaced sampler the per-step walk spans use; a
sampled-out step allocates NO timeline (asserted by a subprocess
overhead guard in tests/test_steptrace.py). Times are perf_counter
microseconds — the span tracer's timebase — so the aggregator's clock
offsets apply unchanged.

This module must stay import-light (telemetry-only imports): the walk
engine consults :func:`current_sink` on every allreduce walk.
"""

from __future__ import annotations

import threading
import time
import weakref as _weakref
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import config as tconfig

_US = 1e6


def _now_us() -> float:
    return time.perf_counter() * _US


class _Sampler:
    """Deterministic evenly-spaced sampler (the SpanSampler math, local
    so the telemetry layer never imports the collective package): step n
    records iff the integer part of n*rate advances."""

    __slots__ = ("_n", "_lock")

    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def sample(self, rate: float) -> bool:
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        with self._lock:
            self._n += 1
            n = self._n
        return int(n * rate) != int((n - 1) * rate)


class BucketLane:
    """One launch unit's lane of a step timeline. Mutated from several
    scheduler threads (launcher/walker/gatherer/unpacker touch disjoint
    fields; ``add_walk`` may be fed from pool threads) — the single
    small lock keeps the JSON rendering consistent."""

    __slots__ = (
        "index", "kind", "name", "nbytes", "members",
        "t_submit_us", "t_ready_us", "t_launch_us",
        "t_walk_us", "walk_us", "wait_us", "send_us",
        "unpack_us", "t_gather_us", "gather_us", "gather_wait_us",
        "edge", "gather_edge", "strategy", "_lock",
    )

    def __init__(self, index: int, kind: str = "ar", name: str = "",
                 nbytes: int = 0, members: int = 0):
        self.index = index
        self.kind = kind
        self.name = name
        self.nbytes = nbytes
        self.members = members
        self.t_submit_us: Optional[float] = None  # first member submitted
        self.t_ready_us: Optional[float] = None  # last member submitted
        self.t_launch_us: Optional[float] = None  # launcher claimed it
        self.t_walk_us: Optional[float] = None  # walk began
        self.walk_us = 0.0
        self.wait_us = 0.0  # blocked on predecessor receives
        self.send_us = 0.0  # blocked on successor sends
        self.unpack_us = 0.0
        self.t_gather_us: Optional[float] = None  # ZeRO weight all-gather
        self.gather_us = 0.0
        self.gather_wait_us = 0.0
        self.edge: Optional[str] = None  # successor/slowest dst of the walk
        self.gather_edge: Optional[str] = None
        self.strategy: Optional[str] = None
        self._lock = threading.Lock()

    # -- scheduler feed points ------------------------------------------
    def note_submit(self, t_us: float) -> None:
        with self._lock:
            if self.t_submit_us is None or t_us < self.t_submit_us:
                self.t_submit_us = t_us
            if self.t_ready_us is None or t_us > self.t_ready_us:
                self.t_ready_us = t_us

    def note_launch(self, t_us: float) -> None:
        self.t_launch_us = t_us

    def note_walk_span(self, t0_us: float, dur_us: float) -> None:
        with self._lock:
            if self.t_walk_us is None:
                self.t_walk_us = t0_us
            self.walk_us += dur_us

    def note_unpack(self, dur_us: float) -> None:
        with self._lock:
            self.unpack_us += dur_us

    def note_gather_span(self, t0_us: float, dur_us: float) -> None:
        with self._lock:
            if self.t_gather_us is None:
                self.t_gather_us = t0_us
            self.gather_us += dur_us

    # -- walk-engine feed (via the thread-ambient sink) -----------------
    def add_walk(self, strategy: str, wall_s: float, wait_s: float,
                 send_s: float, edge: Optional[str],
                 gather: bool = False) -> None:
        """One finished walk's attribution (the same numbers the walk
        profiler gets), accumulated into the lane. `gather=True` routes
        a ZeRO weight all-gather's split into the gather fields."""
        with self._lock:
            if gather:
                self.gather_wait_us += wait_s * _US
                if edge:
                    self.gather_edge = edge
            else:
                self.wait_us += wait_s * _US
                self.send_us += send_s * _US
                if edge:
                    self.edge = edge
            if strategy:
                self.strategy = strategy

    # -- derived --------------------------------------------------------
    def queue_delay_us(self) -> float:
        if self.t_launch_us is None or self.t_ready_us is None:
            return 0.0
        return max(0.0, self.t_launch_us - self.t_ready_us)

    def _blocked_scaled(self) -> Tuple[float, float]:
        """(wait, send) clamped so their sum never exceeds the walk's
        wall span — the WalkProfiler clamp, needed here because CHUNKED
        graph walks accumulate each parallel chunk's blocked time into
        one lane whose walk_us is a single wall-clock window: k chunks
        waiting ~W concurrently sum to k*W > walk_us, and an unclamped
        subtraction would zero a genuinely-blocking peer's self time
        (electing the wrong critical peer). Scaling preserves the
        wait:send ratio, which is the signal."""
        blocked = self.wait_us + self.send_us
        if blocked <= self.walk_us or blocked <= 0.0:
            return self.wait_us, self.send_us
        f = self.walk_us / blocked
        return self.wait_us * f, self.send_us * f

    def _gather_wait_scaled(self) -> float:
        return min(self.gather_wait_us, self.gather_us)

    def self_us(self) -> float:
        """Seconds this bucket was the long pole rather than a victim:
        non-wait walk time (compute + send-blocked — a slow OUTGOING
        edge blocks the sender, a slow peer inflates compute) plus the
        gather's non-wait share and the unpack."""
        wait, _ = self._blocked_scaled()
        walk_self = max(0.0, self.walk_us - wait)
        gather_self = max(0.0, self.gather_us - self._gather_wait_scaled())
        return walk_self + gather_self + self.unpack_us

    def to_json(self) -> dict:
        with self._lock:
            wait, send = self._blocked_scaled()
            compute = max(0.0, self.walk_us - wait - send)
            d = {
                "index": self.index,
                "kind": self.kind,
                "name": self.name,
                "bytes": self.nbytes,
                "members": self.members,
                "t_submit_us": _r(self.t_submit_us),
                "t_ready_us": _r(self.t_ready_us),
                "t_launch_us": _r(self.t_launch_us),
                "queue_delay_us": _r(self.queue_delay_us()),
                "t_walk_us": _r(self.t_walk_us),
                "walk_us": _r(self.walk_us),
                "wait_us": _r(wait),
                "send_us": _r(send),
                "compute_us": _r(compute),
                "unpack_us": _r(self.unpack_us),
                "self_us": _r(self.self_us()),
                "edge": self.edge,
                "strategy": self.strategy,
            }
            if self.t_gather_us is not None or self.gather_us:
                d["t_gather_us"] = _r(self.t_gather_us)
                d["gather_us"] = _r(self.gather_us)
                d["gather_wait_us"] = _r(self._gather_wait_scaled())
                d["gather_edge"] = self.gather_edge
            return d


def _r(v: Optional[float]) -> Optional[int]:
    return int(round(v)) if isinstance(v, (int, float)) else None


class StepRecorder:
    """One scheduler round's timeline on this worker. Created by the
    store (subject to sampling), fed by the scheduler, finished at
    flush; the ZeRO gather tail keeps landing after finish() — the ring
    holds the recorder and renders at export time, so late gathers
    still appear."""

    # allocation counter for the sampling overhead guard
    # (tests/test_steptrace.py subprocess-asserts it stays 0 when
    # KF_TELEMETRY_SPAN_SAMPLE=0)
    allocations = 0

    __slots__ = (
        "epoch", "round", "t_begin_us", "t_end_us",
        "flush_wait_us", "busy_us", "buckets", "_lock", "flush_seq",
    )

    def __init__(self, epoch: int, round_: int):
        StepRecorder.allocations += 1
        self.epoch = int(epoch)
        self.round = int(round_)
        self.t_begin_us = _now_us()
        self.t_end_us: Optional[float] = None
        # delta-scrape cursor (ISSUE 18): assigned by the store at the
        # first export AFTER the timeline flushed — transport metadata,
        # deliberately kept out of to_json so merged lanes are identical
        # whether the scraper used a cursor or not
        self.flush_seq: Optional[int] = None
        self.flush_wait_us = 0.0
        self.busy_us = 0.0
        self.buckets: Dict[int, BucketLane] = {}
        self._lock = threading.Lock()

    def bucket(self, index: int, kind: str = "ar", name: str = "",
               nbytes: int = 0, members: int = 0) -> BucketLane:
        with self._lock:
            b = self.buckets.get(index)
            if b is None:
                b = self.buckets[index] = BucketLane(
                    index, kind, name, nbytes, members
                )
            return b

    def finish(self, flush_wait_s: float, busy_s: float) -> None:
        self.flush_wait_us = flush_wait_s * _US
        self.busy_us = busy_s * _US
        self.t_end_us = _now_us()

    def overlap_frac(self) -> Optional[float]:
        """Comm hidden under compute / total comm for this step: the
        engine-busy time not surfaced as flush wait (the scheduler-side
        measure the BENCH_HOST_r08/r09 OVERLAP lines report)."""
        if self.busy_us <= 0:
            return None
        return max(0.0, self.busy_us - self.flush_wait_us) / self.busy_us

    def queue_delay_frac(self) -> Optional[float]:
        if self.busy_us <= 0:
            return None
        # copy under the lock: submit threads insert lanes into the live
        # dict while scrapes/snapshots/policy signals read the recorder
        # (it sits in the ring from begin_step on) — iterating the dict
        # itself would intermittently raise "changed size during
        # iteration" exactly on busy steps
        with self._lock:
            lanes = list(self.buckets.values())
        return sum(b.queue_delay_us() for b in lanes) / self.busy_us

    def to_json(self) -> dict:
        with self._lock:
            buckets = sorted(self.buckets.values(), key=lambda b: b.index)
        return {
            "epoch": self.epoch,
            "round": self.round,
            "t_begin_us": _r(self.t_begin_us),
            "t_end_us": _r(self.t_end_us),
            "flush_wait_us": _r(self.flush_wait_us),
            "busy_us": _r(self.busy_us),
            "overlap_frac": self.overlap_frac(),
            "queue_delay_frac": self.queue_delay_frac(),
            "buckets": [b.to_json() for b in buckets],
        }


class StepStore:
    """Bounded ring of recent step timelines (KF_STEP_TIMELINE_KEEP)."""

    def __init__(self, keep: Optional[int] = None):
        self._keep = keep if keep is not None else max(
            0, int(knobs.get("KF_STEP_TIMELINE_KEEP"))
        )
        self._ring: "deque[StepRecorder]" = deque(maxlen=max(1, self._keep))
        self._lock = threading.Lock()
        self._sampler = _Sampler()
        self._stats = {"recorded": 0, "sampled_out": 0}
        # delta-scrape cursor space (ISSUE 18): monotonically increasing
        # across the store's lifetime (clear() keeps it), stamped onto
        # timelines at the first export after they flush — `?since=N`
        # re-scrapes ship only newly-flushed timelines
        self._seq = 0
        # memory plane (ISSUE 17): the ring is a long-lived buffer
        # owner; it reports its CAP (mean item x maxlen) so filling up
        # never looks like a leak. Weakref — reset_store() must not
        # leave a pinned store behind.
        try:
            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=_weakref.ref(self)):
                store = ref()
                return store.footprint_bytes() if store is not None else None

            _tmem.register_accountant("steptrace", "telemetry", _acct)
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the step store
        except Exception:  # noqa: BLE001
            pass

    def footprint_bytes(self) -> int:
        """Capacity estimate of the step ring in bytes (the memory
        plane's `telemetry` bucket)."""
        from kungfu_tpu.telemetry import memory as _tmem

        with self._lock:
            ring = list(self._ring)
        cap = deque(ring, maxlen=self._ring.maxlen)
        return _tmem.ring_cap_bytes(cap)

    def begin_step(self, epoch: int, round_: int) -> Optional[StepRecorder]:
        """Start recording one round, or None when the ring is disabled
        (keep=0) or the deterministic sampler thins this step — the None
        path allocates nothing (overhead-guard contract)."""
        if self._keep <= 0:
            return None
        if not self._sampler.sample(tconfig.span_sample()):
            with self._lock:
                self._stats["sampled_out"] += 1
            return None
        rec = StepRecorder(epoch, round_)
        with self._lock:
            self._ring.append(rec)
            self._stats["recorded"] += 1
        return rec

    def timelines(self) -> List[dict]:
        with self._lock:
            recs = list(self._ring)
        return [r.to_json() for r in recs]

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._stats = {"recorded": 0, "sampled_out": 0}

    def export(self, peer: str = "", since: Optional[int] = None) -> dict:
        """The /steptrace document: the ring plus the clock anchors the
        aggregator needs (perf_now_us matches the X-KF-Perf-Now-Us
        header timebase).

        ``since`` is the delta-scrape cursor (ISSUE 18): each timeline
        is stamped with a monotonic flush seq at its first post-flush
        export, carried transport-side as ``seq`` (NOT in the merged
        lanes); ``since=N`` ships only flushed timelines with seq > N,
        and ``next_since`` is the cursor for the next scrape. A
        timeline that falls off the ring before it is ever shipped is
        lost — the same bounded-ring contract the full export has."""
        with self._lock:
            recs = list(self._ring)
            for r in recs:
                if r.t_end_us is not None and r.flush_seq is None:
                    self._seq += 1
                    r.flush_seq = self._seq
            next_since = self._seq
        timelines = []
        for r in recs:
            if since is not None and (
                r.t_end_us is None
                or (r.flush_seq or 0) <= since
            ):
                continue
            d = r.to_json()
            if r.flush_seq is not None:
                d["seq"] = r.flush_seq
            timelines.append(d)
        return {
            "peer": peer or knobs.raw("KF_SELF_SPEC"),
            "perf_now_us": _now_us(),
            "wall_time_s": time.time(),
            "keep": self._keep,
            "next_since": next_since,
            "stats": self.stats(),
            "timelines": timelines,
        }

    def local_signals(self) -> Dict[str, float]:
        """Worker-local adaptation signals (the cluster-wide merge
        overrides these in PolicyContext.metrics when a runner
        aggregator is live): the mean overlap and queue-delay fractions
        of the recent recorded steps."""
        with self._lock:
            recs = list(self._ring)
        ov = [r.overlap_frac() for r in recs]
        qd = [r.queue_delay_frac() for r in recs]
        ov = [v for v in ov if v is not None]
        qd = [v for v in qd if v is not None]
        out: Dict[str, float] = {}
        if ov:
            out["step/overlap_frac"] = sum(ov) / len(ov)
        if qd:
            out["step/queue_delay_frac"] = sum(qd) / len(qd)
        return out


_store: Optional[StepStore] = None
_store_lock = threading.Lock()


def get_store() -> StepStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = StepStore()
        return _store


def reset_store() -> None:
    """Drop the process store (tests flip knobs at runtime)."""
    global _store
    with _store_lock:
        _store = None


# ---------------------------------------------------------------------------
# thread-ambient walk sink: the scheduler parks the active bucket lane
# here around each walk; the walk engine's _record_walk feeds it the
# same wait/send/edge attribution the profiler gets. Read once per walk
# on the walking thread (chunked graph walks fan out to pool threads,
# so the engine captures the sink before dispatching).
# ---------------------------------------------------------------------------

_sink_tls = threading.local()


class _SinkScope:
    __slots__ = ("lane", "gather", "prev")

    def __init__(self, lane: Optional[BucketLane], gather: bool):
        self.lane = lane
        self.gather = gather

    def __enter__(self):
        self.prev = getattr(_sink_tls, "cur", None)
        _sink_tls.cur = (
            None if self.lane is None else (self.lane, self.gather)
        )
        return self

    def __exit__(self, *exc):
        _sink_tls.cur = self.prev
        return False


def walk_sink(lane: Optional[BucketLane], gather: bool = False) -> _SinkScope:
    """Route walk attribution on this thread into `lane` (None = no-op
    scope, the sampled-out path)."""
    return _SinkScope(lane, gather)


def current_sink() -> Optional[Tuple[BucketLane, bool]]:
    return getattr(_sink_tls, "cur", None)


def note_walk(sink: Optional[Tuple[BucketLane, bool]], strategy: str,
              wall_s: float, wait_s: float, send_s: float,
              edge: Optional[str]) -> None:
    """Feed one finished walk's attribution to a captured sink (the walk
    engine calls this next to its profiler feed)."""
    if sink is None:
        return
    lane, gather = sink
    lane.add_walk(strategy, wall_s, wait_s, send_s, edge, gather=gather)


# ---------------------------------------------------------------------------
# merge math (pure: the aggregator and the property tests drive it)
# ---------------------------------------------------------------------------

_ALIGN_KEYS = (
    "t_submit_us", "t_ready_us", "t_launch_us", "t_walk_us", "t_gather_us",
)


def align_timeline(tl: dict, offset_us: float) -> dict:
    """A copy of one timeline with every absolute perf_counter stamp
    shifted by `offset_us` onto the merger's timeline (the aggregator's
    NTP-style clock offset: runner_time = worker_time + offset)."""
    out = dict(tl)
    # the delta-scrape cursor (ISSUE 18) is transport metadata between
    # one store and one scraper — merged lanes must be identical whether
    # the scraper used a cursor or not
    out.pop("seq", None)
    for key in ("t_begin_us", "t_end_us"):
        if isinstance(out.get(key), (int, float)):
            out[key] = out[key] + offset_us
    buckets = []
    for b in tl.get("buckets", []):
        nb = dict(b)
        for key in _ALIGN_KEYS:
            if isinstance(nb.get(key), (int, float)):
                nb[key] = nb[key] + offset_us
        buckets.append(nb)
    out["buckets"] = buckets
    return out


def critical_path(peer_timelines: Dict[str, dict],
                  chain_min_frac: float = 0.25,
                  chain_max: int = 5) -> dict:
    """Elect one step's blocking chain from its per-peer timelines.

    Per (peer, bucket) the blocking contribution is ``self_us``: walk
    time NOT spent waiting on a predecessor (compute + send-blocked —
    under synchronous collectives the waiters are victims; the peer
    whose time went to compute or to a blocked send toward a slow edge
    is the cause) plus the gather's non-wait share and the unpack. The
    critical element is the max; the chain is every contribution within
    ``chain_min_frac`` of it, largest first (the cross-peer tail of the
    same slow edge shows up here)."""
    contribs: List[dict] = []
    for peer, tl in peer_timelines.items():
        for b in tl.get("buckets", []):
            self_us = b.get("self_us")
            if self_us is None:
                walk = b.get("walk_us") or 0.0
                wait = b.get("wait_us") or 0.0
                gather = b.get("gather_us") or 0.0
                gwait = b.get("gather_wait_us") or 0.0
                self_us = (
                    max(0.0, walk - wait)
                    + max(0.0, gather - gwait)
                    + (b.get("unpack_us") or 0.0)
                )
            contribs.append({
                "peer": peer,
                "bucket": b.get("index"),
                "name": b.get("name"),
                "edge": b.get("edge") or b.get("gather_edge"),
                "strategy": b.get("strategy"),
                "self_us": float(self_us),
            })
    if not contribs:
        return {"critical": None, "chain": []}
    contribs.sort(key=lambda c: -c["self_us"])
    top = contribs[0]
    cut = top["self_us"] * chain_min_frac
    chain = [c for c in contribs if c["self_us"] >= cut][:chain_max]
    return {"critical": top, "chain": chain}


def merge_steps(peer_docs: Dict[str, dict],
                offsets_us: Dict[str, float],
                limit: Optional[int] = None) -> List[dict]:
    """Merge every peer's /steptrace document into per-step records,
    oldest first: group timelines by (epoch, round), align each peer's
    stamps by its clock offset, elect the critical chain and compute
    the step-wide overlap / queue-delay fractions (busy-weighted across
    peers). Peers missing a step (sampling thins independently) simply
    don't contribute; a step nobody recorded doesn't exist."""
    grouped: Dict[Tuple[int, int], Dict[str, dict]] = {}
    for peer, doc in peer_docs.items():
        off = offsets_us.get(peer) or 0.0
        for tl in (doc or {}).get("timelines", []):
            key = (int(tl.get("epoch", 0)), int(tl.get("round", 0)))
            grouped.setdefault(key, {})[peer] = align_timeline(tl, off)
    steps: List[dict] = []
    for (epoch, rnd) in sorted(grouped):
        peers = grouped[(epoch, rnd)]
        busy = sum((tl.get("busy_us") or 0.0) for tl in peers.values())
        flush = sum((tl.get("flush_wait_us") or 0.0) for tl in peers.values())
        qdelay = sum(
            (b.get("queue_delay_us") or 0.0)
            for tl in peers.values()
            for b in tl.get("buckets", [])
        )
        begins = [
            tl["t_begin_us"] for tl in peers.values()
            if isinstance(tl.get("t_begin_us"), (int, float))
        ]
        # the step window extends past the flush seal to cover ZeRO
        # gather tails (which land after flush by design) — otherwise
        # the lanes clip the 'g' cells the legend advertises while the
        # election still counts the full gather time
        ends = [
            tl["t_end_us"] for tl in peers.values()
            if isinstance(tl.get("t_end_us"), (int, float))
        ]
        for tl in peers.values():
            for b in tl.get("buckets", []):
                g0 = b.get("t_gather_us")
                if isinstance(g0, (int, float)):
                    ends.append(g0 + (b.get("gather_us") or 0.0))
        elected = critical_path(peers)
        steps.append({
            "epoch": epoch,
            "round": rnd,
            "peers": peers,
            "t_begin_us": min(begins) if begins else None,
            "t_end_us": max(ends) if ends else None,
            "wall_us": (
                max(ends) - min(begins) if begins and ends else None
            ),
            "overlap_frac": (
                max(0.0, busy - flush) / busy if busy > 0 else None
            ),
            "queue_delay_frac": qdelay / busy if busy > 0 else None,
            "critical": elected["critical"],
            "chain": elected["chain"],
        })
    if limit is not None and len(steps) > limit:
        steps = steps[-limit:]
    return steps


# ---------------------------------------------------------------------------
# lane rendering (info steps + the flight postmortem's final step)
# ---------------------------------------------------------------------------

_LANE_W = 40


def _lane(tl: dict, t0: float, t1: float, width: int = _LANE_W) -> str:
    """One peer's timeline as a fixed-width lane over [t0, t1]:
    '·' queued (submitted, not launched), '≈' wait-on-recv, '■' compute,
    '>' send-blocked, 'g' gather tail, ' ' idle."""
    span = max(1.0, t1 - t0)
    cells = [" "] * width

    def paint(a: Optional[float], dur: float, ch: str) -> None:
        if not isinstance(a, (int, float)) or dur <= 0:
            return
        lo = int((a - t0) / span * width)
        hi = int((a + dur - t0) / span * width)
        for i in range(max(0, lo), min(width, max(hi, lo + 1))):
            if cells[i] == " ":
                cells[i] = ch

    for b in tl.get("buckets", []):
        walk0 = b.get("t_walk_us")
        wait = b.get("wait_us") or 0.0
        send = b.get("send_us") or 0.0
        walk = b.get("walk_us") or 0.0
        # phase order inside one bucket's walk window is interleaved in
        # reality; the lane shows wait first, then compute, then send —
        # proportions right, sequence schematic
        paint(b.get("t_ready_us"), b.get("queue_delay_us") or 0.0, "·")
        if isinstance(walk0, (int, float)):
            paint(walk0, wait, "≈")
            paint(walk0 + wait, max(0.0, walk - wait - send), "■")
            paint(walk0 + max(0.0, walk - send), send, ">")
        paint(b.get("t_gather_us"), b.get("gather_us") or 0.0, "g")
    return "".join(cells)


def render_step(step: dict) -> List[str]:
    """One merged step as aligned per-peer lanes with the critical chain
    called out (the `info steps` frame unit)."""
    crit = step.get("critical") or {}
    ov = step.get("overlap_frac")
    qd = step.get("queue_delay_frac")
    head = f"step e{step.get('epoch')}:r{step.get('round')}"
    if crit:
        edge = f" edge →{crit['edge']}" if crit.get("edge") else ""
        head += (
            f"  critical {crit.get('peer')} bucket {crit.get('bucket')}"
            f"{edge} ({(crit.get('self_us') or 0.0) / 1e3:.1f} ms)"
        )
    if ov is not None:
        head += f"  overlap {ov:.0%}"
    if qd is not None:
        head += f"  queue {qd:.0%}"
    lines = [head]
    peers = step.get("peers", {})
    t0 = step.get("t_begin_us")
    t1 = step.get("t_end_us")
    if not isinstance(t0, (int, float)) or not isinstance(t1, (int, float)):
        return lines
    crit_peer = crit.get("peer")
    for peer in sorted(peers):
        mark = "*" if peer == crit_peer else " "
        lines.append(f"  {mark}{peer}  |{_lane(peers[peer], t0, t1)}|")
    return lines


def render_timeline(tl: dict, peer: str = "") -> List[str]:
    """One UNMERGED worker timeline (the postmortem's final step: no
    cluster view exists for a dead worker, so the lane is its own)."""
    t0 = tl.get("t_begin_us")
    t1 = tl.get("t_end_us")
    ov = tl.get("overlap_frac")
    head = f"step e{tl.get('epoch')}:r{tl.get('round')}"
    if ov is not None:
        head += f"  overlap {ov:.0%}"
    if not isinstance(t1, (int, float)):
        head += "  (UNFLUSHED — the step was in flight at death)"
        ends = [
            (b.get("t_walk_us") or 0.0) + (b.get("walk_us") or 0.0)
            for b in tl.get("buckets", [])
        ]
        t1 = max(ends) if ends else None
    lines = [head]
    if isinstance(t0, (int, float)) and isinstance(t1, (int, float)):
        label = peer or "self"
        lines.append(f"   {label}  |{_lane(tl, t0, t1)}|")
    for b in tl.get("buckets", []):
        state = "done"
        if b.get("t_launch_us") is None:
            state = "queued (never launched)"
        elif b.get("walk_us") in (None, 0):
            state = "launched, walk never finished"
        elif b.get("kind") == "zero" and not b.get("gather_us"):
            state = "shard updated, weight all-gather outstanding"
        edge = f" edge →{b['edge']}" if b.get("edge") else ""
        lines.append(
            f"   bucket {b.get('index')} [{b.get('kind')}] "
            f"{(b.get('name') or '?')[:40]}{edge}: {state}"
        )
    return lines
