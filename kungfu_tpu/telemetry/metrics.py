"""Process-wide metrics registry: counters, gauges, histograms with labels.

The host plane's single source of numeric truth: transport byte/message
counters, collective-latency histograms, resize counters and the
monitor gauges (noise scale, gradient variance) all live in one
:class:`Registry` and export through one Prometheus text endpoint
(parity: the reference's monitor/server.go exposition, generalized from
two hardcoded counter families to an open registry).

Design notes:
- every metric family is thread-safe (one lock per family; children
  share it — label lookups and float adds are nanosecond-scale next to
  a socket send, and the GIL already serializes the adds);
- histograms are cumulative-bucket Prometheus histograms; quantiles are
  estimated by linear interpolation inside the owning bucket (standard
  histogram_quantile semantics);
- label values are escaped per the Prometheus text exposition spec.
"""

from __future__ import annotations

import math
import os
import threading
import time
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# latency-flavoured default buckets: 100us .. 60s
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_RESERVED = ("__",)

# Cardinality guard (ISSUE 6 satellite): cap distinct label combinations
# PER METRIC FAMILY. Per-peer families ({peer}, {dst}) grow linearly with
# cluster size — at k=64 that is fine, but a bug (or labels built from
# unbounded values like message names) would otherwise grow the registry
# without limit and take /metrics scrape time and RSS with it. Beyond the
# cap, label lookups return a shared detached child (increments are
# accepted and discarded from the exposition) and the drop is counted in
# ``kungfu_telemetry_dropped_series_total{metric}`` — a visible signal
# instead of silent unbounded growth. Read at family-creation time.
MAX_SERIES_ENV = "KF_TELEMETRY_MAX_SERIES"
DEFAULT_MAX_SERIES = 512
DROPPED_SERIES = "kungfu_telemetry_dropped_series_total"


def max_series() -> int:
    """Per-family label-set cap (0 disables the guard)."""
    from kungfu_tpu import knobs

    return max(0, knobs.get(MAX_SERIES_ENV))


def _validate_name(name: str) -> str:
    if not name or name.startswith(_RESERVED):
        raise ValueError(f"bad metric name {name!r}")
    ok = all(c.isalnum() or c in "_:" for c in name)
    if not ok or name[0].isdigit():
        raise ValueError(f"bad metric name {name!r}")
    return name


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labelnames: Sequence[str], labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


class _Metric:
    """Base family: owns children keyed by label-value tuples."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = _validate_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        # cardinality guard state: the cap (0 = unguarded; the dropped-
        # series counter itself is exempt — its cardinality is bounded by
        # the family count), the shared overflow child handed to callers
        # past the cap, and the registry to count drops into (set by
        # Registry._get_or_create; standalone families use the global)
        self._max_series = (
            max_series()
            if self.labelnames and name != DROPPED_SERIES
            else 0
        )
        self._overflow_child = None
        self._registry: Optional["Registry"] = None
        if not self.labelnames:
            # label-less families get their default child eagerly so they
            # always render (a registered counter at 0 is information)
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *labelvalues, **labelkv):
        if labelkv:
            if labelvalues:
                raise ValueError("pass label values positionally OR by name")
            try:
                labelvalues = tuple(labelkv[k] for k in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}") from None
        key = tuple(str(v) for v in labelvalues)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(key)} label values, "
                f"want {len(self.labelnames)}"
            )
        dropped = False
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self._max_series and len(self._children) >= self._max_series:
                    # at the cap: hand back the shared detached child —
                    # writes are accepted (call sites stay branch-free)
                    # but never rendered — and count the drop below,
                    # outside this family's lock
                    if self._overflow_child is None:
                        self._overflow_child = self._new_child()
                    child = self._overflow_child
                    dropped = True
                else:
                    child = self._new_child()
                    self._children[key] = child
        if dropped:
            self._count_drop()
        return child

    def _count_drop(self) -> None:
        reg = self._registry if self._registry is not None else REGISTRY
        try:
            reg.counter(
                DROPPED_SERIES,
                "Label-set lookups rejected by the per-family cardinality "
                "guard (KF_TELEMETRY_MAX_SERIES)",
                ("metric",),
            ).labels(self.name).inc()
        except ValueError:
            pass  # a colliding user family must not break the guard

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames}; use .labels()")
        return self._children[()]

    def remove(self, *labelvalues) -> None:
        """Drop ONE labelled series from the exposition (label-population
        churn, e.g. a link destination that left the cluster). No-op when
        the series never existed; frees a slot under the cardinality cap."""
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            self._children.pop(key, None)

    def clear_children(self) -> None:
        """Drop every labelled child (bounds cardinality when the label
        population churns, e.g. per-peer gauges across elastic resizes).
        No-op on label-less families (their default child is the metric)."""
        if not self.labelnames:
            return
        with self._lock:
            self._children.clear()

    def samples(self) -> List[Tuple[str, str, float]]:
        """Flat (name+labels suffix, label string, value) samples."""
        out = []
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            ls = _label_str(self.labelnames, key)
            out.extend(child._samples(self.name, self.labelnames, key, ls))
        return out

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for name, ls, value in self.samples():
            lines.append(f"{name}{ls} {_fmt_value(value)}")
        return "\n".join(lines)


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self, lock: threading.Lock):
        self._value = 0.0
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self, name, labelnames, key, ls):
        return [(name, ls, self.value)]


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self, lock: threading.Lock):
        self._value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self, name, labelnames, key, ls):
        return [(name, ls, self.value)]


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class _HistogramChild:
    __slots__ = ("_counts", "_sum", "_count", "_bounds", "_lock")

    def __init__(self, lock: threading.Lock, bounds: Tuple[float, ...]):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = lock

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1), interpolated within the owning
        bucket (histogram_quantile semantics). NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return math.nan
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i] if i < len(self._bounds) else math.inf
                if hi == math.inf:
                    return lo  # open-ended bucket: clamp like Prometheus
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self._bounds[-1] if self._bounds else math.nan

    def _samples(self, name, labelnames, key, ls):
        out = []
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cum = 0
        for bound, c in zip(self._bounds, counts):
            cum += c
            le = _label_str(
                tuple(labelnames) + ("le",), tuple(key) + (_fmt_value(bound),)
            )
            out.append((name + "_bucket", le, cum))
        le = _label_str(tuple(labelnames) + ("le",), tuple(key) + ("+Inf",))
        out.append((name + "_bucket", le, total))
        out.append((name + "_sum", ls, s))
        out.append((name + "_count", ls, total))
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket")
        self._bounds = bounds
        super().__init__(name, help, labelnames)

    def _new_child(self):
        return _HistogramChild(self._lock, self._bounds)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum


class Registry:
    """Named metric families; get-or-create semantics so any module can
    declare its metrics idempotently at import or call time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # extra exposition blocks appended to render() (e.g. the net
        # monitor's windowed rates, which aren't plain registry samples)
        self._extra_renderers: List = []

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with different "
                        f"type/labels ({m.kind} {m.labelnames})"
                    )
                want_buckets = kw.get("buckets")
                if want_buckets is not None and tuple(
                    sorted(float(b) for b in want_buckets)
                ) != m._bounds:
                    # as loud as a type mismatch: silently keeping the
                    # first registrant's buckets would truncate the
                    # second's range into +Inf with no signal
                    raise ValueError(
                        f"histogram {name!r} re-registered with different "
                        f"buckets ({m._bounds} vs {tuple(want_buckets)})"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            m._registry = self  # drop counting lands in the owning registry
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def add_renderer(self, fn) -> None:
        """Attach an extra `() -> str` exposition block (idempotent)."""
        with self._lock:
            if fn not in self._extra_renderers:
                self._extra_renderers.append(fn)

    def collect(self) -> Dict[str, List[Tuple[str, str, float]]]:
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.samples() for m in metrics}

    def render(self, include_extras: bool = True) -> str:
        """Full Prometheus text exposition. include_extras=False skips the
        attached renderers (for embedders that merge their own block and
        must not emit a metric family twice)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
            extras = list(self._extra_renderers) if include_extras else []
        blocks = [m.render() for m in metrics]
        for fn in extras:
            try:
                blocks.append(fn().rstrip("\n"))
            except Exception as e:  # noqa: BLE001 - one bad renderer must not 500 /metrics
                from kungfu_tpu.telemetry import log

                log.debug("metrics: extra renderer failed: %s", e)
        return "\n".join(b for b in blocks if b) + "\n"

    def clear(self) -> None:
        """Drop every family (tests)."""
        with self._lock:
            self._metrics.clear()
            self._extra_renderers.clear()


REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY


def counter(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(
    name: str,
    help: str = "",
    labelnames: Sequence[str] = (),
    buckets: Iterable[float] = DEFAULT_BUCKETS,
) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render() -> str:
    return REGISTRY.render()


# -- process self-health ------------------------------------------------
# OOM kills and fd leaks are the failure modes a postmortem most often
# has to explain; these gauges give the flight recorder and the cluster
# plane the trend line. Sampled on demand (every /metrics scrape and
# every flight snapshot), not on a timer of their own.

_PROC_START = time.time()
_PAGE_SIZE = (
    os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
)


def _rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm", "rb") as f:
            return float(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        pass
    try:  # non-Linux fallback: peak RSS is better than nothing
        import resource
        import sys

        maxrss = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        # ru_maxrss is KiB on Linux/BSD but BYTES on macOS
        return maxrss if sys.platform == "darwin" else maxrss * 1024
    except (ImportError, ValueError, OSError):
        return None


def _open_fds() -> Optional[float]:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return None


def update_process_health(registry: Optional[Registry] = None) -> Dict[str, float]:
    """Sample RSS / open fds / thread count / uptime into the registry's
    ``kungfu_process_*`` gauges; returns what was measured."""
    reg = registry or REGISTRY
    out: Dict[str, float] = {}
    rss = _rss_bytes()
    if rss is not None:
        reg.gauge(
            "kungfu_process_rss_bytes", "Resident set size of this process"
        ).set(rss)
        out["rss_bytes"] = rss
    fds = _open_fds()
    if fds is not None:
        reg.gauge(
            "kungfu_process_open_fds", "Open file descriptors of this process"
        ).set(fds)
        out["open_fds"] = fds
    n_threads = float(threading.active_count())
    reg.gauge(
        "kungfu_process_threads", "Live Python threads in this process"
    ).set(n_threads)
    out["threads"] = n_threads
    uptime = max(time.time() - _PROC_START, 0.0)
    reg.gauge(
        "kungfu_process_uptime_seconds",
        "Seconds since this process imported the metrics registry",
    ).set(uptime)
    out["uptime_seconds"] = uptime
    return out
