"""Straggler/anomaly detection over per-peer telemetry series.

The MLPerf TPU-pod scaling work identified step-time skew across
replicas as THE primary scaling diagnostic: one slow peer gates every
synchronous collective, so the cluster trains at the straggler's pace.
This module turns the aggregator's per-peer scrape series (step times,
RTTs) into robust outlier flags the adaptation layer can act on.

Method (robust to the exact failure it hunts): each peer keeps a
rolling window of recent observations and is represented by its window
**median** (a peer's own noise spike must not flag it). Across peers,
the score is a robust z-score against the cluster median using MAD
(median absolute deviation, scaled by 1.4826 to estimate sigma) — the
z-score/IQR family of flags, but with estimators that a single extreme
peer cannot drag. A peer is flagged when BOTH hold:

- score >= z_threshold  (statistically far from the cluster), and
- value >= ratio_threshold * cluster median  (materially slower —
  a homogeneous fast cluster with microsecond jitter stays quiet).

With fewer than ``min_peers`` reporting peers the detector stays quiet:
skew is only defined relative to a population.
"""

from __future__ import annotations

import threading
from collections import deque
from statistics import median as _median
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

MAD_SIGMA = 1.4826  # MAD -> sigma for a normal distribution


class PeerScore(NamedTuple):
    value: float  # the peer's rolling-median observation
    score: float  # robust z against the cluster median
    flagged: bool


class StragglerScorer:
    def __init__(
        self,
        window: int = 16,
        z_threshold: float = 3.0,
        ratio_threshold: float = 1.5,
        min_peers: int = 3,
        min_samples: int = 2,
    ):
        self.window = window
        self.z_threshold = z_threshold
        self.ratio_threshold = ratio_threshold
        self.min_peers = min_peers
        self.min_samples = min_samples
        self._lock = threading.Lock()
        self._series: Dict[str, deque] = {}

    def observe(self, peer: str, value: float) -> None:
        with self._lock:
            q = self._series.get(peer)
            if q is None:
                q = self._series[peer] = deque(maxlen=self.window)
            q.append(float(value))

    def forget(self, live_peers: Iterable[str]) -> None:
        """Drop series for peers no longer in the cluster (elastic
        resizes must not leave ghost peers skewing the population)."""
        live = set(live_peers)
        with self._lock:
            for p in [p for p in self._series if p not in live]:
                del self._series[p]

    def drop(self, peer: str) -> None:
        """Drop one peer's series (its data source went dark: a frozen
        window must not keep flagging — or skewing — the population)."""
        with self._lock:
            self._series.pop(peer, None)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    def _medians(self) -> Dict[str, float]:
        with self._lock:
            return {
                p: _median(list(q))
                for p, q in self._series.items()
                if len(q) >= self.min_samples
            }

    def scores(self) -> Dict[str, PeerScore]:
        """Per-peer (rolling value, robust z, flagged) for every peer
        with enough samples. Empty until min_peers peers report."""
        meds = self._medians()
        if len(meds) < self.min_peers:
            return {
                p: PeerScore(v, 0.0, False) for p, v in meds.items()
            }
        cluster = _median(list(meds.values()))
        mad = _median([abs(v - cluster) for v in meds.values()])
        # sigma floor: a perfectly homogeneous cluster has MAD 0 and a
        # bare z-score would flag nanoseconds of jitter; 5% of the
        # cluster median (or an epsilon for all-zero series) keeps the
        # score scale meaningful
        sigma = max(MAD_SIGMA * mad, 0.05 * abs(cluster), 1e-9)
        out: Dict[str, PeerScore] = {}
        for p, v in meds.items():
            z = (v - cluster) / sigma
            flagged = (
                z >= self.z_threshold
                and v >= self.ratio_threshold * cluster
            )
            out[p] = PeerScore(v, z, flagged)
        return out

    def stragglers(self) -> List[str]:
        return sorted(p for p, s in self.scores().items() if s.flagged)

    def cluster_median(self) -> Optional[float]:
        meds = self._medians()
        return _median(list(meds.values())) if meds else None

    def skew(self) -> Optional[float]:
        """max(peer median) / cluster median — 1.0 means perfectly even;
        the headline number for "how much is the slowest peer costing"."""
        meds = self._medians()
        if len(meds) < 2:
            return None
        cluster = _median(list(meds.values()))
        if cluster <= 0:
            return None
        return max(meds.values()) / cluster


def blocking_edge(
    peer: str,
    steps: Optional[List[dict]] = None,
    links: Optional[dict] = None,
) -> Optional[List[Optional[str]]]:
    """The measured edge behind a flagged straggler (ISSUE 13 satellite):
    a z-score says *who* is slow, this says *where* — so the straggler
    audit event can name the blocking (src, dst) instead of only a
    duration.

    Preference order: the most recent merged step whose critical peer IS
    the flagged one (its elected edge is the direct measurement), else
    the slowest estimated link touching the peer in the k×k matrix
    (``merge_matrix`` document), else None — a compute straggler has no
    edge and should not get a fabricated one."""
    for s in reversed(steps or []):
        c = s.get("critical")
        if c and str(c.get("peer")) == str(peer) and c.get("edge"):
            return [str(peer), str(c["edge"])]
    worst: Optional[List[Optional[str]]] = None
    worst_bw: Optional[float] = None
    for src, row in ((links or {}).get("edges") or {}).items():
        for dst, info in row.items():
            if str(peer) not in (str(src), str(dst)):
                continue
            bw = info.get("bw")
            if not isinstance(bw, (int, float)) or bw <= 0:
                continue
            if worst_bw is None or bw < worst_bw:
                worst_bw = float(bw)
                worst = [str(src), str(dst)]
    return worst


def classify_cause(
    peer: str,
    steps: Optional[List[dict]] = None,
    links: Optional[dict] = None,
    resources: Optional[dict] = None,
    memory: Optional[dict] = None,
) -> Tuple[str, Optional[List[Optional[str]]]]:
    """Name WHY a flagged peer is slow (ISSUE 16 + 17): ``(cause,
    edge)`` with cause in {network, memory, compute, unknown}. Every
    cause is backed by a measurement, never inferred from absence:

    - the step plane elected this peer's edge as a recent critical
      path → **network** (the direct per-step measurement, strongest);
    - the memory plane says the peer is thrashing (sustained major
      page faults — its working set is paging off disk/swap) →
      **memory** (a pegged CPU or a slow link is a SYMPTOM when every
      access is a disk read, so this outranks the compute election);
    - the resource plane says the peer burned >= its saturation
      fraction of its effective cores → **compute** (a ring re-order
      or more bandwidth cannot speed up a pegged CPU);
    - otherwise, the slowest measured link touching the peer →
      **network** (weaker — a matrix estimate, not a step election —
      so the live thrash/saturation measurements outrank it);
    - no measurement at all → **unknown** with no fabricated edge.

    ``resources``/``memory`` are the merged /cluster/resources and
    /cluster/memory documents (their ``peers[peer]["saturated"]`` and
    ``peers[peer]["thrashing"]`` flags)."""
    for s in reversed(steps or []):
        c = s.get("critical")
        if c and str(c.get("peer")) == str(peer) and c.get("edge"):
            return "network", [str(peer), str(c["edge"])]
    # lazy imports: straggler is imported by the scorer-only paths too
    from kungfu_tpu.telemetry import memory as tmemory
    from kungfu_tpu.telemetry import resource as tresource

    if tmemory.peer_thrashing(memory, peer):
        return "memory", None
    if tresource.peer_saturated(resources, peer):
        return "compute", None
    edge = blocking_edge(peer, steps=None, links=links)
    if edge is not None:
        return "network", edge
    return "unknown", None
