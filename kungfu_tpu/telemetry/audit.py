"""Resize audit log: every elastic membership change, on the record.

Capability beyond the reference: KungFu logs resizes as free text; here
each membership change appends a structured record — old/new cluster,
trigger (config server / explicit / schedule / reload), per-phase sync
durations, progress and checkpoint version when the driver knows them —
queryable in-process (:func:`records`), over HTTP (``/audit``) and as
JSONL. Strategy switches from the adaptive controller land in the same
log so "why did throughput change at t?" has one answer surface.

Each record also feeds the metrics registry (resize counter + latency
histogram) and drops an instant event into the trace buffer, so all
three telemetry views agree on when adaptation happened.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import List, Optional

from kungfu_tpu.telemetry import metrics, tracing

MAX_RECORDS = 1024

# resizes take ~100ms..minutes; widen the default latency buckets
RESIZE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0)


@dataclasses.dataclass
class AuditRecord:
    kind: str  # "resize" | "strategy_switch" | ...
    wall_time: float  # unix seconds
    peer: str  # reporting peer ("host:port"), "" when unknown
    cluster_version: Optional[int] = None
    trigger: str = ""
    old_size: Optional[int] = None
    new_size: Optional[int] = None
    old_peers: Optional[List[str]] = None
    new_peers: Optional[List[str]] = None
    phases_ms: Optional[dict] = None  # wait_config/consensus/notify/update
    duration_ms: Optional[float] = None
    progress: Optional[int] = None
    checkpoint_version: Optional[int] = None
    detached: bool = False
    detail: Optional[dict] = None
    # delta-scrape cursor (ISSUE 18): `seq` is the record's stable
    # identity in this process's log (dedupe key for aggregator-side
    # caches); `useq` re-stamps on annotate_last so a `?since=` scrape
    # re-ships records whose late-known fields changed
    seq: Optional[int] = None
    useq: Optional[int] = None

    def to_json(self) -> dict:
        return {
            k: v
            for k, v in dataclasses.asdict(self).items()
            if v is not None and v != ""
        }


_lock = threading.Lock()
_records: List[AuditRecord] = []
_seq = 0  # identity space (stamped once per record)
_useq = 0  # update-cursor space (re-stamped on annotate)


def _stamp_locked(rec: AuditRecord) -> None:
    global _seq, _useq
    _seq += 1
    _useq += 1
    rec.seq = _seq
    rec.useq = _useq


def _metrics_hooks(rec: AuditRecord) -> None:
    if rec.kind == "resize":
        metrics.counter(
            "kungfu_resize_total",
            "Elastic membership changes seen by this process",
            ("trigger",),
        ).labels(rec.trigger or "unknown").inc()
        if rec.duration_ms is not None:
            metrics.histogram(
                "kungfu_resize_duration_seconds",
                "End-to-end resize latency (consensus+notify+update)",
                buckets=RESIZE_BUCKETS,
            ).observe(rec.duration_ms / 1e3)
    elif rec.kind == "strategy_switch":
        metrics.counter(
            "kungfu_strategy_switch_total",
            "Adaptive collective strategy switches",
        ).inc()
    tracing.instant(
        f"audit.{rec.kind}",
        trigger=rec.trigger,
        old_size=rec.old_size,
        new_size=rec.new_size,
        version=rec.cluster_version,
    )


def record_resize(
    *,
    peer: str = "",
    cluster_version: Optional[int] = None,
    trigger: str = "",
    old_peers=None,
    new_peers=None,
    phases_ms: Optional[dict] = None,
    progress: Optional[int] = None,
    checkpoint_version: Optional[int] = None,
    detached: bool = False,
) -> AuditRecord:
    """Append one membership-change record (called by Peer._propose)."""
    old_list = [str(p) for p in old_peers] if old_peers is not None else None
    new_list = [str(p) for p in new_peers] if new_peers is not None else None
    duration = None
    if phases_ms:
        # duration = the resize WORK (consensus+notify+update). The
        # config-server wait is recorded in phases_ms but excluded here:
        # it measures how long the cluster idled before agreeing, and a
        # retrying server blip would inflate a ~100ms resize to 15s+
        duration = round(
            sum(
                float(v)
                for k, v in phases_ms.items()
                if not k.startswith("wait")
            ),
            3,
        )
    rec = AuditRecord(
        kind="resize",
        wall_time=time.time(),
        peer=str(peer),
        cluster_version=cluster_version,
        trigger=trigger,
        old_size=len(old_list) if old_list is not None else None,
        new_size=len(new_list) if new_list is not None else None,
        old_peers=old_list,
        new_peers=new_list,
        phases_ms=dict(phases_ms) if phases_ms else None,
        duration_ms=duration,
        progress=progress,
        checkpoint_version=checkpoint_version,
        detached=detached,
    )
    with _lock:
        _stamp_locked(rec)
        _records.append(rec)
        del _records[:-MAX_RECORDS]
    _metrics_hooks(rec)
    return rec


def record_event(kind: str, *, peer: str = "", trigger: str = "", **detail) -> AuditRecord:
    """Append a non-resize audit event (e.g. a strategy switch)."""
    rec = AuditRecord(
        kind=kind,
        wall_time=time.time(),
        peer=str(peer),
        trigger=trigger,
        detail={k: v for k, v in detail.items() if v is not None} or None,
    )
    with _lock:
        _stamp_locked(rec)
        _records.append(rec)
        del _records[:-MAX_RECORDS]
    _metrics_hooks(rec)
    return rec


def annotate_last(kind: str = "resize", peer: str = "", **fields) -> bool:
    """Attach late-known fields (progress, checkpoint_version) to the most
    recent record of `kind` (optionally for a specific peer). The resize
    itself is recorded deep in the peer protocol; the elastic driver
    learns progress only afterwards."""
    with _lock:
        for rec in reversed(_records):
            if rec.kind != kind:
                continue
            if peer and rec.peer != str(peer):
                continue
            for k, v in fields.items():
                if hasattr(rec, k):
                    setattr(rec, k, v)
                else:
                    rec.detail = dict(rec.detail or {})
                    rec.detail[k] = v
            # the record changed: move it past every cursor that
            # already shipped it, keeping its stable identity (seq)
            global _useq
            _useq += 1
            rec.useq = _useq
            return True
    return False


def records(
    kind: Optional[str] = None, peer: str = "",
    since: Optional[int] = None,
) -> List[AuditRecord]:
    with _lock:
        out = list(_records)
    if kind:
        out = [r for r in out if r.kind == kind]
    if peer:
        out = [r for r in out if r.peer == str(peer)]
    if since is not None:
        out = [r for r in out if (r.useq or 0) > since]
    return out


def next_since() -> int:
    """The current delta-scrape cursor: passing this as ``since`` to a
    later :func:`records`/:func:`to_json` ships only records created or
    annotated after this call."""
    with _lock:
        return _useq


def clear() -> None:
    with _lock:
        _records.clear()


def to_json(since: Optional[int] = None) -> List[dict]:
    return [r.to_json() for r in records(since=since)]


def to_jsonl() -> str:
    return "\n".join(json.dumps(r) for r in to_json()) + ("\n" if _records else "")
