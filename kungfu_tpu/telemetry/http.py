"""Per-worker telemetry HTTP endpoint: /metrics + /trace + /audit +
/steptrace.

One server per worker replaces the bespoke /metrics-only server that
used to live in monitor/net.py (parity: the reference peer's
port+10000 monitoring server, srcs/go/monitor/server.go — extended to
serve the whole telemetry subsystem):

- ``/metrics``  Prometheus text exposition of the process registry
  (plus attached renderers, e.g. the net monitor's windowed rates);
- ``/trace``    Chrome-trace JSON of the span ring buffer
  (load in chrome://tracing or ui.perfetto.dev);
- ``/audit``    the resize/strategy audit log as JSON;
- ``/steptrace`` the step plane's recent per-step timelines (ISSUE 13)
  with the perf-clock anchors the cluster merge aligns on;
- ``/decisions`` the decision ledger's adaptation records (ISSUE 15)
  with the same perf-clock anchors for the cluster merge;
- ``/resources`` the resource attribution plane's per-bucket CPU
  accounting + optional profiler aggregation (ISSUE 16), same anchors;
- ``/memory`` the memory attribution plane's per-bucket byte
  accounting + headroom forecast (ISSUE 17), same anchors.

Shutdown is clean: ``stop()`` both shuts the serve loop down AND closes
the listening socket, so a stopped peer never leaks its telemetry port
(the old MetricsServer left the socket open until GC).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from kungfu_tpu.telemetry import audit, metrics, tracing

# every response carries this process's monotonic clock (perf_counter
# microseconds — the span tracer's timebase) so a scraper can estimate
# the clock offset NTP-style from its request round trip and merge
# traces from many workers onto one timeline
CLOCK_HEADER = "X-KF-Perf-Now-Us"
WALL_HEADER = "X-KF-Wall-Time-S"


def _steptrace_doc() -> dict:
    # lazy: most processes serving /metrics never record a step, and the
    # store's knobs should resolve at first USE, not server construction
    from kungfu_tpu.telemetry import steptrace

    return steptrace.get_store().export()


def _decisions_doc() -> dict:
    # lazy for the same reason: the ledger's knobs resolve at first use
    from kungfu_tpu.telemetry import decisions

    return decisions.get_ledger().export()


def _resources_doc() -> dict:
    # lazy for the same reason: the plane's knobs resolve at first use
    from kungfu_tpu.telemetry import resource

    return resource.get_plane().export()


def _memory_doc() -> dict:
    # lazy for the same reason: the plane's knobs resolve at first use
    from kungfu_tpu.telemetry import memory

    return memory.get_plane().export()


class TelemetryServer:
    def __init__(
        self,
        port: int,
        host: str = "0.0.0.0",
        registry: Optional[metrics.Registry] = None,
        extra_routes: Optional[Dict[str, Callable[[], "tuple[str, str]"]]] = None,
    ):
        reg = registry or metrics.get_registry()

        def _metrics_page() -> "tuple[str, str]":
            # self-health gauges (RSS/fds/threads/uptime) are sampled on
            # demand: every scrape refreshes them, so OOM/fd-leak
            # postmortems get a trend line without a sampler thread
            metrics.update_process_health(reg)
            return reg.render(), "text/plain; version=0.0.4"

        routes: Dict[str, Callable[[], "tuple[str, str]"]] = {
            "/metrics": _metrics_page,
            "/trace": lambda: (
                tracing.chrome_trace_json(),
                "application/json",
            ),
            "/audit": lambda: (
                json.dumps(audit.to_json()),
                "application/json",
            ),
            "/steptrace": lambda: (
                json.dumps(_steptrace_doc()),
                "application/json",
            ),
            "/decisions": lambda: (
                json.dumps(_decisions_doc()),
                "application/json",
            ),
            "/resources": lambda: (
                json.dumps(_resources_doc()),
                "application/json",
            ),
            "/memory": lambda: (
                json.dumps(_memory_doc()),
                "application/json",
            ),
        }
        if extra_routes:
            routes.update(extra_routes)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(inner):
                from urllib.parse import urlsplit

                # query/fragment never select the route: a scraper's
                # cache-buster (/metrics?t=...) must hit /metrics
                path = urlsplit(inner.path).path.rstrip("/")
                route = routes.get(path or "/metrics")
                if route is None:
                    inner.send_response(404)
                    inner.end_headers()
                    return
                try:
                    body_s, ctype = route()
                except Exception as e:  # noqa: BLE001 - a broken view is a 500, not a crash
                    inner.send_response(500)
                    inner.end_headers()
                    inner.wfile.write(str(e).encode())
                    return
                body = body_s.encode()
                inner.send_response(200)
                inner.send_header("Content-Type", ctype)
                inner.send_header("Content-Length", str(len(body)))
                inner.send_header(CLOCK_HEADER, repr(time.perf_counter() * 1e6))
                inner.send_header(WALL_HEADER, repr(time.time()))
                inner.end_headers()
                inner.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._stopped = threading.Event()
        self._started = False

    def start(self) -> None:
        self._started = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self._started:
            # shutdown() handshakes with serve_forever; calling it on a
            # never-started server blocks forever
            self.httpd.shutdown()
        self.httpd.server_close()  # release the port NOW, not at GC
