"""Per-worker telemetry HTTP endpoint: /metrics + /trace + /audit +
/steptrace.

One server per worker replaces the bespoke /metrics-only server that
used to live in monitor/net.py (parity: the reference peer's
port+10000 monitoring server, srcs/go/monitor/server.go — extended to
serve the whole telemetry subsystem):

- ``/metrics``  Prometheus text exposition of the process registry
  (plus attached renderers, e.g. the net monitor's windowed rates);
- ``/trace``    Chrome-trace JSON of the span ring buffer
  (load in chrome://tracing or ui.perfetto.dev);
- ``/audit``    the resize/strategy audit log as JSON;
- ``/steptrace`` the step plane's recent per-step timelines (ISSUE 13)
  with the perf-clock anchors the cluster merge aligns on;
- ``/decisions`` the decision ledger's adaptation records (ISSUE 15)
  with the same perf-clock anchors for the cluster merge;
- ``/resources`` the resource attribution plane's per-bucket CPU
  accounting + optional profiler aggregation (ISSUE 16), same anchors;
- ``/memory`` the memory attribution plane's per-bucket byte
  accounting + headroom forecast (ISSUE 17), same anchors;
- ``/host/telemetry`` the per-host sub-aggregator digest (ISSUE 18):
  a worker elected host head pre-merges its local siblings' endpoints
  into one document so the root aggregator sweeps O(hosts), not O(k);
  non-elected workers answer ``{"enabled": false}``.

Ring-backed endpoints (``/steptrace``, ``/decisions``, ``/audit``)
take a ``?since=<seq>`` delta cursor (ISSUE 18): re-scrapes ship only
records created or mutated past the cursor, with the next cursor in
the document (``next_since``; the audit list carries per-record
``useq`` instead).

Shutdown is clean: ``stop()`` both shuts the serve loop down AND closes
the listening socket, so a stopped peer never leaks its telemetry port
(the old MetricsServer left the socket open until GC).
"""

from __future__ import annotations

import inspect
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from kungfu_tpu.telemetry import audit, metrics, tracing

# every response carries this process's monotonic clock (perf_counter
# microseconds — the span tracer's timebase) so a scraper can estimate
# the clock offset NTP-style from its request round trip and merge
# traces from many workers onto one timeline
CLOCK_HEADER = "X-KF-Perf-Now-Us"
WALL_HEADER = "X-KF-Wall-Time-S"


def _since(query: Dict[str, str]) -> Optional[int]:
    """Parse the delta-scrape cursor (ISSUE 18) off a route's query
    dict; a malformed value reads as 'no cursor' (full document) — a
    scraper must never get a 500 for a bad cursor."""
    raw = query.get("since")
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _steptrace_doc(since: Optional[int] = None) -> dict:
    # lazy: most processes serving /metrics never record a step, and the
    # store's knobs should resolve at first USE, not server construction
    from kungfu_tpu.telemetry import steptrace

    return steptrace.get_store().export(since=since)


def _decisions_doc(since: Optional[int] = None) -> dict:
    # lazy for the same reason: the ledger's knobs resolve at first use
    from kungfu_tpu.telemetry import decisions

    return decisions.get_ledger().export(since=since)


def _resources_doc() -> dict:
    # lazy for the same reason: the plane's knobs resolve at first use
    from kungfu_tpu.telemetry import resource

    return resource.get_plane().export()


def _memory_doc() -> dict:
    # lazy for the same reason: the plane's knobs resolve at first use
    from kungfu_tpu.telemetry import memory

    return memory.get_plane().export()


def _host_doc() -> dict:
    # lazy: only a worker elected host sub-aggregator (ISSUE 18) serves
    # a real digest; everyone else answers {"enabled": false} so the
    # root can probe the role cheaply
    from kungfu_tpu.telemetry import cluster

    return cluster.host_digest_doc()


def _adapt_route(fn: Callable) -> Callable[[Dict[str, str]], "tuple[str, str]"]:
    """Make a route callable accept the parsed query dict. Routes that
    already take one positional parameter get it; zero-arg callables
    (the historical extra_routes contract) are wrapped — back-compat
    for embedders registering plain thunks."""
    try:
        params = [
            p for p in inspect.signature(fn).parameters.values()
            if p.default is inspect.Parameter.empty
            and p.kind in (
                inspect.Parameter.POSITIONAL_ONLY,
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
            )
        ]
        takes_query = len(params) >= 1
    except (TypeError, ValueError):
        takes_query = False
    if takes_query:
        return fn
    return lambda query, _fn=fn: _fn()


class TelemetryServer:
    def __init__(
        self,
        port: int,
        host: str = "0.0.0.0",
        registry: Optional[metrics.Registry] = None,
        extra_routes: Optional[Dict[str, Callable[[], "tuple[str, str]"]]] = None,
    ):
        reg = registry or metrics.get_registry()

        def _metrics_page() -> "tuple[str, str]":
            # self-health gauges (RSS/fds/threads/uptime) are sampled on
            # demand: every scrape refreshes them, so OOM/fd-leak
            # postmortems get a trend line without a sampler thread
            metrics.update_process_health(reg)
            return reg.render(), "text/plain; version=0.0.4"

        # ring-backed endpoints take the ?since=<seq> delta cursor
        # (ISSUE 18); the rest ignore their query dict
        routes: Dict[str, Callable[[], "tuple[str, str]"]] = {
            "/metrics": _metrics_page,
            "/trace": lambda: (
                tracing.chrome_trace_json(),
                "application/json",
            ),
            "/audit": lambda q: (
                json.dumps(audit.to_json(since=_since(q))),
                "application/json",
            ),
            "/steptrace": lambda q: (
                json.dumps(_steptrace_doc(_since(q))),
                "application/json",
            ),
            "/decisions": lambda q: (
                json.dumps(_decisions_doc(_since(q))),
                "application/json",
            ),
            "/resources": lambda: (
                json.dumps(_resources_doc()),
                "application/json",
            ),
            "/memory": lambda: (
                json.dumps(_memory_doc()),
                "application/json",
            ),
            "/host/telemetry": lambda: (
                json.dumps(_host_doc()),
                "application/json",
            ),
        }
        if extra_routes:
            routes.update(extra_routes)
        routes = {path: _adapt_route(fn) for path, fn in routes.items()}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(inner):
                from urllib.parse import parse_qsl, urlsplit

                # query/fragment never select the route: a scraper's
                # cache-buster (/metrics?t=...) must hit /metrics
                split = urlsplit(inner.path)
                path = split.path.rstrip("/")
                route = routes.get(path or "/metrics")
                if route is None:
                    inner.send_response(404)
                    inner.end_headers()
                    return
                try:
                    query = dict(parse_qsl(split.query))
                    body_s, ctype = route(query)
                except Exception as e:  # noqa: BLE001 - a broken view is a 500, not a crash
                    inner.send_response(500)
                    inner.end_headers()
                    inner.wfile.write(str(e).encode())
                    return
                body = body_s.encode()
                inner.send_response(200)
                inner.send_header("Content-Type", ctype)
                inner.send_header("Content-Length", str(len(body)))
                inner.send_header(CLOCK_HEADER, repr(time.perf_counter() * 1e6))
                inner.send_header(WALL_HEADER, repr(time.time()))
                inner.end_headers()
                inner.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._stopped = threading.Event()
        self._started = False

    def start(self) -> None:
        self._started = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self._started:
            # shutdown() handshakes with serve_forever; calling it on a
            # never-started server blocks forever
            self.httpd.shutdown()
        self.httpd.server_close()  # release the port NOW, not at GC
