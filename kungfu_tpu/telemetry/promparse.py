"""Prometheus text-exposition parsing + federation merge.

The cluster aggregator scrapes every worker's ``/metrics`` page and
re-serves them as ONE exposition with a ``peer`` label identifying the
scraped worker (ISSUE 2 tentpole). That needs a small parser for the
text format our own :mod:`~kungfu_tpu.telemetry.metrics` registry emits
(plus anything renderer blocks append): sample lines with optional
escaped label values, ``# HELP``/``# TYPE`` metadata, ``+Inf``/``NaN``
values.

Federation semantics follow Prometheus itself:

- the injected target label is ``peer``;
- a sample that ALREADY carries a ``peer`` label (e.g. the worker's
  per-remote-peer egress counters) keeps its value under
  ``exported_peer`` — exactly what a Prometheus server does on a label
  collision with honor_labels off;
- ``# HELP``/``# TYPE`` metadata is emitted once per family and all
  samples of a family are regrouped to be consecutive (the text format
  forbids interleaving).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

# one source of truth for text-format rendering rules: re-rendering a
# scraped page must produce exactly what the worker's registry emits
from kungfu_tpu.telemetry.metrics import _escape_label as _escape
from kungfu_tpu.telemetry.metrics import _fmt_value


class Sample(NamedTuple):
    name: str
    labels: Tuple[Tuple[str, str], ...]  # insertion-ordered (k, v) pairs
    value: float

    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)


def _parse_value(raw: str) -> float:
    low = raw.lower()
    if low in ("+inf", "inf"):
        return math.inf
    if low == "-inf":
        return -math.inf
    if low == "nan":
        return math.nan
    return float(raw)


def _parse_labels(body: str) -> List[Tuple[str, str]]:
    """Parse the inside of a ``{...}`` label body, honouring ``\\"``,
    ``\\\\`` and ``\\n`` escapes in values."""
    out: List[Tuple[str, str]] = []
    i, n = 0, len(body)
    while i < n:
        while i < n and body[i] in ", ":
            i += 1
        if i >= n:
            break
        eq = body.index("=", i)
        name = body[i:eq].strip()
        i = eq + 1
        if i >= n or body[i] != '"':
            raise ValueError(f"unquoted label value in {body!r}")
        i += 1
        chars: List[str] = []
        while i < n:
            c = body[i]
            if c == "\\" and i + 1 < n:
                nxt = body[i + 1]
                chars.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            chars.append(c)
            i += 1
        out.append((name, "".join(chars)))
    return out


def parse_line(line: str) -> Optional[Sample]:
    """One sample line -> Sample; None for comments/blank/garbage."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if "{" in line:
        brace = line.index("{")
        name = line[:brace]
        close = line.rindex("}")
        labels = _parse_labels(line[brace + 1 : close])
        rest = line[close + 1 :].split()
    else:
        parts = line.split()
        if len(parts) < 2:
            return None
        name, rest = parts[0], parts[1:]
        labels = []
    if not rest:
        return None
    try:
        value = _parse_value(rest[0])  # rest[1], if any, is a timestamp
    except ValueError:
        return None
    return Sample(name, tuple(labels), value)


def parse_text(text: str) -> List[Sample]:
    out = []
    for line in text.splitlines():
        try:
            s = parse_line(line)
        except ValueError:
            s = None
        if s is not None:
            out.append(s)
    return out


def sample_value(
    samples: Iterable[Sample], name: str, **want_labels
) -> Optional[float]:
    """First matching sample's value (labels compared as a subset)."""
    want = {k: str(v) for k, v in want_labels.items()}
    for s in samples:
        if s.name != name:
            continue
        d = s.labels_dict()
        if all(d.get(k) == v for k, v in want.items()):
            return s.value
    return None


def _fmt(v: float) -> str:
    # the registry never renders NaN (counters/gauges hold real floats),
    # but a scraped page may carry one through a merge
    return "NaN" if math.isnan(v) else _fmt_value(v)


def render_sample(s: Sample) -> str:
    if s.labels:
        inner = ",".join(f'{k}="{_escape(v)}"' for k, v in s.labels)
        return f"{s.name}{{{inner}}} {_fmt(s.value)}"
    return f"{s.name} {_fmt(s.value)}"


def _family_of(name: str) -> str:
    """Histogram/summary series names map back to their family."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def _meta_of(text: str) -> Dict[str, Dict[str, str]]:
    """family -> {"help": ..., "type": ...} from # HELP / # TYPE lines."""
    meta: Dict[str, Dict[str, str]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("#"):
            continue
        parts = line.split(None, 3)
        if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
            fam = parts[2]
            meta.setdefault(fam, {})[parts[1].lower()] = (
                parts[3] if len(parts) > 3 else ""
            )
    return meta


def inject_label(s: Sample, label: str, value: str) -> Sample:
    """Add the federation target label; an existing label of the same
    name is preserved as ``exported_<name>`` (Prometheus collision rule)."""
    labels = []
    for k, v in s.labels:
        labels.append((f"exported_{k}" if k == label else k, v))
    return Sample(s.name, ((label, value),) + tuple(labels), s.value)


def merge_expositions(pages: List[Tuple[Optional[str], str]]) -> str:
    """Federate [(peer_label, exposition_text), ...] into one page.

    Every sample gains ``peer="<label>"``; families are regrouped so all
    samples of a family are consecutive with one HELP/TYPE header (first
    scrape's metadata wins). A page with label ``None`` passes through
    without injection — the aggregator's own registry (whose
    ``kungfu_cluster_*`` gauges already carry the right peer labels)
    rides along that way.
    """
    meta: Dict[str, Dict[str, str]] = {}
    families: Dict[str, List[Sample]] = {}
    order: List[str] = []
    for peer_label, text in pages:
        for fam, m in _meta_of(text).items():
            meta.setdefault(fam, m)
        for s in parse_text(text):
            fam = _family_of(s.name)
            if fam not in families:
                families[fam] = []
                order.append(fam)
            families[fam].append(
                s if peer_label is None
                else inject_label(s, "peer", peer_label)
            )
    lines: List[str] = []
    for fam in order:
        m = meta.get(fam, {})
        if m.get("help"):
            lines.append(f"# HELP {fam} {m['help']}")
        if m.get("type"):
            lines.append(f"# TYPE {fam} {m['type']}")
        lines.extend(render_sample(s) for s in families[fam])
    return "\n".join(lines) + ("\n" if lines else "")
