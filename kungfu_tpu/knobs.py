"""Central registry of every ``KF_*`` environment knob (ISSUE 7).

One module owns the whole configuration surface: each knob is declared
exactly once with its name, default, parser and doc string, and every
read in the package goes through :func:`get`/:func:`raw`.  Before this
registry the 48 knobs were scattered across ~20 modules, each with its
own ad-hoc ``os.environ.get(...) or default`` idiom — adding a knob
meant inventing parsing semantics, and nothing kept docs/collectives.md
and docs/telemetry.md env tables honest.  Now:

- ``kfcheck`` (devtools) statically enforces that any exact ``KF_*``
  string literal in the package is declared here (rule KF100) and that
  no module reads ``os.environ`` with a ``KF_*`` key directly (KF101);
- ``docs/knobs.md`` is *generated* from this registry
  (``python -m kungfu_tpu.devtools.kfcheck --write-knobs-doc``) and
  kfcheck fails when it goes stale (KF102).

Semantics, shared by every knob: an UNSET or empty-string variable
resolves to the declared default; a set value is parsed by the knob's
parser.  A malformed value falls back to the default with a logged
warning, except for ``strict`` knobs (cluster-agreed engine knobs like
``KF_CONFIG_ALGO``) where a typo must fail fast rather than silently
diverge the cluster — those raise ``ValueError``.

This module must stay import-light (no kungfu_tpu imports at module
level): the logger itself reads knobs from here.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional

__all__ = [
    "Knob", "declared", "names", "get", "raw", "is_set", "render_doc",
]


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    default: str  # env-level default (the string an unset var resolves to)
    parse: Callable[[str], object]
    doc: str
    section: str
    kind: str = "str"  # human-readable type for the generated doc
    default_doc: str = ""  # display override when the default is dynamic
    strict: bool = False  # parse errors raise instead of warn-and-default
    # cluster-agreed: the resolved value decides rendezvous names, message
    # sizes or walk dataflow, so it MUST be identical fleet-wide and MUST
    # appear in HostSession.engine_knobs()'s consensus tuple. This flag is
    # the single source of truth for that contract — kfcheck rule KF701
    # cross-checks it against the consensus tuple, so adding a
    # cluster-agreed knob without consensus coverage is a build failure.
    consensus: bool = False


_REGISTRY: Dict[str, Knob] = {}
_SECTIONS: List[str] = []  # insertion order for doc rendering


def _knob(name, default, parse, doc, *, section, kind, default_doc="",
          strict=False, consensus=False) -> None:
    if name in _REGISTRY:
        raise ValueError(f"knob {name} declared twice")
    if section not in _SECTIONS:
        _SECTIONS.append(section)
    _REGISTRY[name] = Knob(
        name=name, default=default, parse=parse, doc=doc, section=section,
        kind=kind, default_doc=default_doc, strict=strict,
        consensus=consensus,
    )


# --- parsers -----------------------------------------------------------

_TRUTHY = frozenset({"1", "true", "yes", "on", "y", "enabled"})


def _bool(s: str) -> bool:
    return str(s).strip().lower() in _TRUTHY


def _int(s: str) -> int:
    return int(str(s).strip())


def _float(s: str) -> float:
    return float(str(s).strip())


def _int_bytes(s: str) -> int:
    """Integer byte count; accepts float notation ("8e6")."""
    return int(float(str(s).strip()))


def _str(s: str) -> str:
    return str(s)


def _stripped(s: str) -> str:
    return str(s).strip()


def _csv(s: str) -> tuple:
    return tuple(p.strip() for p in str(s).split(",") if p.strip())


def _opt_int(s: str):
    s = str(s).strip()
    return int(s) if s else None


def _choice(name: str, choices, *, empty_as: Optional[str] = None):
    """Lowercased membership check; mirrors the engine's historical
    fail-fast messages ("KF_CONFIG_ALGO must be one of [...], got ...")."""
    allowed = tuple(choices)

    def parse(s: str) -> str:
        raw = str(s).strip().lower()
        if raw == "" and empty_as is not None:
            return empty_as
        if raw not in allowed:
            shown = sorted(c for c in allowed if c)
            raise ValueError(
                f"{name} must be one of {shown}, got {raw!r}"
            )
        return raw

    return parse


# --- declarations ------------------------------------------------------
# Section order is the order of docs/knobs.md.

_SEC_CONTRACT = "Worker contract (set by the runner)"
_knob("KF_SELF_SPEC", "", _str,
      "This worker's identity as `host:port`. Unset means single-process "
      "fallback: the worker becomes a one-peer cluster of itself.",
      section=_SEC_CONTRACT, kind="str")
_knob("KF_INIT_PEERS", "", _str,
      "Comma-separated initial peer list (`host:port,...`). Defaults to "
      "`KF_SELF_SPEC` (a cluster of one).",
      section=_SEC_CONTRACT, kind="str", default_doc="KF_SELF_SPEC")
_knob("KF_INIT_RUNNERS", "", _str,
      "Comma-separated runner (supervisor) endpoints.",
      section=_SEC_CONTRACT, kind="str")
_knob("KF_PARENT_ID", "", _str,
      "The spawning runner's `host:port`, empty for orphan workers.",
      section=_SEC_CONTRACT, kind="str")
_knob("KF_INIT_CLUSTER_VERSION", "0", _int,
      "Cluster version the worker starts at (bumped by every resize).",
      section=_SEC_CONTRACT, kind="int")
_knob("KF_INIT_PROGRESS", "0", _int,
      "Training progress (steps) restored into the elastic state on start.",
      section=_SEC_CONTRACT, kind="int")
_knob("KF_ALLREDUCE_STRATEGY", "BINARY_TREE_STAR", _stripped,
      "Initial collective strategy name (see `base/strategy.py`; "
      "`AUTO` lets `auto_select` pick from the topology).",
      section=_SEC_CONTRACT, kind="str")
_knob("KF_DEVICE_SLOTS", "", _csv,
      "Comma-separated accelerator chip ids this worker may open "
      "(empty = unrestricted). Mirrored into `TPU_VISIBLE_DEVICES`.",
      section=_SEC_CONTRACT, kind="csv")
_knob("KF_SPAWN_TS", "", _str,
      "Unix timestamp the runner spawned this worker at; start() reports "
      "spawn→ready latency from it.",
      section=_SEC_CONTRACT, kind="float-ts")
_knob("KF_LOG_PREFIX", "", _str,
      "Per-worker log prefix (`rank/np`), set by the runner; falls back "
      "to `KF_SELF_SPEC`.",
      section=_SEC_CONTRACT, kind="str")
_knob("KF_RUNNER_PID", "0", _int,
      "PID of the supervising runner (standby activation checks it).",
      section=_SEC_CONTRACT, kind="int")

_SEC_ELASTIC = "Elastic / adaptation"
_knob("KF_CONFIG_SERVER", "", _str,
      "Config-server URL for elastic membership proposals "
      "(empty = static cluster).",
      section=_SEC_ELASTIC, kind="url")
_knob("KF_ELASTIC_MODE", "", _str,
      "Resize style: empty (delta resize in-process) or `reload` "
      "(workers restart on membership change).",
      section=_SEC_ELASTIC, kind="str")
_knob("KF_RECOVER_EPOCH", "", _str,
      "Set by the monitored runner on relaunch: the minimum completed "
      "epoch; checkpoint restore caps at it.",
      section=_SEC_ELASTIC, kind="int")
_knob("KF_MONITOR_ADDR", "", _str,
      "Where `send_heartbeat` POSTs worker heartbeats "
      "(set by the monitored runner).",
      section=_SEC_ELASTIC, kind="host:port")
_knob("KF_CONFIG_ENABLE_MONITORING", "", _bool,
      "Truthy spelling enables the gradient-noise/variance monitor "
      "(also implied by `KF_TELEMETRY=metrics`).",
      section=_SEC_ELASTIC, kind="bool")
_knob("KF_CONFIG_ENABLE_STALL_DETECTION", "", _bool,
      "Truthy spelling logs collectives that exceed their deadline "
      "repeatedly until they complete.",
      section=_SEC_ELASTIC, kind="bool")

_SEC_STANDBY = "Standby pool"
_knob("KF_STANDBY_FIFO", "", _str,
      "Path of the activation FIFO a standby worker blocks on "
      "(`kf-standby` refuses to run without it).",
      section=_SEC_STANDBY, kind="path")
_knob("KF_STANDBY_PRELOAD", "", _csv,
      "Extra modules a standby imports before parking, so activation "
      "skips their import cost.",
      section=_SEC_STANDBY, kind="csv")
_knob("KF_ACTIVATED_TS", "", _str,
      "Monotonic timestamp stamped by the standby pool at activation "
      "(activation-latency accounting).",
      section=_SEC_STANDBY, kind="float-ts")

_SEC_LOG = "Logging"
_knob("KF_LOG_LEVEL", "", _stripped,
      "Log level (DEBUG/INFO/WARN/ERROR). Falls back to the reference's "
      "`KF_CONFIG_LOG_LEVEL`.",
      section=_SEC_LOG, kind="level", default_doc="KF_CONFIG_LOG_LEVEL")
_knob("KF_CONFIG_LOG_LEVEL", "INFO", _stripped,
      "Legacy (reference-parity) log level, used when `KF_LOG_LEVEL` "
      "is unset.",
      section=_SEC_LOG, kind="level")

_SEC_TELEMETRY = "Telemetry"
_knob("KF_TELEMETRY", "", _stripped,
      "Telemetry feature selection: comma list of `metrics`, `trace`, "
      "`audit`; `all`/any truthy value enables everything.",
      section=_SEC_TELEMETRY, kind="csv")
_knob("KF_TELEMETRY_DIR", "", _str,
      "Per-run telemetry directory (flight-recorder journals, "
      "postmortems). kfrun mints one under /tmp/kungfu-telemetry and "
      "injects it into every worker.",
      section=_SEC_TELEMETRY, kind="path")
_knob("KF_TELEMETRY_MAX_SERIES", "512", _int,
      "Cardinality guard: max distinct label-sets per metric family "
      "(0 disables). Past the cap, lookups get a shared detached child "
      "and `kungfu_telemetry_dropped_series_total` counts the drops.",
      section=_SEC_TELEMETRY, kind="int")
_knob("KF_TELEMETRY_SPAN_SAMPLE", "1.0", _float,
      "Fraction of collective walks whose per-step spans are emitted, "
      "in [0,1]; deterministic (not random) sampling.",
      section=_SEC_TELEMETRY, kind="float")
_knob("KF_TRACE_BUFFER", "8192", _int,
      "Span ring-buffer capacity (events) for the /trace view.",
      section=_SEC_TELEMETRY, kind="int")
_knob("KF_STEP_TIMELINE_KEEP", "16", _int,
      "Step-trace ring size: how many recent per-step critical-path "
      "timelines each worker keeps (served at /steptrace, merged into "
      "/cluster/steps, journaled by the flight recorder). 0 disables "
      "the step plane entirely.",
      section=_SEC_TELEMETRY, kind="int")

_SEC_DECISION = "Decision ledger"
_knob("KF_DECISION_KEEP", "64", _int,
      "Decision-ledger ring size: how many adaptation decisions "
      "(strategy/wire votes, re-plans, mode flips, resizes) each worker "
      "keeps with their measured outcomes (served at /decisions, merged "
      "into /cluster/decisions, journaled by the flight recorder). "
      "0 disables the ledger entirely.",
      section=_SEC_DECISION, kind="int")
_knob("KF_DECISION_WINDOW", "8", _int,
      "Paired measurement window: how many step durations form the "
      "baseline captured at an adaptation and the post-settle window "
      "that closes it with a realized gain (minimum 2).",
      section=_SEC_DECISION, kind="int")
_knob("KF_DECISION_SETTLE", "2", _int,
      "Steps skipped after an adaptation before its outcome window "
      "starts measuring (pools/caches/estimators re-warm under the new "
      "configuration; counting those steps would bias every realized "
      "gain low).",
      section=_SEC_DECISION, kind="int")
_knob("KF_DECISION_REGRESS_RATIO", "0.9", _float,
      "Regression floor: a closed decision whose realized gain stays at "
      "or under this ratio (baseline step time / post-flip step time) "
      "for KF_DECISION_PATIENCE consecutive windows fires an "
      "`adaptation_regressed` audit event — the rollback signal.",
      section=_SEC_DECISION, kind="float")
_knob("KF_DECISION_PATIENCE", "2", _int,
      "Regression-watchdog patience: consecutive below-floor "
      "measurement windows (the closing window counts as the first) "
      "before `adaptation_regressed` fires.",
      section=_SEC_DECISION, kind="int")

_SEC_RESOURCE = "Resource attribution"
_knob("KF_RESOURCE_INTERVAL", "2.0", _float,
      "Minimum seconds between per-thread CPU accounting sweeps "
      "(/proc/self/task deltas). Sweeps are on-demand — triggered by "
      "/resources scrapes, policy signal refreshes and flight "
      "snapshots — so this throttles, it does not schedule.",
      section=_SEC_RESOURCE, kind="float")
_knob("KF_RESOURCE_SAMPLE_HZ", "0", _float,
      "Sampling-profiler rate (stack samples per second) splitting the "
      "main thread into train-compute vs blocked-in-engine with "
      "module-prefix aggregation. 0 (the default) means the sampler "
      "thread is never started and allocates nothing.",
      section=_SEC_RESOURCE, kind="float")
_knob("KF_RESOURCE_KEEP", "512", _int,
      "Sampling-profiler ring size: how many recent stack samples the "
      "module-prefix aggregation is computed over.",
      section=_SEC_RESOURCE, kind="int")

_SEC_MEMORY = "Memory attribution"
_knob("KF_MEMORY_INTERVAL", "2.0", _float,
      "Minimum seconds between memory accounting sweeps (RSS sample, "
      "registered byte accountants, major-fault delta). Sweeps are "
      "on-demand — triggered by /memory scrapes, policy signal "
      "refreshes and flight snapshots — so this throttles, it does "
      "not schedule.",
      section=_SEC_MEMORY, kind="float")
_knob("KF_MEMORY_WINDOWS", "6", _int,
      "Leak-watchdog patience: consecutive sweeps a bucket's tracked "
      "bytes must grow strictly before the one-shot "
      "`memory_leak_suspect` audit event fires for that bucket.",
      section=_SEC_MEMORY, kind="int")
_knob("KF_MEMORY_WARMUP", "30", _float,
      "Leak-watchdog arming delay in seconds: sweeps inside this "
      "window after the plane starts never accumulate growth streaks. "
      "A booting process's RSS grows monotonically (imports, first "
      "allocations) and a real leak persists long past any boot "
      "transient — without the grace, a slow boot under load fakes a "
      "`memory_leak_suspect` on a clean worker.",
      section=_SEC_MEMORY, kind="float")
_knob("KF_MEMORY_TREND", "64", _int,
      "RSS trend window: how many recent (time, rss) sweep samples the "
      "linear headroom forecast is fitted over.",
      section=_SEC_MEMORY, kind="int")
_knob("KF_MEMORY_OOM_MARGIN", "0.05", _float,
      "Postmortem OOM verdict margin: a dead worker whose final RSS "
      "was within this fraction of its memory limit is marked "
      "`oom_suspected` in the harvested postmortem.",
      section=_SEC_MEMORY, kind="float")
_knob("KF_MEMORY_LIMIT", "0", _int_bytes,
      "Override for the effective memory limit in bytes (accepts "
      "float notation, e.g. `2e9`). 0 (the default) means auto: "
      "cgroup v2 `memory.max`, cgroup v1 hierarchical fallback, then "
      "physical RAM. Set it to rehearse OOM headroom behaviour under "
      "a fake tight limit.",
      section=_SEC_MEMORY, kind="int", default_doc="0 (auto)")

_SEC_FLIGHT = "Flight recorder"
_knob("KF_FLIGHT", "", _bool,
      "Explicit on/off override for the flight recorder; unset means "
      "auto (on when `KF_TELEMETRY_DIR` is plumbed or any telemetry "
      "feature is enabled).",
      section=_SEC_FLIGHT, kind="bool", default_doc="auto")
_knob("KF_FLIGHT_INTERVAL", "5.0", _float,
      "Seconds between journal snapshots (a SIGKILL loses at most this "
      "much history).",
      section=_SEC_FLIGHT, kind="float")
_knob("KF_FLIGHT_FSYNC", "", _bool,
      "Truthy forces fsync after every journal frame (crash-safe at the "
      "cost of write latency).",
      section=_SEC_FLIGHT, kind="bool")
_knob("KF_FLIGHT_MAX_BYTES", str(8 * 1024 * 1024), _int_bytes,
      "Journal size bound; past it the journal rotates one generation.",
      section=_SEC_FLIGHT, kind="int")

_SEC_CLUSTER = "Cluster plane (runner-side aggregation)"
_knob("KF_CLUSTER_HEALTH_URL", "", _str,
      "The runner aggregator's debug endpoint base URL, injected into "
      "every worker; workers pull cluster health signals from it and "
      "`info top/links/postmortem` default to it.",
      section=_SEC_CLUSTER, kind="url")
_knob("KF_CLUSTER_SCRAPE_INTERVAL", "5.0", _float,
      "Seconds between the aggregator's scrape sweeps over worker "
      "telemetry endpoints.",
      section=_SEC_CLUSTER, kind="float")
_knob("KF_AGG_HIER_MIN_PEERS", "32", _int,
      "At or above this many scrape targets the aggregator switches to "
      "scale mode: hierarchical per-host fan-in (elected host heads "
      "pre-merge their local workers into one /host/telemetry digest), "
      "sampled link-matrix rotation and delta-cursor scrapes. Below it "
      "the flat exact plane runs — small clusters keep today's "
      "behavior bit-for-bit. 0 disables scale mode entirely.",
      section=_SEC_CLUSTER, kind="int")
_knob("KF_AGG_LINK_ROTATION_SWEEPS", "8", _int,
      "In scale mode, the number of sweeps over which the link-matrix "
      "row rotation covers every peer (each sweep ingests ~k/N rows). "
      "Bounds every edge estimate's staleness at rotation_sweeps x "
      "effective scrape interval.",
      section=_SEC_CLUSTER, kind="int")
_knob("KF_AGG_LINK_TOP_EDGES", "16", _int,
      "In scale mode, the N slowest edges whose source rows are "
      "re-ingested EVERY sweep regardless of rotation — the re-planner "
      "input (min_bw / slowest_edge) can never be sampled out.",
      section=_SEC_CLUSTER, kind="int")
_knob("KF_AGG_LINK_MAX_AGE_S", "60.0", _float,
      "ReplanPolicy refuses to vote for a re-plan while the oldest "
      "sampled link-matrix row is older than this (the lockstep check "
      "still runs; this peer votes no). 0 disables the staleness gate.",
      section=_SEC_CLUSTER, kind="float")
_knob("KF_AGG_DELTA", "",
      _choice("KF_AGG_DELTA", ("", "auto", "on", "off"), empty_as="auto"),
      "Delta scrapes: ship only new/changed records off the ring-backed "
      "worker endpoints (?since= cursors on /steptrace, /decisions, "
      "/audit). `auto` (default) enables them in scale mode only; "
      "`on`/`off` force.",
      section=_SEC_CLUSTER, kind="choice", default_doc="auto")
_knob("KF_AGG_MAX_BACKOFF", "8.0", _float,
      "Upper bound on the aggregator's overload backoff multiplier: "
      "when a sweep overruns the scrape interval the effective interval "
      "doubles (audited `aggregator_overload`) up to interval x this, "
      "and cools back down when sweeps recover.",
      section=_SEC_CLUSTER, kind="float")

_SEC_LINK = "Link observability"
_knob("KF_LINK_BW_MIN_BYTES", str(64 << 10), _int,
      "Sends smaller than this never feed the per-link bandwidth "
      "estimator (control frames measure latency, not bandwidth).",
      section=_SEC_LINK, kind="int")
_knob("KF_LINK_EWMA_ALPHA", "0.2", _float,
      "EWMA smoothing factor for per-link bandwidth/latency estimates.",
      section=_SEC_LINK, kind="float")
_knob("KF_LINK_MAX_PEERS", "256", _int,
      "Max per-destination link estimators kept per worker.",
      section=_SEC_LINK, kind="int")

_SEC_ENGINE = "Collective engine (cluster-agreed)"
_knob("KF_CONFIG_ALGO", "",
      _choice("KF_CONFIG_ALGO", ("", "tree", "segmented", "auto")),
      "Forces the collective algorithm family: `tree` (rank-0 graph "
      "walks), `segmented` (ring reduce-scatter/all-gather), or `auto` "
      "(topology heuristic). Unset: no override — the session keeps its "
      "configured strategy. Cluster-agreed: checked by "
      "`check_knob_consensus` at every session epoch.",
      section=_SEC_ENGINE, kind="choice", strict=True, consensus=True,
      default_doc="(unset: no override)")
_knob("KF_CONFIG_WIRE", "",
      _choice("KF_CONFIG_WIRE", ("off", "bf16", "f16", "auto", "int8", "int4"),
              empty_as="off"),
      "Compressed wire format for f32 allreduce payloads: bf16/f16 "
      "(2-byte, f32 ring accumulation), or block-scaled int8/int4 with "
      "error-feedback residuals (`KF_WIRE_BLOCK` elements per scale); "
      "`auto` resolves to bf16 for eligible payloads. Cluster-agreed.",
      section=_SEC_ENGINE, kind="choice", strict=True, consensus=True,
      default_doc="off")
_knob("KF_CONFIG_WIRE_MIN_BYTES", str(64 << 10), _int,
      "Payloads below this bypass the wire codec (keeps probe-sized "
      "monitored traffic exact). Cluster-agreed.",
      section=_SEC_ENGINE, kind="int", consensus=True)
_knob("KF_WIRE_BLOCK", "16", _int,
      "Elements per absmax scale block of the int8/int4 wire codec "
      "(one f32 scale per block: smaller blocks track outliers, bigger "
      "blocks amortize the 4-byte scale). Cluster-agreed: it decides "
      "the byte length of every quantized message.",
      section=_SEC_ENGINE, kind="int", consensus=True)
_knob("KF_CONFIG_CHUNK_BYTES", "0", _int,
      "Overrides the chunked-walk chunk size heuristic (0 = heuristic). "
      "Cluster-agreed.",
      section=_SEC_ENGINE, kind="int", consensus=True)
_knob("KF_CONFIG_SEGMENT_MIN_BYTES", str(64 << 10), _int,
      "Payloads below this fall back from the segmented ring to rank-0 "
      "tree graphs (per-segment framing overhead dominates). "
      "Cluster-agreed.",
      section=_SEC_ENGINE, kind="int", consensus=True)
_knob("KF_CONFIG_GROUP_WINDOW", "", _opt_int,
      "Concurrent workspaces per batch in group collectives; default "
      "scales with the cgroup-aware core count (min(8, cores)). "
      "Local-only (not cluster-agreed).",
      section=_SEC_ENGINE, kind="int", default_doc="min(8, cores)")
_knob("KF_CONFIG_GROUP_FUSE_MIN", "4", _int,
      "Minimum same-(dtype,op) tensors before group ops fuse them into "
      "one contiguous walk. Cluster-agreed.",
      section=_SEC_ENGINE, kind="int", consensus=True)
_knob("KF_CONFIG_GROUP_BUCKET_BYTES", str(64 << 20), _int,
      "Fused-bucket size cap for the 3-stage pack/walk/unpack pipeline. "
      "Cluster-agreed (part of the fused workspace name).",
      section=_SEC_ENGINE, kind="int", consensus=True)
_knob("KF_CONFIG_ASYNC", "",
      _choice("KF_CONFIG_ASYNC", ("off", "on", "auto"), empty_as="off"),
      "Asynchronous collective scheduler: group allreduces submitted "
      "per-tensor as gradients become ready launch from a background "
      "thread and overlap backprop (`on`), or only when the session has "
      "≥2 peers (`auto`). `off` runs the synchronous step-end group op. "
      "Cluster-agreed: the mode decides the fused rendezvous names, so "
      "it is checked by `check_knob_consensus` at every session epoch.",
      section=_SEC_ENGINE, kind="choice", strict=True, consensus=True,
      default_doc="off")
_knob("KF_CONFIG_ZERO", "",
      _choice("KF_CONFIG_ZERO", ("off", "on", "auto"), empty_as="off"),
      "ZeRO-1 sharded weight update: gradients are reduce-scattered, "
      "each peer runs the optimizer on (and holds state for) only its "
      "1/k shard, and an all-gather of updated weights (bf16 on the "
      "wire when `KF_CONFIG_WIRE` is active) broadcasts the result. "
      "`on` shards on every multi-peer session, `auto` resolves to on "
      "when the session has ≥2 peers, `off` keeps the replicated "
      "update. Cluster-agreed: the mode decides the whole step's "
      "rendezvous dataflow, so it is checked by `check_knob_consensus` "
      "at every session epoch.",
      section=_SEC_ENGINE, kind="choice", strict=True, consensus=True,
      default_doc="off")
_knob("KF_CONFIG_REPLAN", "",
      _choice("KF_CONFIG_REPLAN",
              ("off", "ring", "ring+segments", "auto", "hier"),
              empty_as="off"),
      "Measured-topology re-planning of the segmented ring: `ring` lets "
      "the vote-driven re-plan reorder ring neighbours from the measured "
      "link matrix, `ring+segments` additionally sizes segments by "
      "measured per-peer throughput, `auto` == `ring+segments`, `hier` "
      "derives TWO-LEVEL plans (per-host intra reduce/broadcast × an "
      "inter-host ring over elected heads, falling back to the flat "
      "measured ring on a single host group) and enables straggler "
      "demotion, `off` keeps the naive rank-order ring. Cluster-agreed: "
      "every peer must run the same lockstep re-plan rounds (and the "
      "adopted plan decides segment bounds), so it is checked by "
      "`check_knob_consensus` at every session epoch.",
      section=_SEC_ENGINE, kind="choice", strict=True, consensus=True,
      default_doc="off")
_knob("KF_REPLAN_DEMOTE_PATIENCE", "3", _int,
      "Closed decision-ledger windows the SAME peer must stay elected "
      "critical (with straggler cause ≠ network-transient) before "
      "`ReplanPolicy` votes it into the demoted role under "
      "`KF_CONFIG_REPLAN=hier`; a recovered peer is promoted back after "
      "the same number of clean windows. Cluster-agreed: demotion flips "
      "the adopted plan's rendezvous dataflow, so every peer must apply "
      "the same patience.",
      section=_SEC_ENGINE, kind="int", strict=True, consensus=True)
_knob("KF_CONFIG_ASYNC_QUEUE", "2", _int,
      "Async scheduler launch-queue depth: how many packed buckets may "
      "sit between the pack and walk stages (bounds live pooled staging "
      "buffers; the walk itself is serialized for cross-peer launch "
      "determinism). Local-only (not cluster-agreed — it changes no "
      "rendezvous name, only local overlap).",
      section=_SEC_ENGINE, kind="int")

_SEC_TRANSPORT = "Transport / shared memory"
_knob("KF_CONFIG_SHM", "1", lambda s: str(s).strip() != "0",
      "Same-host transport rides a shared-memory ring unless this is "
      "exactly `0`.",
      section=_SEC_TRANSPORT, kind="bool")
_knob("KF_CONFIG_SHM_CAPACITY", str(256 << 20), _int,
      "Shared-memory arena size in bytes.",
      section=_SEC_TRANSPORT, kind="int")
_knob("KF_CONFIG_SHM_MIN_BYTES", str(256 << 10), _int,
      "Frames smaller than this take the socket path (ring setup cost "
      "beats small copies).",
      section=_SEC_TRANSPORT, kind="int")

_SEC_DEBUG = "Debug instrumentation"
_knob("KF_DEBUG_LOCKS", "", _bool,
      "Truthy installs the runtime lock-order detector "
      "(`devtools/lockwatch.py`): wraps `threading.Lock/RLock`, builds "
      "the cross-thread acquisition graph, reports ABBA cycles and "
      "long-held locks as `lock_order_violation`/`lock_long_held` audit "
      "events + `kungfu_debug_lock_*` metrics. Off = wrapper not "
      "installed, zero overhead.",
      section=_SEC_DEBUG, kind="bool")
_knob("KF_DEBUG_LOCKS_HELD_MS", "1000", _float,
      "Lock hold time (ms) past which the detector reports a long-held "
      "lock.",
      section=_SEC_DEBUG, kind="float")
_knob("KF_DEBUG_PROTOCOL", "", _bool,
      "Truthy installs the runtime collective-order sentinel "
      "(`devtools/protowatch.py`): wraps the session's collective entry "
      "points, keeps a per-peer rolling digest of (kind, name, dtype, "
      "nbytes, strategy) per round, cross-checks it on the "
      "knob-independent star walk at scheduler flush boundaries, and on "
      "divergence reports each peer's first divergent call site as "
      "`protocol_divergence` audit events + "
      "`kungfu_debug_protocol_*` metrics — before the rendezvous hang, "
      "not after. Off = protowatch never imported, hot path untouched.",
      section=_SEC_DEBUG, kind="bool")
_knob("KF_SHAPE_LINKS", "", _str,
      "Shaped-link harness (ISSUE 14): per-edge latency/bandwidth/"
      "jitter shaping of transport sends, applied inside the timed "
      "send window so the link table, walk profiler and step plane all "
      "observe the shape. Format: `;`-separated entries "
      "`[src>]dst=key:value[,key:value...]` with keys `lat:<ms>` "
      "(per-message latency), `bw:<rate>` (token-bucket pacing; rate "
      "accepts KiB/MiB/GiB[ps] suffixes, plain numbers are bytes/sec) "
      "and `jitter:<ms>` (deterministic pseudo-random 0..jitter extra). "
      "`dst` is a `host:port` peer spec or `*`; `src` (optional) "
      "restricts the entry to the sender with that peer spec. "
      "`uplink:<host>=bw:rate` entries model a SHARED host uplink: all "
      "senders matching `<host>` (a bare hostname, or a `|`-joined "
      "member list of peer specs for single-host harnesses) drain ONE "
      "cross-process token bucket (file-locked mmap) for bytes leaving "
      "the host — per-edge buckets cannot model uplink contention "
      "(ISSUE 19). Local-only test/bench harness, never set in "
      "production.",
      section=_SEC_DEBUG, kind="str")
_knob("KF_TEST_SLOW_EDGE", "", _str,
      "DEPRECATED alias of `KF_SHAPE_LINKS`: `[src>]dst=ms` parses as "
      "`[src>]dst=lat:ms` (with a deprecation warning) so stale envs "
      "keep injecting. Use `KF_SHAPE_LINKS`. Local-only, never set in "
      "production.",
      section=_SEC_DEBUG, kind="str")
_knob("KF_DEBUG_PROTOCOL_WINDOW", "512", _int,
      "Collective-order sentinel: max recorded entries per check window. "
      "Past the cap, entries fold into the rolling digest (divergence is "
      "still detected, but the per-entry diff loses the folded prefix).",
      section=_SEC_DEBUG, kind="int")


# --- accessors ---------------------------------------------------------

def declared() -> Dict[str, Knob]:
    """Name → Knob for every declared knob (a copy)."""
    return dict(_REGISTRY)


def names() -> List[str]:
    return sorted(_REGISTRY)


def is_set(name: str) -> bool:
    """True when the variable is present in the environment (even empty).
    Most callers want :func:`get`; this exists for the few tri-state
    knobs (e.g. KF_FLIGHT: unset=auto, set=forced on/off)."""
    _REGISTRY[name]  # KeyError on undeclared names: declare before use
    return name in os.environ


def raw(name: str) -> str:
    """The raw string value: the environment's, or the declared default
    when unset/empty."""
    k = _REGISTRY[name]
    v = os.environ.get(name)
    if v is None or v.strip() == "":
        return k.default
    return v


def get(name: str):
    """Parsed knob value. Unset/empty resolves to the default; malformed
    values warn and fall back to the default, except strict knobs
    (cluster-agreed), which raise ValueError."""
    k = _REGISTRY[name]
    v = os.environ.get(name)
    if v is None or v.strip() == "":
        return k.parse(k.default)
    try:
        return k.parse(v)
    except (ValueError, TypeError) as e:
        if k.strict:
            # name the knob: a bare "invalid literal for int()" from a
            # cluster-agreed knob gives the operator nothing to grep for
            if name in str(e):
                raise
            raise ValueError(f"{name}: {e}") from None
        # import here, not at module level: the logger reads knobs too
        from kungfu_tpu.telemetry import log

        log.warn("%s: malformed value %r (keeping default %r)",
                 name, v, k.default)
        return k.parse(k.default)


# --- doc generation ----------------------------------------------------

_DOC_HEADER = """\
# Configuration knobs

<!-- GENERATED FILE — do not edit by hand.
     Source of truth: kungfu_tpu/knobs.py.
     Regenerate: python -m kungfu_tpu.devtools.kfcheck --write-knobs-doc
     Staleness is enforced by kfcheck rule KF102 (tests/test_kfcheck.py). -->

Every `KF_*` environment variable the system reads, generated from the
central registry in `kungfu_tpu/knobs.py`. Unset or empty variables
resolve to the default; malformed values warn and keep the default,
except knobs marked **strict**, which fail fast (they are cluster-agreed
— a typo'd peer must error, not silently diverge; see
[docs/collectives.md](collectives.md) for the consensus check).

Boolean knobs accept any truthy spelling (`1/true/yes/on/y/enabled`).

Knobs marked **consensus** are cluster-agreed: their resolved value
decides rendezvous names, message sizes or walk dataflow, so they ride
`HostSession.engine_knobs()`'s fail-fast consensus check at every
session epoch — kfcheck rule KF701 enforces that the registry flag and
the consensus tuple never drift apart.
"""


def render_doc() -> str:
    out = [_DOC_HEADER]
    for section in _SECTIONS:
        out.append(f"\n## {section}\n")
        out.append("| Knob | Type | Default | What it does |")
        out.append("| --- | --- | --- | --- |")
        for k in sorted((k for k in _REGISTRY.values()
                         if k.section == section), key=lambda k: k.name):
            default = k.default_doc or k.default or "(empty)"
            kind = k.kind + (" · strict" if k.strict else "") + (
                " · consensus" if k.consensus else ""
            )
            out.append(f"| `{k.name}` | {kind} | `{default}` | {k.doc} |")
    out.append("")
    return "\n".join(out)
