"""PairAveraging (AD-PSGD): asynchronous decentralized data parallelism.

Capability parity: srcs/python/kungfu/tensorflow/optimizers/async_sgd.py
(_PairAveraging) + the p2p versioned store (srcs/go/store, handler/p2p.go)
+ the AsyncRequestModel prefetch pattern (ops/cpu/peer_to_peer.cpp:166-258).

Per step: pick a random peer, fetch its (fused) model from its host-side
store, average 0.5/0.5 with our params, apply local gradients, publish our
new model. No global barrier — workers proceed at their own pace; stale
peers are tolerated (that is the algorithm's point).

TPU mapping (SURVEY §7 hard-parts): a device pull mid-step is not
XLA-friendly, so the exchange is host-side and OVERLAPPED: a background
thread prefetches the next peer's model while the device runs the current
step; the averaging+apply is one compiled program taking the fetched fused
vector as a plain input.
"""

from __future__ import annotations

import json
import random
import threading
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _pack_host(tree) -> bytes:
    """Dtype-faithful wire blob: raw leaf bytes + dtype/shape header
    (base/serialize.py) — bf16 models exchange losslessly; an f32 flatten
    would corrupt bf16/f64 params in transit."""
    from kungfu_tpu.base.serialize import pack_leaves

    return pack_leaves(jax.tree.leaves(jax.device_get(tree)))


class PairAveraging:
    """Trainer-side driver owning the p2p exchange.

    peer: kungfu_tpu.peer.Peer (host runtime); base: optax transformation.
    """

    BLOB = "pair-avg-model"

    def __init__(
        self,
        base: optax.GradientTransformation,
        peer=None,
        name: str = "model",
        rng: Optional[random.Random] = None,
    ):
        if peer is None:
            from kungfu_tpu.peer import get_default_peer

            peer = get_default_peer()
        self.peer = peer
        self.base = base
        self.blob = f"{self.BLOB}:{name}"
        self.rng = rng or random.Random(peer.rank * 7919 + 17)
        self._prefetch: Optional[threading.Thread] = None
        self._fetched: List[Optional[np.ndarray]] = [None]  # per-thread slot
        self._shapes = None
        self._step_fns = {}
        # per-step publish version: each publish is an immutable
        # (version, blob) in the VersionedStore (GC window 3), so a reader
        # mid-request gets a consistent snapshot while we publish the next
        # (parity: p2p.go versioned requests)
        self._version = 0
        # pair-exchange hit rate: a falling "avg" share means peers are
        # stale/mid-resize and steps degrade to plain local SGD. Label
        # children cached here — step() is the training hot path
        self._m_steps = None
        from kungfu_tpu.telemetry import config as _tcfg

        if _tcfg.metrics_enabled():
            from kungfu_tpu.telemetry import metrics as _tm

            fam = _tm.counter(
                "kungfu_pair_avg_steps_total",
                "PairAveraging steps by exchange outcome",
                ("outcome",),
            )
            self._m_steps = {
                "avg": fam.labels("avg"), "plain": fam.labels("plain")
            }

    # -- jitted compute ------------------------------------------------
    def _build(self, params):
        leaves, treedef = jax.tree.flatten(params)
        self._shapes = (treedef, len(leaves))

        @jax.jit
        def avg_apply(params, other, grads, opt_state):
            # average in f32 regardless of storage dtype (a bf16 0.5*(p+o)
            # loses a mantissa bit per step), round back to the param dtype
            params = jax.tree.map(
                lambda p, o: (
                    0.5 * (p.astype(jnp.float32) + o.astype(jnp.float32))
                ).astype(p.dtype),
                params,
                other,
            )
            updates, opt_state = self.base.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        @jax.jit
        def apply_only(params, grads, opt_state):
            updates, opt_state = self.base.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._step_fns = {"avg": avg_apply, "plain": apply_only}

    # -- host-side exchange --------------------------------------------
    def _random_peer_rank(self) -> Optional[int]:
        size = self.peer.size
        if size <= 1:
            return None
        r = self.rng.randrange(size - 1)
        return r + 1 if r >= self.peer.rank else r

    def _start_prefetch(self) -> None:
        target = self._random_peer_rank()
        if target is None:
            return

        slot: List[Optional[bytes]] = [None]

        def fetch():
            sess = self.peer.current_session()
            try:
                data = self.peer.p2p.request(
                    sess.peers[target], self.blob, timeout=30, version="latest"
                )
            except (ConnectionError, TimeoutError, OSError):
                data = None
            slot[0] = data

        self._fetched = slot
        self._prefetch = threading.Thread(target=fetch, daemon=True)
        self._prefetch.start()

    def init(self, params) -> optax.OptState:
        """Publish the initial model, fence, start the first prefetch
        (parity: async_sgd.py:106-108 init-store + barrier)."""
        self._build(params)
        self.peer.p2p.save_version(self._version, self.blob, _pack_host(params))
        if not self.peer.config.single_process:
            # KF700: version-stamped so a re-init after an elastic
            # resize can never rendezvous with the old epoch's barrier
            self.peer.current_session().barrier(
                tag=f":pair-avg-init:v{self.peer.cluster_version}"
            )
        self._start_prefetch()
        return self.base.init(params)

    def _unpack_other(self, blob) -> Optional[object]:
        """Wire blob -> params-shaped pytree (None on malformed data — a
        stale peer mid-resize may serve a different-shaped model)."""
        from kungfu_tpu.base.serialize import unpack_leaves

        import struct

        treedef, n = self._shapes
        try:
            leaves = unpack_leaves(bytes(blob), n)
        except (
            ValueError,  # wrong leaf count / bad reshape (json.JSONDecodeError too)
            KeyError,  # header missing dtype/shape
            struct.error,  # blob shorter than the length prefix
            UnicodeDecodeError,  # garbage where the json header should be
            AttributeError,  # unknown dtype name in resolve_dtype
        ):
            return None
        return jax.tree.unflatten(treedef, leaves)

    def step(self, params, opt_state, grads):
        """One training step; call with the already-computed LOCAL grads."""
        other_blob: Optional[bytes] = None
        if self._prefetch is not None:
            self._prefetch.join(timeout=30)
            if not self._prefetch.is_alive():
                # orphaned fetches keep writing only their own slot, so a
                # timed-out thread can never clobber a later prefetch
                other_blob = self._fetched[0]
            self._prefetch = None
        other = self._unpack_other(other_blob) if other_blob else None
        if self._m_steps is not None:
            self._m_steps["avg" if other is not None else "plain"].inc()
        if other is not None:
            params, opt_state = self._step_fns["avg"](
                params, other, grads, opt_state
            )
        else:
            params, opt_state = self._step_fns["plain"](params, grads, opt_state)
        # publish new model as the next immutable version, then overlap the
        # next fetch with caller compute
        self._version += 1
        self.peer.p2p.save_version(self._version, self.blob, _pack_host(params))
        self._start_prefetch()
        return params, opt_state
