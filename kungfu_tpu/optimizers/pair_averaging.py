"""PairAveraging (AD-PSGD): asynchronous decentralized data parallelism.

Capability parity: srcs/python/kungfu/tensorflow/optimizers/async_sgd.py
(_PairAveraging) + the p2p versioned store (srcs/go/store, handler/p2p.go)
+ the AsyncRequestModel prefetch pattern (ops/cpu/peer_to_peer.cpp:166-258).

Per step: pick a random peer, fetch its (fused) model from its host-side
store, average 0.5/0.5 with our params, apply local gradients, publish our
new model. No global barrier — workers proceed at their own pace; stale
peers are tolerated (that is the algorithm's point).

TPU mapping (SURVEY §7 hard-parts): a device pull mid-step is not
XLA-friendly, so the exchange is host-side and OVERLAPPED: a background
thread prefetches the next peer's model while the device runs the current
step; the averaging+apply is one compiled program taking the fetched fused
vector as a plain input.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _fuse_host(tree) -> np.ndarray:
    leaves = jax.tree.leaves(jax.device_get(tree))
    return np.concatenate([np.ravel(np.asarray(l, np.float32)) for l in leaves])


class PairAveraging:
    """Trainer-side driver owning the p2p exchange.

    peer: kungfu_tpu.peer.Peer (host runtime); base: optax transformation.
    """

    BLOB = "pair-avg-model"

    def __init__(
        self,
        base: optax.GradientTransformation,
        peer=None,
        name: str = "model",
        rng: Optional[random.Random] = None,
    ):
        if peer is None:
            from kungfu_tpu.peer import get_default_peer

            peer = get_default_peer()
        self.peer = peer
        self.base = base
        self.blob = f"{self.BLOB}:{name}"
        self.rng = rng or random.Random(peer.rank * 7919 + 17)
        self._prefetch: Optional[threading.Thread] = None
        self._fetched: List[Optional[np.ndarray]] = [None]  # per-thread slot
        self._shapes = None
        self._step_fns = {}

    # -- jitted compute ------------------------------------------------
    def _build(self, params):
        leaves, treedef = jax.tree.flatten(params)
        shapes = [l.shape for l in leaves]
        dtypes = [l.dtype for l in leaves]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        self._shapes = (treedef, shapes, dtypes, sizes)

        def unflatten(vec):
            out, off = [], 0
            for shape, dt, size in zip(shapes, dtypes, sizes):
                out.append(jnp.reshape(vec[off:off + size], shape).astype(dt))
                off += size
            return jax.tree.unflatten(treedef, out)

        @jax.jit
        def avg_apply(params, other_vec, grads, opt_state):
            other = unflatten(other_vec)
            params = jax.tree.map(lambda p, o: 0.5 * (p + o), params, other)
            updates, opt_state = self.base.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        @jax.jit
        def apply_only(params, grads, opt_state):
            updates, opt_state = self.base.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._step_fns = {"avg": avg_apply, "plain": apply_only}

    # -- host-side exchange --------------------------------------------
    def _random_peer_rank(self) -> Optional[int]:
        size = self.peer.size
        if size <= 1:
            return None
        r = self.rng.randrange(size - 1)
        return r + 1 if r >= self.peer.rank else r

    def _start_prefetch(self) -> None:
        target = self._random_peer_rank()
        if target is None:
            return

        slot: List[Optional[np.ndarray]] = [None]

        def fetch():
            sess = self.peer.current_session()
            try:
                data = self.peer.p2p.request(sess.peers[target], self.blob, timeout=30)
            except (ConnectionError, TimeoutError, OSError):
                data = None
            slot[0] = np.frombuffer(data, np.float32) if data is not None else None

        self._fetched = slot
        self._prefetch = threading.Thread(target=fetch, daemon=True)
        self._prefetch.start()

    def init(self, params) -> optax.OptState:
        """Publish the initial model, fence, start the first prefetch
        (parity: async_sgd.py:106-108 init-store + barrier)."""
        self._build(params)
        self.peer.p2p.save(self.blob, _fuse_host(params).tobytes())
        if not self.peer.config.single_process:
            self.peer.current_session().barrier(tag=":pair-avg-init")
        self._start_prefetch()
        return self.base.init(params)

    def step(self, params, opt_state, grads):
        """One training step; call with the already-computed LOCAL grads."""
        other: Optional[np.ndarray] = None
        if self._prefetch is not None:
            self._prefetch.join(timeout=30)
            if not self._prefetch.is_alive():
                # orphaned fetches keep writing only their own slot, so a
                # timed-out thread can never clobber a later prefetch
                other = self._fetched[0]
            self._prefetch = None
        if other is not None and other.size:
            params, opt_state = self._step_fns["avg"](
                params, jnp.asarray(other), grads, opt_state
            )
        else:
            params, opt_state = self._step_fns["plain"](params, grads, opt_state)
        # publish new model, then overlap the next fetch with caller compute
        self.peer.p2p.save(self.blob, _fuse_host(params).tobytes())
        self._start_prefetch()
        return params, opt_state
