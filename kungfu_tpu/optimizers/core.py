"""Distributed optimizer wrappers as optax gradient transformations.

Capability parity with the reference optimizer framework
(srcs/python/kungfu/tensorflow/optimizers/core.py + sync_sgd.py, sma_sgd.py,
ada_sgd.py): each wrapper takes a base optax optimizer and injects
cross-replica communication into the update. TPU-first: the communication
is `lax.pmean`/`psum` traced into the SAME compiled program as the model
step, so grad-allreduce overlaps backprop under XLA's scheduler — there is
no op-ordering problem (the NCCL scheduler's job, scheduler.cpp:37-129, is
subsumed by XLA's static schedule).

All wrappers must run inside a `shard_map` over the mesh axis they reduce
on (see kungfu_tpu.parallel.make_train_step).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax


def synchronous_sgd(base: optax.GradientTransformation, axis_name: str = "dp") -> optax.GradientTransformation:
    """S-SGD (parity: SynchronousSGDOptimizer, sync_sgd.py:15-109): average
    gradients over the axis before the base update. One fused XLA AllReduce
    per step (XLA combines the per-leaf psums)."""

    def init(params):
        return base.init(params)

    def update(grads, state, params=None, **extra):
        grads = jax.tree.map(lambda g: lax.pmean(g, axis_name), grads)
        return base.update(grads, state, params, **extra)

    return optax.GradientTransformation(init, update)


class _ZeroState(NamedTuple):
    base: optax.OptState


def zero_sharded(
    base: optax.GradientTransformation,
    axis_size: int,
    axis_name: str = "dp",
) -> optax.GradientTransformation:
    """ZeRO-1 sharded weight update on the device plane (ISSUE 11;
    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training", arXiv:2004.13336): gradients are reduce-scattered
    (`lax.psum_scatter`) so each replica averages only its 1/k shard,
    the base optimizer updates that shard — its state exists for the
    shard only, the k-fold state/FLOP cut — and the updated parameters
    are re-assembled with `lax.all_gather`. The returned updates equal
    S-SGD's up to float reassociation (psum_scatter associates like
    psum), at 1/k optimizer state and update FLOPs per replica.

    Each leaf is flattened and zero-padded to a multiple of
    ``axis_size`` (the mapped axis size, passed explicitly so state
    shapes are static); padding lanes carry zero gradients, so
    stateful base transforms see zeros there on every replica alike.
    Like the other wrappers this must run inside a `shard_map` over
    `axis_name` — init() included, since each replica initializes state
    for ITS shard (use out_specs ``P(axis_name)`` on the state so the
    global view concatenates the shards)."""
    k = int(axis_size)
    if k < 1:
        raise ValueError(f"axis_size must be >= 1, got {axis_size}")

    def _shard_len(n: int) -> int:
        return -(-n // k)

    def _pad_flat(leaf):
        flat = leaf.reshape(-1)
        m = _shard_len(flat.size)
        return jnp.pad(flat, (0, m * k - flat.size)), m

    def _my_shard(leaf):
        padded, m = _pad_flat(leaf)
        idx = lax.axis_index(axis_name)
        return lax.dynamic_slice(padded, (idx * m,), (m,))

    def init(params):
        return _ZeroState(base=base.init(jax.tree.map(_my_shard, params)))

    def update(grads, state, params=None, **extra):
        if params is None:
            raise ValueError("zero_sharded requires params")
        # reduce-scatter + average: each replica holds the mean of its
        # 1/k gradient shard (psum_scatter of the padded flat leaf)
        def g_shard(g):
            padded, _ = _pad_flat(g)
            return lax.psum_scatter(
                padded, axis_name, scatter_dimension=0, tiled=True
            ) / k

        grad_shards = jax.tree.map(g_shard, grads)
        param_shards = jax.tree.map(_my_shard, params)
        shard_updates, base_state = base.update(
            grad_shards, state.base, param_shards, **extra
        )
        new_shards = optax.apply_updates(param_shards, shard_updates)

        # all-gather the updated shards and express the result as an
        # optax update (new - old), unpadded and reshaped per leaf
        def regather(new_shard, p):
            full = lax.all_gather(new_shard, axis_name, tiled=True)
            return full[: p.size].reshape(p.shape) - p

        updates = jax.tree.map(regather, new_shards, params)
        return updates, _ZeroState(base=base_state)

    return optax.GradientTransformation(init, update)


class _SMAState(NamedTuple):
    base: optax.OptState


def synchronous_averaging(
    base: optax.GradientTransformation,
    axis_name: str = "dp",
    alpha: float = 0.1,
) -> optax.GradientTransformation:
    """SMA / EA-SGD (parity: SynchronousAveragingOptimizer, sma_sgd.py:9-75):
    each step blends params toward the cluster average with weight ``alpha``,
    then applies the LOCAL gradients. Converges better than S-SGD at large
    cluster sizes (reference README: 75% vs 59% top-1 at 16 workers)."""

    def init(params):
        return _SMAState(base=base.init(params))

    def update(grads, state, params, **extra):
        if params is None:
            raise ValueError("synchronous_averaging requires params")
        avg = jax.tree.map(lambda p: lax.pmean(p, axis_name), params)
        base_updates, base_state = base.update(grads, state.base, params, **extra)
        # total update = alpha * (avg - p) + base_update(local grads)
        updates = jax.tree.map(
            lambda a, p, u: alpha * (a - p) + u, avg, params, base_updates
        )
        return updates, _SMAState(base=base_state)

    return optax.GradientTransformation(init, update)


class _AdaSGDState(NamedTuple):
    step: jnp.ndarray
    sma: optax.OptState
    ssgd: optax.OptState


def adaptive_sgd(
    base: optax.GradientTransformation,
    change_step: int,
    axis_name: str = "dp",
    alpha: float = 0.1,
) -> optax.GradientTransformation:
    """AdaptiveSGD (parity: AdaSGDOptimizer, ada_sgd.py:12-84): SMA before
    ``change_step``, S-SGD after. The switch is a `lax.cond` so one compiled
    program covers both phases (no recompilation at the switch). At the
    switch step the update folds in a rank-0 re-broadcast of the params
    (parity: AdaSGDHook re-broadcast) — SMA's local-gradient steps let
    replicas diverge, and S-SGD alone would freeze that divergence in."""
    sma = synchronous_averaging(base, axis_name, alpha)
    ssgd = synchronous_sgd(base, axis_name)

    def init(params):
        return _AdaSGDState(
            step=jnp.zeros((), jnp.int32),
            sma=sma.init(params),
            ssgd=ssgd.init(params),
        )

    def update(grads, state, params, **extra):
        def run_sma(_):
            u, s = sma.update(grads, state.sma, params, **extra)
            return u, _AdaSGDState(state.step + 1, s, state.ssgd)

        def run_ssgd(_):
            u, s = ssgd.update(grads, state.ssgd, params, **extra)
            if params is not None:
                # switch step: fold in the rank-0 re-sync broadcast
                from kungfu_tpu.ops.collective import broadcast

                at_switch = state.step == change_step
                u = jax.tree.map(
                    lambda ui, p: ui
                    + at_switch.astype(ui.dtype)
                    * (broadcast(p, axis_name) - p).astype(ui.dtype),
                    u,
                    params,
                )
            return u, _AdaSGDState(state.step + 1, state.sma, s)

        return lax.cond(state.step < change_step, run_sma, run_ssgd, None)

    return optax.GradientTransformation(init, update)
