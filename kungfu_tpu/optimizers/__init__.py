from kungfu_tpu.optimizers.core import (
    adaptive_sgd,
    synchronous_averaging,
    synchronous_sgd,
    zero_sharded,
)

__all__ = [
    "adaptive_sgd",
    "synchronous_averaging",
    "synchronous_sgd",
    "zero_sharded",
]
