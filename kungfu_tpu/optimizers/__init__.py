from kungfu_tpu.optimizers.core import (
    adaptive_sgd,
    synchronous_averaging,
    synchronous_sgd,
)

__all__ = [
    "adaptive_sgd",
    "synchronous_averaging",
    "synchronous_sgd",
]
