from kungfu_tpu.store.versioned import BlobStore, VersionedStore

__all__ = ["BlobStore", "VersionedStore"]
