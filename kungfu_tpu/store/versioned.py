"""Named blob stores for peer-to-peer model exchange.

Capability parity: srcs/go/store/{store,versionedstore,blob}.go — an
RW-locked named blob store plus a VersionedStore with a GC window (the
reference keeps 3 versions, handler/p2p.go:11) backing PairAveraging model
requests.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional


class BlobStore:
    """Flat named blobs (latest value wins)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._blobs: Dict[str, bytes] = {}

    def put(self, name: str, data: bytes) -> None:
        with self._lock:
            self._blobs[name] = bytes(data)

    def get(self, name: str) -> Optional[bytes]:
        with self._lock:
            return self._blobs.get(name)

    def names(self):
        with self._lock:
            return list(self._blobs)


class VersionedStore:
    """Versioned blobs with a bounded GC window.

    put(version, name, data); get(version, name); next_version(name) gives
    the newest version holding `name`. Old versions beyond the window are
    dropped (parity: versionedstore.go:8-94).
    """

    def __init__(self, window: int = 3):
        self._lock = threading.RLock()
        self._window = window
        self._versions: "OrderedDict[int, Dict[str, bytes]]" = OrderedDict()

    def put(self, version: int, name: str, data: bytes) -> None:
        with self._lock:
            if version not in self._versions:
                self._versions[version] = {}
                while len(self._versions) > self._window:
                    self._versions.popitem(last=False)
            self._versions[version][name] = bytes(data)

    def get(self, version: int, name: str) -> Optional[bytes]:
        with self._lock:
            return self._versions.get(version, {}).get(name)

    def latest_version(self, name: str) -> Optional[int]:
        with self._lock:
            for v in reversed(self._versions):
                if name in self._versions[v]:
                    return v
            return None

    def get_latest(self, name: str) -> Optional[bytes]:
        with self._lock:
            v = self.latest_version(name)
            return None if v is None else self._versions[v][name]
