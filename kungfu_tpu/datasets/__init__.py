"""Dataset helpers: idx/npz loaders for the example configs.

Capability parity: srcs/python/kungfu/tensorflow/v1/helpers/{mnist,idx,
cifar}.py. Zero-egress environment: loaders read files already on disk
(the reference's downloaders are out of scope; pass --data <dir> to the
examples)."""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from kungfu_tpu.datasets.idx import read_idx, write_idx

__all__ = ["read_idx", "write_idx", "load_mnist", "load_cifar10", "load_npz"]

_MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _find(data_dir: str, base: str) -> Optional[str]:
    for name in (base, base + ".gz"):
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


def load_mnist(
    data_dir: str, normalize: bool = True
) -> Dict[str, np.ndarray]:
    """Load the 4 standard MNIST idx files from `data_dir` (gz ok).

    Returns {train_images (N,784) f32, train_labels (N,) i32, ...};
    parity: helpers/mnist.py load_datasets(normalize=True)."""
    out: Dict[str, np.ndarray] = {}
    for key, base in _MNIST_FILES.items():
        path = _find(data_dir, base)
        if path is None:
            raise FileNotFoundError(f"{data_dir}: missing {base}[.gz]")
        arr = read_idx(path)
        if "images" in key:
            arr = arr.reshape(arr.shape[0], -1)
            arr = arr.astype(np.float32)
            if normalize:
                arr /= 255.0
        else:
            arr = arr.astype(np.int32)
        out[key] = arr
    return out


def load_cifar10(data_dir: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Load CIFAR-10 from the python-version pickle batches or a combined
    .npz. Returns (train_x (N,32,32,3) f32 in [0,1], train_y, test_x,
    test_y); parity: helpers/cifar.py."""
    npz = os.path.join(data_dir, "cifar10.npz")
    if os.path.exists(npz):
        d = np.load(npz)
        return (
            d["train_x"].astype(np.float32),
            d["train_y"].astype(np.int32),
            d["test_x"].astype(np.float32),
            d["test_y"].astype(np.int32),
        )
    import pickle

    def read_batch(name):
        with open(os.path.join(data_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32) / 255.0, np.asarray(d[b"labels"], np.int32)

    xs, ys = zip(*(read_batch(f"data_batch_{i}") for i in range(1, 6)))
    tx, ty = read_batch("test_batch")
    return np.concatenate(xs), np.concatenate(ys), tx, ty


def load_npz(path: str, x_key: str = "x", y_key: str = "y"):
    """Generic (x, y) npz loader for custom datasets."""
    d = np.load(path)
    return np.asarray(d[x_key]), np.asarray(d[y_key])
