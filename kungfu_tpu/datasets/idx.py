"""IDX file format (the MNIST container): read/write, gzip-transparent.

Capability parity: srcs/python/kungfu/tensorflow/v1/helpers/idx.py — the
reference's loaders build on an idx reader. Format: magic
``\\x00\\x00<dtype><ndim>``, big-endian uint32 dims, then row-major data.
"""

from __future__ import annotations

import gzip
import struct
from typing import BinaryIO

import numpy as np

# idx type code -> numpy dtype (big-endian where multi-byte)
_IDX_DTYPES = {
    0x08: np.dtype(np.uint8),
    0x09: np.dtype(np.int8),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}
_DTYPE_CODES = {
    np.dtype(np.uint8): 0x08,
    np.dtype(np.int8): 0x09,
    np.dtype(np.int16): 0x0B,
    np.dtype(np.int32): 0x0C,
    np.dtype(np.float32): 0x0D,
    np.dtype(np.float64): 0x0E,
}


def _open(path: str, mode: str) -> BinaryIO:
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def read_idx(path: str) -> np.ndarray:
    """Read an idx(.gz) file into a native-endian array."""
    with _open(path, "rb") as f:
        magic = f.read(4)
        if len(magic) != 4 or magic[0] != 0 or magic[1] != 0:
            raise ValueError(f"{path}: not an idx file (magic {magic!r})")
        dtype_code, ndim = magic[2], magic[3]
        if dtype_code not in _IDX_DTYPES:
            raise ValueError(f"{path}: unknown idx dtype {dtype_code:#x}")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        dt = _IDX_DTYPES[dtype_code]
        data = f.read()
        count = int(np.prod(dims)) if dims else 1
        if len(data) < count * dt.itemsize:
            raise ValueError(
                f"{path}: truncated (need {count * dt.itemsize} bytes, "
                f"have {len(data)})"
            )
        arr = np.frombuffer(data, dt, count=count).reshape(dims)
        return arr.astype(arr.dtype.newbyteorder("="))


def write_idx(path: str, arr: np.ndarray) -> None:
    """Write an array as idx(.gz); inverse of read_idx."""
    dt = np.dtype(arr.dtype.newbyteorder("="))
    if dt not in _DTYPE_CODES:
        raise ValueError(f"idx cannot store dtype {arr.dtype}")
    code = _DTYPE_CODES[dt]
    with _open(path, "wb") as f:
        f.write(bytes([0, 0, code, arr.ndim]))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        be = arr.astype(arr.dtype.newbyteorder(">"), copy=False)
        f.write(np.ascontiguousarray(be).tobytes())
