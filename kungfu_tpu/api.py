"""User-facing process API.

Capability parity: srcs/python/kungfu/python/__init__.py:17-168 —
current_rank/cluster_size/local metadata, barrier, resize/propose,
all_reduce helpers — backed by the in-process Peer singleton instead of
ctypes into libkungfu.
"""

from __future__ import annotations

import atexit
from typing import Optional, Sequence

import numpy as np

from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.peer import finalize_default_peer, get_default_peer

atexit.register(finalize_default_peer)


def current_rank() -> int:
    return get_default_peer().rank


def cluster_size() -> int:
    return get_default_peer().size


def current_local_rank() -> int:
    return get_default_peer().current_session().local_rank


def current_local_size() -> int:
    return get_default_peer().current_session().local_size


def host_count() -> int:
    return get_default_peer().current_session().host_count


def current_cluster_version() -> int:
    return get_default_peer().cluster_version


def uid() -> int:
    """(version, rank) packed; parity: python/__init__.py uid. Rank gets the
    low 32 bits so the version never collides with it (a 16-bit version
    field would silently wrap after 65k resizes)."""
    p = get_default_peer()
    return (p.cluster_version << 32) | p.rank


def detached() -> bool:
    return get_default_peer().detached


def run_barrier() -> None:
    get_default_peer().current_session().barrier()


def all_reduce_array(
    x: np.ndarray, op: ReduceOp = ReduceOp.SUM, name: str = "user"
) -> np.ndarray:
    """Host-plane allreduce of a numpy array (control data, NOT gradients —
    those belong on the ICI plane via kungfu_tpu.ops)."""
    flat = np.ascontiguousarray(x).reshape(-1)
    out = np.zeros_like(flat)
    w = Workspace(send=flat, recv=out, op=op, name=f"kungfu::user::{name}")
    get_default_peer().current_session().all_reduce(w)
    return out.reshape(x.shape)


def all_reduce_int_max(x: int) -> int:
    out = all_reduce_array(np.array([x], np.int64), ReduceOp.MAX, "int-max")
    return int(out[0])


def consensus(data: bytes, name: str = "user") -> bool:
    return get_default_peer().current_session().bytes_consensus(data, name)


def resize(new_size: Optional[int] = None):
    """Resize the cluster; returns (changed, detached).

    With new_size=None, pulls the desired cluster from the config server
    (parity: resize_cluster_from_url); otherwise grows/shrinks to new_size.
    """
    p = get_default_peer()
    if new_size is None:
        return p.resize_cluster_from_url()
    return p.resize_cluster(new_size)


def propose_new_size(new_size: int) -> None:
    get_default_peer().propose_new_size(new_size)


def change_cluster(progress: int):
    return get_default_peer().change_cluster(progress)


def egress_rates() -> "np.ndarray":
    """Per-peer egress rates (bytes/sec), rank-aligned (parity:
    EgressRates op, ops/cpu/monitoring.cpp:5-22 + sess.GetEgressRates).
    All zeros unless KF_CONFIG_ENABLE_MONITORING is set."""
    from kungfu_tpu.monitor.net import get_monitor

    sess = get_default_peer().current_session()
    return np.asarray(get_monitor().egress_rates(list(sess.peers)), np.float64)


def save(name: str, data: bytes) -> None:
    """Publish a blob to this peer's store (parity: SaveVariable)."""
    get_default_peer().p2p.save(name, data)


def request(rank: int, name: str) -> Optional[bytes]:
    """Fetch a blob from peer `rank`'s store (parity: RequestVariable)."""
    p = get_default_peer()
    sess = p.current_session()
    return p.p2p.request(sess.peers[rank], name)
