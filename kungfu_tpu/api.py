"""User-facing process API.

Capability parity: srcs/python/kungfu/python/__init__.py:17-168 —
current_rank/cluster_size/local metadata, barrier, resize/propose,
all_reduce helpers — backed by the in-process Peer singleton instead of
ctypes into libkungfu.
"""

from __future__ import annotations

import atexit
import threading
from typing import Optional, Sequence

import numpy as np

from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.peer import finalize_default_peer, get_default_peer
from kungfu_tpu.transport.message import ConnType as _ConnType

atexit.register(finalize_default_peer)


def current_rank() -> int:
    return get_default_peer().rank


def cluster_size() -> int:
    return get_default_peer().size


def current_local_rank() -> int:
    return get_default_peer().current_session().local_rank


def current_local_size() -> int:
    return get_default_peer().current_session().local_size


def host_count() -> int:
    return get_default_peer().current_session().host_count


def current_cluster_version() -> int:
    return get_default_peer().cluster_version


def uid() -> int:
    """(version, rank) packed; parity: python/__init__.py uid. Rank gets the
    low 32 bits so the version never collides with it (a 16-bit version
    field would silently wrap after 65k resizes)."""
    p = get_default_peer()
    return (p.cluster_version << 32) | p.rank


def detached() -> bool:
    return get_default_peer().detached


def run_barrier() -> None:
    get_default_peer().current_session().barrier()


def all_reduce_array(
    x: np.ndarray, op: ReduceOp = ReduceOp.SUM, name: str = "user"
) -> np.ndarray:
    """Host-plane allreduce of a numpy array (control data, NOT gradients —
    those belong on the ICI plane via kungfu_tpu.ops)."""
    flat = np.ascontiguousarray(x).reshape(-1)
    # empty, not zeros: every element of recv is written by the graph walk
    # (forward / transform2 / copyto), and zeroing 100 MB gradient sets per
    # call is measurable
    out = np.empty_like(flat)
    w = Workspace(send=flat, recv=out, op=op, name=f"kungfu::user::{name}")
    get_default_peer().current_session().all_reduce(w)
    return out.reshape(x.shape)


def group_all_reduce_arrays(
    xs, op: ReduceOp = ReduceOp.SUM, name: str = "group", outs=None
):
    """Host-plane allreduce of a list of arrays (one fused/windowed group
    op — the way the reference reduces a whole gradient set). Pass
    `outs` (same shapes/dtypes as `xs`) to reuse result buffers across
    steps — the reference's TF op outputs are graph-allocated once, and
    fresh 100 MB of np.empty per step costs real page-fault time."""
    flats = [np.ascontiguousarray(x).reshape(-1) for x in xs]
    flat_outs = _group_outs(xs, flats, outs)
    ws = [
        Workspace(send=f, recv=o, op=op, name=f"kungfu::user::{name}:{i}")
        for i, (f, o) in enumerate(zip(flats, flat_outs))
    ]
    get_default_peer().current_session().group_all_reduce(ws)
    return [o.reshape(x.shape) for o, x in zip(flat_outs, xs)]


class AsyncGroupResult:
    """Handle for one round of asynchronous group allreduce
    (:func:`group_all_reduce_async`): ``wait()`` blocks until every
    submitted tensor has been reduced and returns the results (the
    ``outs`` buffers, reshaped). With the scheduler disabled
    (``KF_CONFIG_ASYNC=off``) the group already ran synchronously
    INSIDE the submitting call — results are complete before the handle
    exists, ``wait()`` just returns them and ``timeout`` is moot — so
    the submit-per-tensor + ``flush_async()`` pattern works identically
    under either knob value (one code path, A/B by knob)."""

    def __init__(self, sess, flat_outs, xs, round_index=None):
        self._sess = sess
        self._flat_outs = flat_outs
        self._xs = xs
        self._round = round_index  # scheduler round; None = sync fallback
        self._done = round_index is None

    def wait(self, timeout=None):
        if not self._done:
            # round-aware: several handles of the same round each call
            # wait() (the documented per-tensor pattern) — only the
            # first actually flushes; the rest see the round already
            # advanced and return immediately
            self._sess.scheduler().flush_round(self._round, timeout=timeout)
            self._done = True
        return [o.reshape(x.shape) for o, x in zip(self._flat_outs, self._xs)]


def group_all_reduce_async(
    xs, op: ReduceOp = ReduceOp.SUM, name: str = "group", outs=None
) -> AsyncGroupResult:
    """Asynchronous host-plane group allreduce (ISSUE 10): each array is
    SUBMITTED to the session's background collective scheduler as soon
    as this call sees it — buckets launch and walk while the caller
    keeps computing (the backprop-overlap path) — and the returned
    handle's ``wait()`` blocks only for the tail. Call once per tensor
    as gradients become ready (1-element lists), or with the whole set.

    Tensor identity: ``(name, index)`` must be stable across steps —
    the first step's submission order is negotiated cluster-wide as the
    launch order (consensus-checked), and every later step must submit
    the same set (in any order). Results are bit-identical to
    :func:`group_all_reduce_arrays` on the same inputs. Pass ``outs``
    to reuse result buffers across steps like the sync API."""
    flats = [np.ascontiguousarray(x).reshape(-1) for x in xs]
    flat_outs = _group_outs(xs, flats, outs)
    sess = get_default_peer().current_session()
    if not sess.async_enabled():
        # synchronous fallback, executed EAGERLY: callers following the
        # submit + flush_async() pattern never touch the handle, so a
        # deferred group would silently not run. Name notes: unlike the
        # scheduler path (stable names, scheduler-stamped rounds), each
        # call needs its OWN wire names — a fast peer's step k+1 sends
        # must never be consumed by a slower peer still receiving step
        # k. Peers call in identical program order, so the process-
        # local sequence agrees.
        with _async_seq_lock:
            seq = _async_seq[0]
            _async_seq[0] += 1
        ws = [
            Workspace(send=f, recv=o, op=op,
                      name=f"kungfu::user::async:{name}:{i}@{seq}")
            for i, (f, o) in enumerate(zip(flats, flat_outs))
        ]
        sess.group_all_reduce(ws)
        return AsyncGroupResult(sess, flat_outs, xs)
    sched = sess.scheduler()
    ws = [
        Workspace(send=f, recv=o, op=op, name=f"kungfu::user::async:{name}:{i}")
        for i, (f, o) in enumerate(zip(flats, flat_outs))
    ]
    for w in ws:
        sched.submit(w)
    return AsyncGroupResult(sess, flat_outs, xs, round_index=sched.round_index())


def flush_async(timeout=None) -> None:
    """End the current async round: block until every workspace
    submitted to the session's scheduler has completed (no-op when the
    scheduler is off, unused this epoch, or the round is empty — a
    defensive flush never freezes an empty registration). The per-round
    barrier of the submission API — call once per training step."""
    sess = get_default_peer().current_session()
    if sess.async_enabled():
        sess.scheduler().flush(timeout=timeout)


_async_seq = [0]
_async_seq_lock = threading.Lock()


def _group_outs(xs, flats, outs):
    """Shared outs validation of the group allreduce APIs: C-contiguous,
    size- and dtype-matched — mismatches reach the native reduce as raw
    pointers, so they must fail here, not corrupt memory there."""
    if outs is None:
        return [np.empty_like(f) for f in flats]
    if len(outs) != len(xs):
        raise ValueError(f"outs mismatch: {len(outs)} != {len(xs)}")
    for i, (o, f) in enumerate(zip(outs, flats)):
        # reshape(-1) of a non-contiguous array is a COPY — the
        # collective would fill the copy and the caller's buffer
        # would silently keep last step's data
        if not o.flags["C_CONTIGUOUS"]:
            raise ValueError("outs arrays must be C-contiguous")
        if o.size != f.size:
            raise ValueError(f"outs[{i}] size {o.size} != input size {f.size}")
        if o.dtype != f.dtype:
            raise ValueError(
                f"outs[{i}] dtype {o.dtype} != input dtype {f.dtype}"
            )
    return [o.reshape(-1) for o in outs]


def reduce_scatter(
    x: np.ndarray, op: ReduceOp = ReduceOp.SUM, name: str = "user"
) -> np.ndarray:
    """First-class reduce-scatter (ISSUE 11): reduce `x` across the
    cluster and return only this rank's owned 1/k shard — the RS half of
    the segmented ring walk, (k-1)/k·N bytes per peer, f32-exact. The
    shard layout is the session's ``owned_bounds`` (contiguous
    ``segment_bounds`` slices of the FLATTENED array under the current
    ring plan — equal, or measured-topology re-planned, ISSUE 14),
    identical on every peer without negotiation; ranks beyond the
    element count get an empty shard (the n<k edge the segmented walk
    already handles). ``all_gather(reduce_scatter(x))`` ==
    ``all_reduce_array(x)`` bit for bit."""
    flat = np.ascontiguousarray(x).reshape(-1)
    out = np.empty_like(flat)
    w = Workspace(send=flat, recv=out, op=op, name=f"kungfu::user::rs:{name}")
    b, e = get_default_peer().current_session().reduce_scatter(w)
    return out[b:e].copy()


def all_gather(shard: np.ndarray, name: str = "user") -> np.ndarray:
    """Standalone segment all-gather (ISSUE 11): every rank contributes
    its owned shard (the ``reduce_scatter`` layout) and receives the
    reassembled full array, identical on all peers. The shard must be
    exactly this rank's ``owned_segment_bounds`` slice — a mismatched
    size fails fast here, not as a wire-framing corruption. Rides the
    wire codec like allreduce (bf16 on the wire for eligible f32
    payloads, each segment quantized once by its owner; see
    docs/collectives.md for the error model)."""
    sess = get_default_peer().current_session()
    flat = np.ascontiguousarray(shard).reshape(-1)
    # one int64 lane agrees the total element count (shard sizes differ
    # across ranks under the segment partition, so it is not derivable
    # locally); exact, never compressed
    total = int(all_reduce_array(
        np.array([flat.size], np.int64), ReduceOp.SUM, f"agsz:{name}"
    )[0])
    # plan-aware: the owned-segment layout follows the session's current
    # ring plan (naive, or measured-topology re-planned — ISSUE 14)
    b, e = sess.owned_bounds(total)
    if flat.size != e - b:
        raise ValueError(
            f"all_gather shard has {flat.size} elements but rank "
            f"{sess.rank} owns [{b}:{e}) of {total} — shards must follow "
            "the reduce_scatter layout (owned_segment_bounds)"
        )
    full = np.empty(total, flat.dtype)
    full[b:e] = flat
    sess.all_gather_shards(full, f"kungfu::user::ag:{name}")
    return full


def sharded_update_session(
    params, lr: float, momentum: float = 0.0, name: str = "zero",
    restore_state: "Optional[bytes]" = None,
):
    """Build a :class:`~kungfu_tpu.collective.zero.ShardedUpdateSession`
    — the ZeRO-1 sharded SGD update over the current session (ISSUE 11):
    reduce-scatter gradients, update (and hold optimizer state for) only
    this rank's 1/k shard, all-gather the updated weights (bf16 on the
    wire when the codec wins). See the module docstring for the
    synchronous and scheduler-overlapped driving patterns and the
    resize/re-shard contract (`export_state`/`restore_state`)."""
    from kungfu_tpu.collective.zero import ShardedSGD, ShardedUpdateSession

    return ShardedUpdateSession(
        params, ShardedSGD(lr, momentum=momentum), name=name,
        session=get_default_peer().current_session(),
        restore_state=restore_state,
    )


def broadcast_array(x: np.ndarray, root: int = 0, name: str = "user") -> np.ndarray:
    """Host-plane broadcast from `root` (arbitrary roots, parity: the
    reference's Broadcast op)."""
    flat = np.ascontiguousarray(x).reshape(-1)
    # no root-side copy needed: the bcast root has no prevs, so the graph
    # walk's forward() performs the send->recv copy itself
    out = np.empty_like(flat)
    w = Workspace(send=flat, recv=out, op=ReduceOp.SUM,
                  name=f"kungfu::user::bcast:{name}")
    get_default_peer().current_session().broadcast(w, root=root)
    return out.reshape(x.shape)


def gather_arrays(x: np.ndarray, root: int = 0, name: str = "user"):
    """Host-plane gather of equal-shaped contributions to `root`; returns
    the (size, *x.shape) stack at the root, None elsewhere (parity:
    Gather, arbitrary roots)."""
    sess = get_default_peer().current_session()
    flat = np.ascontiguousarray(x).reshape(-1)
    recv = (
        np.empty(flat.size * sess.size, flat.dtype)
        if sess.rank == root
        else np.empty(0, flat.dtype)
    )
    w = Workspace(send=flat, recv=recv, op=ReduceOp.SUM,
                  name=f"kungfu::user::gather:{name}")
    sess.gather(w, root=root)
    if sess.rank != root:
        return None
    return recv.reshape((sess.size,) + x.shape)


def all_reduce_int_max(x: int) -> int:
    out = all_reduce_array(np.array([x], np.int64), ReduceOp.MAX, "int-max")
    return int(out[0])


def consensus(data: bytes, name: str = "user") -> bool:
    return get_default_peer().current_session().bytes_consensus(data, name)


def resize(new_size: Optional[int] = None):
    """Resize the cluster; returns (changed, detached).

    With new_size=None, pulls the desired cluster from the config server
    (parity: resize_cluster_from_url); otherwise grows/shrinks to new_size.
    """
    p = get_default_peer()
    if new_size is None:
        return p.resize_cluster_from_url()
    return p.resize_cluster(new_size)


def propose_new_size(new_size: int) -> None:
    get_default_peer().propose_new_size(new_size)


def last_resize_phases() -> dict:
    """Per-phase ms breakdown of the most recent resize seen by this peer
    (wait_config / consensus / notify / update)."""
    return dict(get_default_peer().last_resize_phases)


def trace_summary(prefix: str = "") -> dict:
    """Total ms per hot-path span recorded in this process (transport
    send/recv, collective walks, fuse pack/unpack, elastic state sync) —
    parity: the reference compiles TRACE_SCOPE into its GPU hot paths
    (srcs/cpp/include/kungfu/utils/trace.hpp, gpu_collective.cpp)."""
    from kungfu_tpu.utils import trace

    return trace.summary_ms(prefix)


def telemetry_dump(prefix: str = "") -> dict:
    """Snapshot of the whole telemetry subsystem: Prometheus metrics
    text, Chrome-trace JSON, resize audit records and a per-span ms
    summary (see kungfu_tpu.telemetry.dump)."""
    from kungfu_tpu import telemetry

    return telemetry.dump(prefix)


def resize_audit() -> list:
    """The elastic resize audit records of this process, as dicts
    (old/new cluster, trigger, per-phase durations, progress)."""
    from kungfu_tpu.telemetry import audit

    return [r.to_json() for r in audit.records(kind="resize")]


def metrics_text() -> str:
    """Prometheus text exposition of the process metrics registry — the
    same body the per-worker /metrics endpoint serves."""
    from kungfu_tpu.telemetry import metrics

    return metrics.render()


def change_cluster(progress: int):
    return get_default_peer().change_cluster(progress)


def monitored_all_reduce_array(
    x: np.ndarray, op: ReduceOp = ReduceOp.SUM, name: str = "user"
) -> np.ndarray:
    """Host-plane allreduce with throughput accounting feeding the adaptive
    controller (parity: MonitoredAllReduce op)."""
    flat = np.ascontiguousarray(x).reshape(-1)
    # empty, not zeros: every element of recv is written by the graph walk
    # (forward / transform2 / copyto), and zeroing 100 MB gradient sets per
    # call is measurable
    out = np.empty_like(flat)
    w = Workspace(send=flat, recv=out, op=op, name=f"kungfu::monitored::{name}")
    get_default_peer().current_session().monitored_all_reduce(w)
    return out.reshape(x.shape)


def check_interference() -> bool:
    """Vote on interference; True if the cluster switched strategy (parity:
    check_interference, session/adaptiveStrategies.go:61-121)."""
    return get_default_peer().current_session().check_interference()


def check_replan(want: bool = True, min_gain: float = 1.05) -> bool:
    """One lockstep measured-topology re-plan round (ISSUE 14): vote,
    exchange link rows, derive, digest-assert + adopt. Call on EVERY
    peer at the same step boundary (the collective contract — see
    ``policy.ReplanPolicy``, which drives this on an interval); a no-op
    unless ``KF_CONFIG_REPLAN`` is on. True if a plan was adopted."""
    sess = get_default_peer().current_session()
    return sess.check_replan(want=want, min_gain=min_gain) is not None


def active_strategy() -> "Optional[Strategy]":
    """The running adaptive candidate's Strategy (the enum), or None
    under a set_tree override. ISSUE 10 satellite: this used to return
    the codec-qualified display string while its callers expected the
    Strategy — the string contract now lives in its own accessor,
    :func:`active_candidate`."""
    return get_default_peer().current_session().active_strategy()


def active_candidate() -> str:
    """Display name of the running adaptive candidate: the strategy,
    suffixed with "/<codec>" when a wire codec is active (candidates are
    (strategy, codec) pairs — an interference vote may have toggled
    compression rather than the graphs); "SET_TREE" under a set_tree
    override."""
    return get_default_peer().current_session().active_candidate_name()


def calc_stats() -> dict:
    """Per-strategy throughput stats (parity: calc_stats/log_stats ops)."""
    return get_default_peer().current_session().calc_stats()


def get_peer_latencies(samples: int = 3) -> np.ndarray:
    """RTT seconds to every peer (self = 0); parity: GetPeerLatencies op."""
    from kungfu_tpu.monitor.latency import probe_peer_latencies

    p = get_default_peer()
    sess = p.current_session()
    return probe_peer_latencies(p.client, list(sess.peers), sess.rank, samples)


def minimum_spanning_tree(weights) -> list:
    """Father array of the MST of a dense cost matrix (parity:
    MinimumSpanningTree op backed by the native Prim kernel)."""
    from kungfu_tpu.plan.mst import minimum_spanning_tree as _mst

    return _mst(weights)


_latency_probe_seq: dict = {}  # cluster version -> probes this epoch


def optimized_tree(samples: int = 3) -> list:
    """Probe latencies, allgather rows into the full matrix, and return the
    MST father array — identical on every peer (deterministic MST over the
    consensus matrix), ready for set_tree."""
    from kungfu_tpu.monitor.latency import latency_matrix_from_rows

    peer = get_default_peer()
    sess = peer.current_session()
    n = sess.size
    row = get_peer_latencies(samples)
    recv = np.zeros(n * n, np.float64)
    # KF700: back-to-back probes must not share a rendezvous name. The
    # counter is PER CLUSTER VERSION, not process-lifetime: a joiner's
    # process starts at 0 while survivors have probed for epochs — only
    # within one epoch do peers call in identical program order, so only
    # the (version, calls-this-version) pair agrees cluster-wide
    v = peer.cluster_version
    seq = _latency_probe_seq.get(v, 0)
    _latency_probe_seq[v] = seq + 1
    w = Workspace(send=row, recv=recv, op=ReduceOp.SUM,
                  name=f"kungfu::latency:v{v}:{seq}")
    sess.all_gather(w)
    matrix = latency_matrix_from_rows(list(recv.reshape(n, n)))
    return minimum_spanning_tree(matrix)


def set_tree(fathers) -> None:
    """Install a collective tree for the current epoch (parity: SetTree
    op); a resize reverts to the configured strategy — re-probe with
    optimized_tree() after membership changes."""
    get_default_peer().set_tree(fathers)


def get_neighbour(step: int) -> int:
    """Deterministic partner schedule: at step t, pair with the peer whose
    rank differs in bit position (t mod log2-ceiling) — a hypercube-style
    schedule giving each peer a distinct partner per step (capability
    parity: GetNeighbour op for PairAveraging peer selection). On
    non-power-of-two clusters an out-of-range hypercube partner falls back
    to the round-robin schedule, so the result is always a VALID peer and
    never self (the reference's GetNeighbour has the same guarantee)."""
    sess = get_default_peer().current_session()
    n, r = sess.size, sess.rank
    if n == 1:
        return 0
    bits = max(1, (n - 1).bit_length())
    partner = r ^ (1 << (step % bits))
    if partner < n:
        return partner
    # fallback: (r+1+k) % n with k <= n-2 can never wrap onto r
    return (r + 1 + step % (n - 1)) % n


def round_robin_peer(step: int) -> int:
    """Round-robin over the other peers (parity: RoundRobin op)."""
    sess = get_default_peer().current_session()
    n, r = sess.size, sess.rank
    if n == 1:
        return 0
    return (r + 1 + step % (n - 1)) % n


def egress_rates() -> "np.ndarray":
    """Per-peer egress rates (bytes/sec), rank-aligned (parity:
    EgressRates op, ops/cpu/monitoring.cpp:5-22 + sess.GetEgressRates).
    All zeros unless monitoring is on (KF_CONFIG_ENABLE_MONITORING
    truthy or KF_TELEMETRY=metrics)."""
    from kungfu_tpu.monitor.net import get_monitor

    sess = get_default_peer().current_session()
    return np.asarray(get_monitor().egress_rates(list(sess.peers)), np.float64)


_queue_ids: dict = {}
_queue_lock = threading.Lock()


def new_queue(src: int, dst: int) -> int:
    """Allocate the next queue id for the (src, dst) peer pair.

    Parity: NewQueue (ops/cpu/queue.cpp:7-44 + libkungfu-comm/queue.go):
    both endpoints call new_queue in the same program order, so each side's
    local counter yields matching ids without any wire traffic. Counters
    are scoped to the cluster epoch — after an elastic resize the rank
    space changes, so every peer restarts the pair counters from 0 (stale
    cross-epoch messages are already fenced by the transport token).
    """
    version = get_default_peer().cluster_version
    with _queue_lock:
        for k in [k for k in _queue_ids if k[0] != version]:
            del _queue_ids[k]  # only one epoch is ever live
        qid = _queue_ids.get((version, src, dst), 0)
        _queue_ids[(version, src, dst)] = qid + 1
        return qid


def queue_put(dst: int, qid: int, data) -> None:
    """Append to queue `qid` toward peer `dst` (parity: QueuePut,
    queue.cpp:47-83). `data` is bytes or a numpy array (sent raw;
    per-connection FIFO order is the queue order). Wire names carry the
    cluster version: a message left undrained in a mailbox across an
    elastic resize can never be popped by the next epoch's queue 0."""
    p = get_default_peer()
    sess = p.current_session()
    payload = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    p.client.send(
        sess.peers[dst],
        f"kungfu::queue:v{p.cluster_version}:{sess.rank}:{dst}:{qid}",
        payload,
        _ConnType.QUEUE,
    )


def queue_get(src: int, qid: int, timeout: float = 30.0) -> bytes:
    """Blocking pop from queue `qid` fed by peer `src` (parity: QueueGet)."""
    p = get_default_peer()
    sess = p.current_session()
    return p.queue.get(
        sess.peers[src],
        f"kungfu::queue:v{p.cluster_version}:{src}:{sess.rank}:{qid}",
        timeout,
    )


def save(name: str, data: bytes, version: Optional[int] = None) -> None:
    """Publish a blob to this peer's store (parity: SaveVariable). With a
    version, the blob is an immutable entry in the versioned store (GC
    window 3) — the consistency contract PairAveraging readers rely on."""
    p = get_default_peer()
    if version is None:
        p.p2p.save(name, data)
    else:
        p.p2p.save_version(version, name, data)


def request(
    rank: int, name: str, version: "Optional[int | str]" = None
) -> Optional[bytes]:
    """Fetch a blob from peer `rank`'s store (parity: RequestVariable).
    version: None = flat store; an int or "latest" = versioned store."""
    p = get_default_peer()
    sess = p.current_session()
    return p.p2p.request(sess.peers[rank], name, version=version)
