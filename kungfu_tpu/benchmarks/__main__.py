"""Allreduce throughput benchmark.

Capability parity: python -m kungfu.tensorflow.v1.benchmarks
(srcs/python/kungfu/tensorflow/v1/benchmarks/__main__.py) — measure
allreduce bus throughput over a fake model's gradient set and print
``RESULT: <v> +-<e> (GiB/s)``. Methods:
  XLA   — on-device psum over the local mesh (the ICI data plane)
  HOST  — the host-side graph-walk engine (DCN plane; run under kfrun)
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from kungfu_tpu.telemetry import log


def bench_xla(model: str, iters: int, warmup: int = 3) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from kungfu_tpu.models.fake import FAKE_MODELS
    from kungfu_tpu.ops.collective import group_all_reduce
    from kungfu_tpu.parallel import make_mesh, DeviceSession

    sizes = FAKE_MODELS[model]
    sess = DeviceSession(make_mesh())
    n = sess.size
    xs = [jnp.ones((n, s), jnp.float32) for s in sizes]
    fn = sess.spmd(
        lambda t: group_all_reduce(t, sess.axis_names[0]),
        in_specs=P(sess.axis_names[0]),
        out_specs=P(),
    )
    for _ in range(warmup):
        out = fn(xs)
    float(jax.device_get(out[0][0, 0]))  # real sync (axon: block_until_ready lies)

    samples = []
    total_bytes = sum(s * 4 for s in sizes)
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(xs)
        float(jax.device_get(out[-1][0, 0]))
        dt = time.perf_counter() - t0
        # algorithm bandwidth: 2(n-1)/n factors omitted — report bus data rate
        samples.append(total_bytes / dt / (1 << 30))
    mean, err = float(np.mean(samples)), float(1.96 * np.std(samples))
    log.echo(f"RESULT: {mean:.3f} +-{err:.3f} (GiB/s) [XLA x{n} devices, {model}]")


def _wire_samples() -> dict:
    """Per-(collective, strategy, codec) wire-byte counter values for
    THIS worker process (each worker owns its registry, so these are
    true per-peer numbers — the in-process test suite only sees
    aggregates)."""
    from kungfu_tpu.telemetry import metrics as tmetrics

    ctr = tmetrics.counter(
        "kungfu_collective_wire_bytes_total",
        "Host-plane collective payload bytes sent by this peer",
        ("collective", "strategy", "codec"),
    )
    return {labels: value for _, labels, value in ctr.samples()}


def _wire_saved() -> float:
    """Total bytes the codec kept off the wire (this peer)."""
    from kungfu_tpu.telemetry import metrics as tmetrics

    ctr = tmetrics.counter(
        "kungfu_collective_wire_saved_bytes_total",
        "Wire bytes saved by the collective codec on this peer",
        ("collective", "codec"),
    )
    return sum(value for _, _, value in ctr.samples())


def bench_host_wire_ab(model: str, iters: int, warmup: int = 4) -> None:
    """Paired same-process wire-codec A/B: measure `iters` with the
    configured codec, then toggle the codec candidate IN-PLACE on every
    worker (adaptive.advance() to candidate 1 — the same lockstep move
    an interference vote makes) and measure `iters` again. Both legs
    share one process, one session and one slice of box time, so
    run-to-run scheduler drift — which on the shared bench box exceeds
    the codec's win at resnet50 scale — cancels out of the ratio."""
    from kungfu_tpu import api
    from kungfu_tpu.models.fake import fake_gradients
    from kungfu_tpu.peer import get_default_peer

    grads = fake_gradients(model)
    outs = [np.empty_like(g) for g in grads]
    total_bytes = sum(g.nbytes for g in grads)
    sess = get_default_peer().current_session()
    legs: dict = {}
    rounds = 8  # 4 alternating rounds per mode
    per = max(2, iters // 4)
    api.run_barrier()

    def toggle() -> None:
        # lockstep flip between candidates 0 and 1 — the same
        # (strategy, codec-toggled) pair an interference vote would
        # move to; deterministic on every peer, barrier'd so no walk
        # straddles the flip (candidate 2+ would change the GRAPHS,
        # which is not what this A/B measures)
        sess.adaptive.active = 1 - sess.adaptive.active
        api.run_barrier()

    for i in range(warmup):
        api.group_all_reduce_arrays(grads, name=f"wu:{i}", outs=outs)
    for rnd in range(rounds):
        mode = sess._active_wire_mode()
        # one settle iteration after each flip: the first walk on a new
        # wire format faults in its pooled staging sizes
        api.group_all_reduce_arrays(grads, name=f"settle:{rnd}", outs=outs)
        samples = legs.setdefault(mode, [])
        for i in range(per):
            t0 = time.perf_counter()
            api.group_all_reduce_arrays(grads, name=f"ab:{rnd}:{i}", outs=outs)
            samples.append(total_bytes / (time.perf_counter() - t0) / (1 << 30))
        toggle()
    if api.current_rank() == 0:
        meds = {m: float(np.median(s)) for m, s in legs.items()}
        for m, s in legs.items():
            log.echo(
                f"RESULT: {float(np.mean(s)):.3f} "
                f"+-{float(1.96 * np.std(s)):.3f} (GiB/s) "
                f"median {meds[m]:.3f} [HOST-AB wire={m}, "
                f"x{api.cluster_size()} workers, {model}, "
                f"{len(s)} interleaved samples]"
            )
        modes = list(meds)
        if len(modes) == 2:
            on = next((m for m in modes if m != "off"), modes[0])
            off = "off" if "off" in meds else modes[1]
            log.echo(
                f"RESULT: wire={on} / wire={off} median speedup: "
                f"{meds[on] / meds[off]:.2f}x [interleaved paired, {model}]"
            )


def _simulated_backprop(grads, scratch, passes: int = 16) -> None:
    """Deterministic per-tensor FLOP load standing in for backward-pass
    compute (the bench has no real model). 16 passes of elementwise
    work per parameter is a LOW bound on a real backward pass's
    FLOP-to-gradient-bytes ratio (a conv/matmul backward touches each
    weight far more than 16 times), so the overlap this measures is the
    conservative end of what a real step offers the scheduler. Both
    legs pay the identical load, so the A/B ratio stays drift-free, and
    it never mutates the gradients — the bit-identity claim depends on
    both legs reducing the same bytes."""
    for g, s in zip(grads, scratch):
        for _ in range(passes):
            np.multiply(g, np.float32(1.0000001), out=s)


def bench_host_async_ab(model: str, iters: int, warmup: int = 4,
                        passes: int = 16) -> None:
    """Paired same-process async-scheduler A/B (ISSUE 10): the SYNC leg
    runs the serial step loop — simulate every tensor's backward
    compute, then one step-end `group_all_reduce_arrays` — while the
    ASYNC leg submits each tensor to the background scheduler the moment
    its compute finishes (readiness order: last layer first, like real
    backprop) and only flushes the tail. Legs interleave in alternating
    rounds within one process/session, so box drift cancels out of the
    ratio exactly like --wire-ab. The OVERLAP line reports the measured
    flush-wait vs engine-busy time — flush-wait ≪ walk time is the
    overlap actually happening, not inferred."""
    from kungfu_tpu import api
    from kungfu_tpu.models.fake import fake_gradients
    from kungfu_tpu.peer import get_default_peer

    grads = fake_gradients(model)
    outs = [np.empty_like(g) for g in grads]
    scratch = [np.empty_like(g) for g in grads]
    total_bytes = sum(g.nbytes for g in grads)
    sess = get_default_peer().current_session()
    if not sess.async_enabled():
        raise SystemExit(
            "--async A/B needs the scheduler: KF_CONFIG_ASYNC=on|auto "
            "must reach every worker before the session comes up (the "
            "--async flag sets it process-wide; under kfrun use "
            "KF_BENCH_ASYNC with the bench agent)"
        )
    sched = sess.scheduler()
    n = len(grads)
    legs: dict = {"sync": [], "async": []}
    rounds = 8  # 4 alternating rounds per mode
    # unlike --wire-ab, allow per=1: the async A/B pays a simulated
    # backward per sample, so bert-size sets at 16 steps blow through
    # any reasonable harness timeout — --iters controls the budget
    per = max(1, iters // 4)

    def run_sync(tag: str) -> None:
        _simulated_backprop(grads, scratch, passes)
        api.group_all_reduce_arrays(grads, name=tag, outs=outs)

    def run_async() -> None:
        # readiness order: reversed (the last layer's gradient exists
        # first); registration pins the launch order from round one, so
        # every peer walks identical bucket sequences regardless
        for i in reversed(range(n)):
            _simulated_backprop(grads[i : i + 1], scratch[i : i + 1], passes)
            api.group_all_reduce_async(
                [grads[i]], name=f"b{i}", outs=[outs[i]]
            )
        api.flush_async()

    api.run_barrier()
    for i in range(warmup):
        run_sync(f"wu:{i}")
    run_async()  # registration round + async staging warmup
    api.run_barrier()
    stats0 = sched.stats()
    for rnd in range(rounds):
        mode = "sync" if rnd % 2 == 0 else "async"
        samples = legs[mode]
        for it in range(per):
            t0 = time.perf_counter()
            if mode == "sync":
                # per-iteration names: a fast worker's next-iteration
                # sends must not be consumed by a slow worker still in
                # this one (same reason as --wire-ab's ab:{rnd}:{i})
                run_sync(f"ab:{rnd}:{it}")
            else:
                run_async()
            samples.append(
                total_bytes / (time.perf_counter() - t0) / (1 << 30)
            )
        api.run_barrier()
    stats1 = sched.stats()
    if api.current_rank() != 0:
        return
    meds = {m: float(np.median(s)) for m, s in legs.items()}
    for m, s in legs.items():
        log.echo(
            f"RESULT: {float(np.mean(s)):.3f} "
            f"+-{float(1.96 * np.std(s)):.3f} (GiB/s) "
            f"median {meds[m]:.3f} [HOST-AB async={m}, "
            f"x{api.cluster_size()} workers, {model}, "
            f"{len(s)} interleaved samples]"
        )
    log.echo(
        f"RESULT: async / sync median speedup: "
        f"{meds['async'] / meds['sync']:.2f}x [interleaved paired, "
        f"{model}, simulated backprop]"
    )
    a_rounds = max(1, stats1["rounds"] - stats0["rounds"])
    flush_wait = (stats1["flush_wait_s"] - stats0["flush_wait_s"]) / a_rounds
    busy = (stats1["busy_s"] - stats0["busy_s"]) / a_rounds
    overlap = (stats1["overlap_s"] - stats0["overlap_s"]) / a_rounds
    frac = overlap / busy if busy > 0 else 0.0
    ratio = flush_wait / busy if busy > 0 else float("inf")
    log.echo(
        f"OVERLAP {model}: flush-wait {flush_wait * 1e3:.1f} ms vs walk "
        f"{busy * 1e3:.1f} ms per step — {frac:.0%} of engine time "
        f"overlapped with backprop (flush-wait/walk {ratio:.2f})"
    )


def bench_host_zero_ab(model: str, iters: int) -> None:
    """Paired same-process ZeRO-1 A/B (ISSUE 11): the REPLICATED leg
    runs the classic step — simulated backward, step-end group
    allreduce, full-param SGD update with full-size momentum on every
    peer — while the SHARDED leg submits each tensor to the sharded
    update session as its compute finishes (reduce-scatter → 1/k shard
    update → weight all-gather, all riding the async scheduler) and
    defers the weight barrier to the TOP of the next step, so tail
    all-gathers overlap the next step's simulated backward. Legs
    interleave in alternating rounds within one process/session like
    --wire-ab, so box drift cancels out of the ratio. Reports per-leg
    RESULT throughput, the UPDATE line (full vs 1/k optimizer-update
    seconds), the STATE line (full vs shard optimizer bytes), per-leg
    WIRE lines (2·(k-1)/k·N allreduce vs (k-1)/k·N reduce-scatter +
    (k-1)/k·N[/2] weight all-gather) and the scheduler OVERLAP line."""
    from kungfu_tpu import api
    from kungfu_tpu.collective.zero import ShardedSGD, ShardedUpdateSession
    from kungfu_tpu.models.fake import fake_gradients
    from kungfu_tpu.peer import get_default_peer
    from kungfu_tpu.telemetry import metrics as tmetrics

    lr, momentum = 0.1, 0.9
    grads = fake_gradients(model)
    params_r = fake_gradients(model, seed=1)
    params_z = fake_gradients(model, seed=1)
    outs = [np.empty_like(g) for g in grads]
    scratch = [np.empty_like(g) for g in grads]
    total_bytes = sum(g.nbytes for g in grads)
    k = api.cluster_size()
    sess = get_default_peer().current_session()
    if not sess.async_enabled():
        raise SystemExit(
            "--zero A/B needs the scheduler: KF_CONFIG_ASYNC=on|auto must "
            "reach every worker before the session comes up (the --zero "
            "flag sets it process-wide; under kfrun use KF_BENCH_ZERO "
            "with the bench agent)"
        )
    zs = ShardedUpdateSession(params_z, ShardedSGD(lr, momentum),
                              name="zbench", session=sess)
    repl_opt = ShardedSGD(lr, momentum)
    repl_state = [repl_opt.init(g.size) for g in grads]
    # replicated optimizer state = full-size momentum on every peer
    # (the params themselves are its masters)
    repl_state_bytes = sum(
        a.nbytes for st in repl_state for a in st.values()
    )
    n = len(grads)
    sched = sess.scheduler()
    update_ctr = tmetrics.counter(
        "kungfu_sharded_update_seconds_total",
        "Seconds spent in the shard-local optimizer update "
        "(the k-fold-reduced update FLOPs of ZeRO-1)",
    )
    repl_update_s = 0.0

    def run_repl(tag: str) -> None:
        nonlocal repl_update_s
        _simulated_backprop(grads, scratch)
        api.group_all_reduce_arrays(grads, name=tag, outs=outs)
        t0 = time.perf_counter()
        for i in range(n):
            repl_opt.apply(params_r[i], outs[i], repl_state[i], 1.0 / k)
        repl_update_s += time.perf_counter() - t0

    def run_zero() -> None:
        # the previous step's tail weight all-gathers land while THIS
        # step's backward computes — wait only at the point the params
        # would actually be consumed
        zs.wait_params()
        for i in reversed(range(n)):  # readiness order: last layer first
            _simulated_backprop(grads[i:i + 1], scratch[i:i + 1])
            zs.submit_grad(i, grads[i])
        zs.flush()

    api.run_barrier()
    for i in range(2):
        run_repl(f"wu:{i}")
    run_zero()  # registration round + staging warmup
    api.run_barrier()
    legs: dict = {"replicated": [], "sharded": []}
    wire: dict = {"replicated": {}, "sharded": {}}
    rounds = 8
    per = max(1, iters // 4)
    stats0 = sched.stats()
    repl_update_s = 0.0
    update0 = update_ctr.value
    repl_rounds = zero_rounds = 0
    for rnd in range(rounds):
        mode = "replicated" if rnd % 2 == 0 else "sharded"
        samples = legs[mode]
        before = _wire_samples()
        for it in range(per):
            t0 = time.perf_counter()
            if mode == "replicated":
                run_repl(f"ab:{rnd}:{it}")
                repl_rounds += 1
            else:
                run_zero()
                zero_rounds += 1
            samples.append(
                total_bytes / (time.perf_counter() - t0) / (1 << 30)
            )
        if mode == "sharded":
            zs.wait_params()  # attribute the tail to the leg it belongs to
        after = _wire_samples()
        for labels, v in after.items():
            d = v - before.get(labels, 0.0)
            if d > 0:
                wire[mode][labels] = wire[mode].get(labels, 0.0) + d
        api.run_barrier()
    stats1 = sched.stats()
    zero_update_s = update_ctr.value - update0
    if api.current_rank() != 0:
        return
    meds = {m: float(np.median(s)) for m, s in legs.items()}
    for m, s in legs.items():
        log.echo(
            f"RESULT: {float(np.mean(s)):.3f} "
            f"+-{float(1.96 * np.std(s)):.3f} (GiB/s) "
            f"median {meds[m]:.3f} [HOST-AB zero={m}, "
            f"x{k} workers, {model}, {len(s)} interleaved samples]"
        )
    log.echo(
        f"RESULT: sharded / replicated median speedup: "
        f"{meds['sharded'] / meds['replicated']:.2f}x [interleaved "
        f"paired, {model}, simulated backprop]"
    )
    ru = repl_update_s / max(1, repl_rounds) * 1e3
    zu = zero_update_s / max(1, zero_rounds) * 1e3
    log.echo(
        f"UPDATE {model}: replicated {ru:.1f} ms/step vs sharded "
        f"{zu:.1f} ms/step ({ru / zu if zu > 0 else float('inf'):.1f}x "
        f"less update compute at k={k})"
    )
    mom_bytes = sum(
        a.nbytes for b in zs._buckets for a in b.state.values()
    )
    master_bytes = sum(b.master.nbytes for b in zs._buckets)
    log.echo(
        f"STATE {model}: replicated {repl_state_bytes / (1 << 20):.1f} MiB "
        f"momentum vs sharded {zs.state_bytes() / (1 << 20):.1f} MiB "
        f"(momentum {mom_bytes / (1 << 20):.1f} — {repl_state_bytes / max(1, mom_bytes):.1f}x "
        f"less — + f32 shard masters {master_bytes / (1 << 20):.1f}); "
        f"total {repl_state_bytes / max(1, zs.state_bytes()):.1f}x less per peer"
    )
    for mode in ("replicated", "sharded"):
        per_leg = max(1, per * rounds // 2)
        for labels, d in sorted(wire[mode].items()):
            per_iter = d / per_leg
            log.echo(
                f"WIRE zero={mode} {labels}: {per_iter / (1 << 20):.1f} "
                f"MiB/iter ({per_iter / total_bytes:.2f}x payload)"
            )
    a_rounds = max(1, stats1["rounds"] - stats0["rounds"])
    flush_wait = (stats1["flush_wait_s"] - stats0["flush_wait_s"]) / a_rounds
    busy = (stats1["busy_s"] - stats0["busy_s"]) / a_rounds
    overlap = (stats1["overlap_s"] - stats0["overlap_s"]) / a_rounds
    frac = overlap / busy if busy > 0 else 0.0
    log.echo(
        f"OVERLAP {model}: flush-wait {flush_wait * 1e3:.1f} ms vs engine "
        f"{busy * 1e3:.1f} ms per step — {frac:.0%} of engine time "
        f"(reduce-scatter + update + weight all-gather) overlapped with "
        f"caller compute"
    )


def bench_host_replan_ab(model: str, iters: int, warmup: int = 4,
                         decisions: bool = False) -> None:
    """Paired same-process measured-topology A/B (ISSUE 14), two legs.

    **Ring order** — run under the harness's ``KF_SHAPE_LINKS`` shape
    (e.g. one slowed edge): warm up on the NAIVE ring so the link table
    measures the shaped edges, run one lockstep re-plan round
    (``check_replan`` — vote, row exchange, pure derivation, digest-
    asserted adoption: the exact production path), then alternate
    measured-order and naive-order rounds within one process/session so
    box drift cancels out of the ratio like every other HOST A/B.

    **Weighted segments** — a compute-shaped peer (rank k-1 pays
    ``_SLOW_FACTOR``× per element of its owned shard, standing in for a
    busy/thermally-throttled host's optimizer update): alternate equal
    segments with throughput-weighted ones derived from the MEASURED
    per-peer update speed (exchanged over the ring, fed through
    ``replan.weights_from_throughput`` — the same clamp/normalize the
    vote path uses), reporting per-leg step medians and the ratio.

    ``decisions`` (ISSUE 15): feed the decision ledger the same timed
    rounds — baseline rounds before the vote, measured-leg rounds after
    — so the ``topology_replanned`` decision the adoption opens closes
    with a ledger-measured realized gain, reported as DECISIONS lines
    next to the paired-A/B headline it must agree with."""
    from kungfu_tpu import api
    from kungfu_tpu.base.ops import ReduceOp
    from kungfu_tpu.base.workspace import Workspace
    from kungfu_tpu.models.fake import fake_gradients
    from kungfu_tpu.peer import get_default_peer
    from kungfu_tpu.plan import replan as rp

    grads = fake_gradients(model)
    outs = [np.empty_like(g) for g in grads]
    total_bytes = sum(g.nbytes for g in grads)
    sess = get_default_peer().current_session()
    k, rank = sess.size, sess.rank
    api.run_barrier()
    for i in range(warmup):
        api.group_all_reduce_arrays(grads, name=f"wu:{i}", outs=outs)
    # matrix probe sweep: the naive ring only measures its own k
    # successor edges, so the planner would be blind to every edge it
    # could move ONTO. A real training run accumulates that coverage
    # from its broader traffic (broadcasts, gathers, elastic state
    # sync, strategy changes); the bench stands that in with two
    # rank-rotating 128 KiB broadcasts — every directed edge gets a
    # bandwidth estimate (two sweeps: the first send on a fresh edge
    # dials and is excluded as a sample), at ~k·(k-1)·128 KiB total
    probe = np.ones((128 << 10) // 4, np.float32)  # 128 KiB
    for sweep in range(2):
        for root in range(k):
            api.broadcast_array(
                probe, root=root, name=f"replan:probe:{sweep}:{root}"
            )
    api.run_barrier()
    ledger = None
    if decisions:
        from kungfu_tpu.telemetry import decisions as tdec

        ledger = tdec.get_ledger()
        # baseline rounds on the naive ring: the step history the
        # adoption's decision record snapshots as its BEFORE window
        for i in range(ledger.window + 1):
            t0 = time.perf_counter()
            api.group_all_reduce_arrays(grads, name=f"dbase:{i}", outs=outs)
            ledger.note_step(time.perf_counter() - t0)
    # one production re-plan round: every peer votes yes (the bench IS
    # the standing bottleneck signal), rows are exchanged, the plan is
    # derived and digest-assert adopted
    plan = sess.check_replan(want=True, min_gain=1.0)
    if api.current_rank() == 0:
        log.echo(
            f"REPLAN {model}: "
            + (
                f"adopted {plan.describe()} (predicted gain "
                f"{plan.gain:.2f}x)" if plan is not None
                else "no plan adopted (uninformative matrix — is "
                "KF_SHAPE_LINKS set and the payload above the bw gate?)"
            )
        )
    legs: dict = {"naive": [], "measured": []}
    rounds = 8
    per = max(2, iters // 4)
    for rnd in range(rounds):
        mode = "naive" if rnd % 2 == 0 else "measured"
        # lockstep toggle at a barrier, like --wire-ab's candidate flip:
        # every peer swaps the same plan, no walk straddles it
        sess._ring_plan = None if mode == "naive" else plan
        api.run_barrier()
        api.group_all_reduce_arrays(grads, name=f"settle:{rnd}", outs=outs)
        for i in range(per):
            t0 = time.perf_counter()
            api.group_all_reduce_arrays(grads, name=f"ab:{rnd}:{i}", outs=outs)
            dt = time.perf_counter() - t0
            legs[mode].append(total_bytes / dt / (1 << 30))
            if ledger is not None and mode == "measured":
                # only the post-flip configuration's rounds feed the
                # decision's AFTER window — the interleaved naive
                # rounds are the A/B's control leg, not the adopted
                # plan's steady state
                ledger.note_step(dt)
    sess._ring_plan = None
    api.run_barrier()
    if api.current_rank() == 0:
        meds = {m: float(np.median(s)) for m, s in legs.items()}
        for m, s in legs.items():
            log.echo(
                f"RESULT: {float(np.mean(s)):.3f} "
                f"+-{float(1.96 * np.std(s)):.3f} (GiB/s) "
                f"median {meds[m]:.3f} [HOST-AB ring={m}, "
                f"x{api.cluster_size()} workers, {model}, "
                f"{len(s)} interleaved samples]"
            )
        if plan is not None and meds["naive"] > 0:
            log.echo(
                f"RESULT: measured-order / naive-order median speedup: "
                f"{meds['measured'] / meds['naive']:.2f}x "
                f"[interleaved paired, {model}, shaped]"
            )
        if ledger is not None:
            recs = [r.to_json() for r in ledger.records()]
            for rec in recs:
                log.echo(
                    f"DECISIONS {model}: {rec.get('kind')} "
                    f"[{rec.get('trigger', '')}] predicted "
                    + (
                        f"{rec['predicted_gain']:.2f}x"
                        if rec.get("predicted_gain") is not None else "—"
                    )
                    + " realized "
                    + (
                        f"{rec['realized_gain']:.2f}x"
                        if rec.get("realized_gain") is not None else "—"
                    )
                    + f" verdict {rec.get('verdict') or rec.get('status')}"
                )
            closed = [
                r for r in recs
                if r.get("kind") == "topology_replanned"
                and r.get("realized_gain")
            ]
            if closed and plan is not None and meds["naive"] > 0:
                ab = meds["measured"] / meds["naive"]
                rg = closed[-1]["realized_gain"]
                log.echo(
                    f"DECISIONS {model}: ledger realized {rg:.2f}x vs "
                    f"paired-A/B {ab:.2f}x — agreement "
                    f"{abs(rg / ab - 1):.0%} (acceptance 15%)"
                )

    # ---- weighted segments vs equal, compute-shaped peer -------------
    # BOTH legs run the measured ring ORDER (when one was adopted), so
    # the shaped edge stays routed-around and the only variable is the
    # segment sizing — the lever this leg measures
    _SLOW_FACTOR = 4.0
    _COST_PER_ELEM = 400e-9  # s/element of simulated optimizer update
    n = 4 << 20  # 16 MiB f32
    base_order = plan.order if plan is not None else tuple(range(k))
    eq_plan = None if plan is None else rp.RingPlan(order=base_order)
    cost = _COST_PER_ELEM * (_SLOW_FACTOR if rank == k - 1 else 1.0)
    x = np.ones(n, np.float32)
    out = np.empty_like(x)

    def shard_step(tag: str) -> float:
        t0 = time.perf_counter()
        b, e = sess.reduce_scatter(Workspace(
            send=x, recv=out, op=ReduceOp.SUM, name=f"{tag}:rs",
        ))
        time.sleep((e - b) * cost)  # the owned-shard update
        full = np.zeros_like(x)
        full[b:e] = out[b:e]
        sess.all_gather_shards(full, f"{tag}:ag")
        dt = time.perf_counter() - t0
        api.run_barrier()
        return dt

    # measure each peer's update speed, exchange it, derive the weights
    # every peer computes identically (pure function of shared input)
    speeds = np.zeros(k, np.float32)
    speeds[rank] = np.float32(1.0 / cost)
    speeds_out = api.all_reduce_array(speeds, ReduceOp.SUM,
                                      "replan:update-speeds")
    rank_w = rp.weights_from_throughput(speeds_out.astype(np.float64))
    wplan = eq_plan
    if rank_w is not None:
        wplan = rp.RingPlan(
            order=base_order,
            weights=rp.segment_weights(base_order, rank_w),
        )
    shard_step("wu-seg")  # warmup
    seg_legs: dict = {"equal": [], "weighted": []}
    for rnd in range(rounds):
        mode = "equal" if rnd % 2 == 0 else "weighted"
        sess._ring_plan = eq_plan if mode == "equal" else wplan
        api.run_barrier()
        for i in range(per):
            seg_legs[mode].append(shard_step(f"seg:{rnd}:{i}"))
    sess._ring_plan = None
    api.run_barrier()
    if api.current_rank() == 0:
        meds = {m: float(np.median(s)) * 1e3 for m, s in seg_legs.items()}
        for m, s in seg_legs.items():
            log.echo(
                f"RESULT: {float(np.mean(s)) * 1e3:.1f} "
                f"+-{float(1.96 * np.std(s)) * 1e3:.1f} ms/step "
                f"median {meds[m]:.1f} [HOST-AB segments={m}, "
                f"x{api.cluster_size()} workers, rs+update+ag 16MiB, "
                f"slow-rank x{_SLOW_FACTOR:.0f} compute, "
                f"{len(s)} interleaved samples]"
            )
        if wplan is not None and meds["weighted"] > 0:
            log.echo(
                f"RESULT: equal / weighted median step-time ratio: "
                f"{meds['equal'] / meds['weighted']:.2f}x "
                f"[interleaved paired, compute-shaped peer]"
            )


def report_steps(model: str) -> None:
    """The --steps report (ISSUE 13): per-step critical-path summary
    from the step plane itself — overlap measured per recorded timeline
    (replacing the scheduler-side flush-wait proxy as the headline
    number; both print so drift between the two planes is visible), the
    submit→launch queue-delay fraction, and the bucket that was the
    long pole most often with its attributed edge. Rank 0 only; reads
    this worker's own /steptrace ring (the bench has no aggregator, so
    the election is over local lanes)."""
    from kungfu_tpu import api
    from kungfu_tpu.telemetry import steptrace

    if api.current_rank() != 0:
        return
    tls = steptrace.get_store().timelines()
    done = [t for t in tls if t.get("busy_us")]
    if not done:
        log.echo(
            f"STEPS {model}: no recorded step timelines (the step plane "
            "records scheduler rounds; needs KF_CONFIG_ASYNC=on|auto and "
            "KF_TELEMETRY_SPAN_SAMPLE > 0)"
        )
        return
    ov = [t["overlap_frac"] for t in done if t.get("overlap_frac") is not None]
    qd = [
        t["queue_delay_frac"] for t in done
        if t.get("queue_delay_frac") is not None
    ]
    busy_ms = sum(t["busy_us"] for t in done) / len(done) / 1e3
    flush_ms = sum(t.get("flush_wait_us") or 0 for t in done) / len(done) / 1e3
    log.echo(
        f"STEPS {model}: {len(done)} recorded steps, overlap "
        f"{sum(ov) / len(ov):.0%} (step plane)"
        + (f", queue delay {sum(qd) / len(qd):.1%}" if qd else "")
        + f", engine {busy_ms:.1f} ms vs flush-wait {flush_ms:.1f} ms per step"
    )
    # most-frequent critical bucket across the recorded steps, elected
    # with the cluster merge's own math over this worker's lanes
    wins: dict = {}
    for t in done:
        elected = steptrace.critical_path({"self": t})
        c = elected.get("critical")
        if not c:
            continue
        key = (c.get("bucket"), c.get("name"), c.get("edge"))
        agg = wins.setdefault(key, {"n": 0, "self_us": 0.0})
        agg["n"] += 1
        agg["self_us"] += c["self_us"]
    for (bucket, name, edge), agg in sorted(
        wins.items(), key=lambda kv: -kv[1]["n"]
    )[:3]:
        log.echo(
            f"STEPS critical: bucket {bucket} {name} in "
            f"{agg['n']}/{len(done)} steps, self "
            f"{agg['self_us'] / agg['n'] / 1e3:.1f} ms/step"
            + (f", edge →{edge}" if edge else "")
        )


def report_resources(model: str) -> None:
    """The --resources report (ISSUE 16): where this worker's CPU time
    actually went during the bench, from the resource plane's per-thread
    accounting — the window spans the bench because main() anchors a
    baseline sweep before dispatch. Rank 0 only; reads this worker's own
    plane (the bench has no aggregator). The ceiling line is the same
    Amdahl clamp derive_plan applies: a peer that burned cf of a core on
    compute cannot speed up more than 1/cf by re-ordering the ring, so
    a raw predicted gain above that is the r12 86x-style fiction."""
    from kungfu_tpu import api
    from kungfu_tpu.telemetry import resource

    if api.current_rank() != 0:
        return
    plane = resource.get_plane()
    if not plane.acct.supported():
        log.echo(
            f"RESOURCES {model}: /proc per-thread accounting unsupported "
            "on this platform"
        )
        return
    plane.maybe_sweep(force=True)
    doc = plane.export()
    if doc.get("sweeps", 0) < 2 or not doc.get("window_s"):
        log.echo(
            f"RESOURCES {model}: no accounting window (plane came up "
            "after the bench?)"
        )
        return
    buckets = doc.get("buckets") or {}
    parts = ", ".join(
        f"{b} {info['frac']:.0%}"
        for b in resource.BUCKETS
        for info in [buckets.get(b) or {}]
        if info.get("frac")
    )
    log.echo(
        f"RESOURCES {model}: cpu {doc.get('cpu_frac') or 0.0:.0%} of "
        f"{doc['cores']} core(s) over {doc['window_s']:.1f} s, engine "
        f"{doc.get('engine_frac') or 0.0:.0%} of busy"
        + (f" [{parts}]" if parts else "")
        + (" SATURATED" if doc.get("saturated") else "")
    )
    cf = plane.compute_frac()
    if cf > 0.0:
        log.echo(
            f"RESOURCES ceiling: compute floor {cf:.2f} clamps any "
            f"predicted re-plan gain to <= {1.0 / max(cf, 1e-6):.2f}x "
            "(derive_plan's Amdahl clamp; a raw prediction above this "
            "is unrealizable on this peer)"
        )


def report_memory(model: str) -> None:
    """The --memory report (ISSUE 17): where this worker's RSS actually
    sits after the bench, from the memory plane's registered
    accountants. Rank 0 only; reads this worker's own plane (the bench
    has no aggregator). Riding the --zero A/B this is the
    paper-replication number measured rather than computed: the
    ``zero_state`` bucket holds the sharded session's live shard bytes
    (1/k momentum + f32 shard masters), straight from the accountant
    the session registered — the STATE line's claim, asserted from the
    plane that the autoscaler actually consults."""
    from kungfu_tpu import api
    from kungfu_tpu.telemetry import memory as tmemory

    if api.current_rank() != 0:
        return
    plane = tmemory.get_plane()
    if not plane.supported():
        log.echo(
            f"MEMORY {model}: /proc RSS accounting unsupported on this "
            "platform"
        )
        return
    plane.maybe_sweep(force=True)
    doc = plane.export()
    rss = doc.get("rss_bytes")
    if not rss:
        log.echo(f"MEMORY {model}: no RSS sample (plane came up late?)")
        return
    limit = doc.get("limit_bytes")
    hf = doc.get("headroom_frac")
    buckets = doc.get("buckets") or {}
    parts = ", ".join(
        f"{b} {tmemory.fmt_bytes(info['bytes'])} ({info['frac']:.0%})"
        for b in tmemory.BUCKETS
        for info in [buckets.get(b) or {}]
        if info.get("bytes")
    )
    log.echo(
        f"MEMORY {model}: rss {tmemory.fmt_bytes(rss)}"
        + (f" of {tmemory.fmt_bytes(limit)} limit" if limit else "")
        + (
            f" ({hf:.0%} headroom)"
            if isinstance(hf, (int, float)) else ""
        )
        + (f" [{parts}]" if parts else "")
    )
    zero_names = {
        name: nbytes
        for name, nbytes in (doc.get("accountants") or {}).items()
        if name.startswith("zero:")
    }
    for name, nbytes in sorted(zero_names.items()):
        log.echo(
            f"MEMORY {model}: sharded optimizer state ({name}): "
            f"{tmemory.fmt_bytes(nbytes)} per peer, measured from the "
            "plane's accountant (1/k momentum + f32 shard masters)"
        )
    leaks = doc.get("leak_suspects") or []
    if leaks:
        log.echo(
            f"MEMORY {model}: LEAK SUSPECTS over the bench window: "
            + ", ".join(leaks)
        )


def bench_host(model: str, iters: int, warmup: int = 4) -> None:
    from kungfu_tpu import api
    from kungfu_tpu.models.fake import fake_gradients

    from kungfu_tpu.collective.host_session import get_walk_profiler

    grads = fake_gradients(model)
    outs = [np.empty_like(g) for g in grads]
    total_bytes = sum(g.nbytes for g in grads)
    api.run_barrier()
    # warmup: connection + shm-arena setup and first-touch page faults
    # belong to session bring-up, not steady-state bandwidth (the XLA
    # bench warms up identically). 4 rounds, not 2: the wire codec's
    # pooled staging buffers (wire + encode scratches) are new exact-
    # size pool bins whose first-touch ramp measurably lasts past 2
    # iterations on the bench box
    for i in range(warmup):
        api.group_all_reduce_arrays(grads, name=f"warmup:{i}", outs=outs)
    wire_before = _wire_samples()
    saved_before = _wire_saved()
    # the EFF report below must describe the measured iterations only:
    # warmup walks run on cold pools and would drag the attribution
    get_walk_profiler().reset()
    samples = []
    for i in range(iters):
        t0 = time.perf_counter()
        api.group_all_reduce_arrays(grads, name=f"bench:{i}", outs=outs)
        dt = time.perf_counter() - t0
        samples.append(total_bytes / dt / (1 << 30))
    wire_after = _wire_samples()
    saved = _wire_saved() - saved_before
    mean, err = float(np.mean(samples)), float(1.96 * np.std(samples))
    if api.current_rank() == 0:
        med = float(np.median(samples))
        log.echo(
            f"RESULT: {mean:.3f} +-{err:.3f} (GiB/s) median {med:.3f} "
            f"[HOST x{api.cluster_size()} workers, {model}]"
        )
        # per-peer wire bytes (this rank): the A/B numbers behind the
        # segmented engine (2(k-1)/k x payload vs full-payload relays)
        # and the wire codec (a further /2 on compressed series); labels
        # are (collective, strategy, codec)
        for labels, after in sorted(wire_after.items()):
            delta = after - wire_before.get(labels, 0.0)
            if delta <= 0:
                continue
            per_iter = delta / iters
            log.echo(
                f"WIRE {labels}: {per_iter / (1 << 20):.1f} MiB/iter "
                f"({per_iter / total_bytes:.2f}x payload)"
            )
        if saved > 0:
            log.echo(
                f"WIRE saved by codec: {saved / iters / (1 << 20):.1f} "
                f"MiB/iter ({saved / iters / total_bytes:.2f}x payload)"
            )
        # utilization, not just bytes (ISSUE 6): per walk family the
        # achieved throughput at the 2(k-1)/k*N bandwidth-optimal byte
        # volume, the efficiency ratio against the measured link speed
        # when the link plane has an estimate, and where the walk time
        # went (wait-on-recv / reduce+codec compute / send-blocked)
        for key, s in sorted(get_walk_profiler().snapshot().items()):
            eff = s.get("efficiency")
            eff_s = f", {eff:.2f} of link bw" if eff is not None else ""
            log.echo(
                f"EFF {key}: {s['achieved_gib_s']:.3f} GiB/s at the "
                f"2(k-1)/k bound{eff_s} "
                f"(wait {s['wait_frac']:.0%} compute {s['compute_frac']:.0%} "
                f"send {s['send_frac']:.0%}, {s['walks']} walks)"
            )
        # where the time went (hot-path spans, this process only)
        summary = api.trace_summary()
        top = sorted(summary.items(), key=lambda kv: -kv[1])[:10]
        for name, ms in top:
            log.echo(f"TRACE {name}: {ms:.0f} ms")


def bench_p2p(model: str, iters: int) -> None:
    """p2p model-request throughput (parity: kungfu-bench-p2p,
    tests/go/cmd/ — each worker fetches its ring neighbour's published
    model from the versioned store)."""
    from kungfu_tpu import api
    from kungfu_tpu.models.fake import fake_gradients

    blob = b"".join(g.tobytes() for g in fake_gradients(model))
    rank, size = api.current_rank(), api.cluster_size()
    api.save("bench-model", blob, version=0)
    api.run_barrier()
    peer = (rank + 1) % size
    samples = []
    for i in range(iters):
        t0 = time.perf_counter()
        got = api.request(peer, "bench-model", version="latest")
        dt = time.perf_counter() - t0
        assert got is not None and len(got) == len(blob)
        samples.append(len(blob) / dt / (1 << 30))
    api.run_barrier()
    mean, err = float(np.mean(samples)), float(1.96 * np.std(samples))
    if rank == 0:
        log.echo(
            f"RESULT: {mean:.3f} +-{err:.3f} (GiB/s) "
            f"[P2P x{size} workers, {model}]"
        )


def bench_gns(iters: int) -> None:
    """GNS monitoring overhead: train-step time with the plain S-SGD
    optimizer vs monitor_gradient_noise_scale wrapping the same base.

    Parity: the reference ships the harness but publishes no number
    (benchmarks/monitoring/benchmark.py, BASELINE.md row 'GNS monitoring
    overhead'). Runs a small MLP over the local device mesh."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from kungfu_tpu.models.mlp import init_mlp, mlp_loss
    from kungfu_tpu.monitor import monitor_gradient_noise_scale
    from kungfu_tpu.optimizers import synchronous_sgd
    from kungfu_tpu.parallel import DeviceSession, make_mesh
    from jax.sharding import PartitionSpec as P

    sess = DeviceSession(make_mesh())
    axis = sess.axis_names[0]
    params = init_mlp(jax.random.PRNGKey(0))
    x = jnp.ones((64 * sess.size, 784), jnp.float32)
    y = jnp.zeros((64 * sess.size,), jnp.int32)

    def make_step(opt):
        state = opt.init(params)

        def local(params, state, x, y):
            loss, grads = jax.value_and_grad(mlp_loss)(params, (x, y))
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state, lax.pmean(loss, axis)

        step = sess.spmd(
            local,
            in_specs=(P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P()),
        )
        return step, state

    def timeit(opt):
        step, state = make_step(opt)
        p = params
        for _ in range(3):
            p, state, loss = step(p, state, x, y)
        float(jax.device_get(loss))
        best = float("inf")
        for _ in range(max(3, iters // 3)):
            t0 = time.perf_counter()
            for _ in range(10):
                p, state, loss = step(p, state, x, y)
            float(jax.device_get(loss))
            best = min(best, (time.perf_counter() - t0) / 10)
        return best * 1e3

    base = optax.sgd(0.1)
    t_plain = timeit(synchronous_sgd(base, axis))
    t_gns = timeit(monitor_gradient_noise_scale(base, batch_small=64, axis_name=axis))
    log.echo(
        f"RESULT: plain {t_plain:.3f} ms/step, +GNS {t_gns:.3f} ms/step, "
        f"overhead {100 * (t_gns - t_plain) / t_plain:+.1f}% "
        f"[GNS x{sess.size} devices]"
    )


def bench_scrape(out_path: str = "BENCH_AGG_r15.json",
                 sweeps: int = 5) -> None:
    """Telemetry-plane scaling A/B (ISSUE 18): flat per-peer scraping
    vs the scaled shapes (hierarchical digest fan-in + sampled link
    matrix) against an in-process simulated fleet at k=64 and k=256.

    The fleet sits behind the aggregator's injectable transport hook —
    no sockets, so the A/B isolates exactly what the tentpole changes:
    fan-out count (k fetches vs hosts digests), root-side exposition
    parsing (k promparse passes vs pre-parsed digest docs), and the
    /cluster/links document size (full merged matrix vs the rotated
    sample + retained slowest edges). Writes the trajectory to
    ``out_path`` and prints one RESULT line per k."""
    import json
    import os
    import statistics

    from kungfu_tpu.telemetry import cluster as tcluster
    from kungfu_tpu.telemetry import decisions as tdecisions
    from kungfu_tpu.telemetry import metrics as tmetrics
    from kungfu_tpu.telemetry import steptrace as tsteptrace

    per_host, neighbors = 16, 32
    # plane documents every digest carries (hier ships these in-band;
    # without them the root would fall back to per-worker plane fetches)
    _store = tsteptrace.StepStore(keep=4)
    for _r in (1, 2):
        _rec = _store.begin_step(0, _r)
        if _rec is not None:
            _rec.finish(flush_wait_s=0.001, busy_s=0.04)
    plane_docs = {
        "steptrace": _store.export(peer="bench"),
        "decisions": tdecisions.DecisionLedger(keep=4).export(),
        "resources": {"peer": "bench", "wall_time_s": time.time()},
        "memory": {"peer": "bench", "wall_time_s": time.time()},
    }

    def make_fetch(hosts):
        labels = [
            f"h{h:02d}:{9000 + i}"
            for h in range(hosts) for i in range(per_host)
        ]
        k = len(labels)
        pages, digests = {}, {}
        # realistic exposition density: the full bucket ladder plus the
        # four per-destination link families — the root-side promparse
        # cost hier amortizes onto the per-host sub-aggregators
        buckets = ("0.005", "0.01", "0.025", "0.05", "0.1", "0.25",
                   "0.5", "1.0", "2.5", "5.0", "10.0", "+Inf")
        for idx, label in enumerate(labels):
            dsts = [labels[(idx + 1 + j) % k] for j in range(neighbors)]
            lines = [
                "# TYPE kungfu_steps_total counter",
                "kungfu_steps_total 100",
                "# TYPE kungfu_step_duration_seconds histogram",
            ]
            lines += [
                f'kungfu_step_duration_seconds_bucket{{le="{le}"}} 100'
                for le in buckets
            ]
            lines += [
                "kungfu_step_duration_seconds_sum 5.0",
                "kungfu_step_duration_seconds_count 100",
                "# TYPE kungfu_collective_latency_seconds counter",
                "kungfu_collective_latency_seconds 2.5",
                "# TYPE kungfu_egress_bytes_total counter",
                "kungfu_egress_bytes_total 1048576",
                "# TYPE kungfu_ingress_bytes_total counter",
                "kungfu_ingress_bytes_total 1048576",
                "# TYPE kungfu_peer_rtt_seconds gauge",
            ]
            lines += [
                f'kungfu_peer_rtt_seconds{{peer="{d}"}} 0.002'
                for d in dsts[:4]
            ]
            for fam, val in (
                (tcluster.LINK_BW, "1e8"),
                (tcluster.LINK_LAT, "0.002"),
                (tcluster.LINK_BYTES, "4194304"),
                (tcluster.LINK_MSGS, "64"),
            ):
                lines.append(f"# TYPE {fam} gauge")
                lines += [f'{fam}{{dst="{d}"}} {val}' for d in dsts]
            lines += [
                "# TYPE kungfu_topology_ring_position gauge",
                f"kungfu_topology_ring_position {idx}",
            ]
            pages[label] = ("\n".join(lines) + "\n").encode()
        for h in range(hosts):
            host = f"h{h:02d}"
            workers = {}
            for i in range(per_host):
                label = f"{host}:{9000 + i}"
                text = pages[label].decode()
                workers[label] = {
                    "url": f"http://{host}:{9000 + i}",
                    "metrics_text": text,
                    "parsed": tcluster.parsed_to_doc(
                        tcluster.parse_worker_page(text)
                    ),
                    "rtt_s": 1e-4,
                    "clock_offset_us": 0.0,
                    **plane_docs,
                }
            digests[host] = json.dumps({
                "enabled": True, "host": host,
                "wall_time": time.time(), "workers": workers,
            }).encode()

        plane_bodies = {
            "/steptrace": json.dumps(plane_docs["steptrace"]).encode(),
            "/decisions": json.dumps(plane_docs["decisions"]).encode(),
            "/resources": json.dumps(plane_docs["resources"]).encode(),
            "/memory": json.dumps(plane_docs["memory"]).encode(),
        }

        def fetch(base_url, path, timeout):
            hostport = base_url.split("//", 1)[1]
            endpoint = path.partition("?")[0]
            if endpoint == tcluster.HOST_DIGEST_PATH:
                return digests[hostport.split(":", 1)[0]], {}
            if endpoint == "/metrics":
                return pages[hostport], {}
            body = plane_bodies.get(endpoint)
            if body is None:
                raise OSError(f"404 {endpoint}")
            return body, {}

        targets = [
            (label, f"http://{label}") for label in labels
        ]
        return fetch, targets

    def run(hosts, scale):
        os.environ["KF_AGG_HIER_MIN_PEERS"] = "32" if scale else "0"
        fetch, targets = make_fetch(hosts)
        agg = tcluster.TelemetryAggregator(
            interval=30.0, registry=tmetrics.Registry(), fetch=fetch
        )
        agg.set_peers(targets)
        try:
            times = []
            for _ in range(sweeps):
                t0 = time.perf_counter()
                agg.scrape_once()
                times.append(time.perf_counter() - t0)
            links_bytes = len(json.dumps(agg.cluster_links()).encode())
            mode = agg.plane_envelope()["mode"]
        finally:
            agg.stop()
        return {
            "mode": mode,
            "sweep_s": round(statistics.median(times), 6),
            "links_bytes": links_bytes,
        }

    from kungfu_tpu import knobs

    saved = (
        knobs.raw("KF_AGG_HIER_MIN_PEERS")
        if knobs.is_set("KF_AGG_HIER_MIN_PEERS") else None
    )
    results = {}
    try:
        for hosts in (4, 16):  # k=64, k=256 at 16 workers/host
            k = hosts * per_host
            flat = run(hosts, scale=False)
            scaled = run(hosts, scale=True)
            entry = {
                "hosts": hosts, "workers_per_host": per_host,
                "link_neighbors": neighbors,
                "flat": flat, "scale": scaled,
                "sweep_speedup": round(
                    flat["sweep_s"] / max(scaled["sweep_s"], 1e-9), 2
                ),
                "links_payload_ratio": round(
                    flat["links_bytes"] / max(scaled["links_bytes"], 1), 2
                ),
            }
            results[f"k{k}"] = entry
            log.info(
                "RESULT scrape k=%d: sweep %.1fms -> %.1fms (%.1fx), "
                "/cluster/links %d B -> %d B (%.1fx), mode %s -> %s",
                k, flat["sweep_s"] * 1e3, scaled["sweep_s"] * 1e3,
                entry["sweep_speedup"], flat["links_bytes"],
                scaled["links_bytes"], entry["links_payload_ratio"],
                flat["mode"], scaled["mode"],
            )
    finally:
        if saved is None:
            os.environ.pop("KF_AGG_HIER_MIN_PEERS", None)
        else:
            os.environ["KF_AGG_HIER_MIN_PEERS"] = saved
    doc = {
        "bench": "telemetry-plane scrape A/B (ISSUE 18)",
        "sweeps_per_config": sweeps,
        "results": results,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    log.info("RESULT scrape trajectory written to %s", out_path)


def main() -> None:
    p = argparse.ArgumentParser("kungfu_tpu.benchmarks")
    p.add_argument("--method", choices=["XLA", "HOST", "P2P", "GNS"], default="XLA")
    p.add_argument("--model", default="resnet50-imagenet")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument(
        "--algo", choices=["auto", "tree", "segmented"], default="",
        help="HOST engine A/B: force the collective algorithm family "
        "(sets KF_CONFIG_ALGO before the session comes up; every worker "
        "runs the same argv so the override is cluster-agreed)",
    )
    p.add_argument(
        "--wire", choices=["off", "bf16", "f16", "auto", "int8", "int4"],
        default="",
        help="HOST engine A/B: wire codec for f32 payloads (sets "
        "KF_CONFIG_WIRE before the session comes up; cluster-agreed the "
        "same way as --algo). int8/int4 are the block-scaled quantized "
        "codecs (ISSUE 20) with error-feedback on the segmented paths",
    )
    p.add_argument(
        "--wire-ab", action="store_true",
        help="HOST only: paired same-process codec A/B — run --iters "
        "with the --wire codec, toggle the codec candidate in lockstep "
        "(the adaptive mechanism), run --iters again, report both "
        "medians and the drift-free speedup ratio",
    )
    p.add_argument(
        "--zero", action="store_true", dest="zero_ab",
        help="HOST only: paired same-process ZeRO-1 A/B — alternate the "
        "replicated step (group allreduce + full-param SGD, full-size "
        "momentum) with the sharded update (reduce-scatter → 1/k shard "
        "update → weight all-gather through the async scheduler; sets "
        "KF_CONFIG_ASYNC=on and KF_CONFIG_ZERO=on before the session "
        "comes up), report per-leg medians, UPDATE/STATE/WIRE lines and "
        "the OVERLAP line",
    )
    p.add_argument(
        "--steps", action="store_true", dest="steps_report",
        help="HOST only: after the bench, print the STEPS report — "
        "per-step overlap/queue-delay fractions and the most-frequent "
        "critical bucket from the step plane's recorded timelines "
        "(meaningful with --async/--zero, whose legs drive the "
        "scheduler the plane instruments)",
    )
    p.add_argument(
        "--resources", action="store_true", dest="resources_report",
        help="HOST only: after the bench, print the RESOURCES report — "
        "per-bucket CPU attribution over the bench window from the "
        "resource plane's per-thread accounting, plus the compute-floor "
        "gain ceiling derive_plan's clamp enforces (rides any A/B; "
        "KF_BENCH_RESOURCES=1 in the harness mirrors it)",
    )
    p.add_argument(
        "--memory", action="store_true", dest="memory_report",
        help="HOST only: after the bench, print the MEMORY report — the "
        "memory plane's RSS decomposition over the registered byte "
        "accountants (arena/pool/zero_state/sched_inflight/telemetry/"
        "untracked) plus headroom against the effective limit; riding "
        "--zero it reports the sharded optimizer-state bytes MEASURED "
        "from the plane (KF_BENCH_MEMORY=1 in the harness mirrors it)",
    )
    p.add_argument(
        "--passes", type=int, default=16,
        help="HOST --async only: simulated-backprop passes per tensor "
        "(compute:comm ratio of the A/B; 16 is a conservative LOW bound "
        "for real backward passes — raise it to model matmul-heavy "
        "layers, e.g. when a shaped link makes comm sleep-dominated)",
    )
    p.add_argument(
        "--replan", action="store_true", dest="replan_ab",
        help="HOST only: paired same-process measured-topology A/B "
        "(ISSUE 14) — warm up on the naive ring under the harness's "
        "KF_SHAPE_LINKS shape, adopt the measured re-plan through the "
        "production vote/exchange/digest path, then alternate "
        "measured-order vs naive-order rounds; plus the weighted-vs-"
        "equal segments A/B under a compute-shaped peer (sets "
        "KF_CONFIG_ALGO=segmented and KF_CONFIG_REPLAN=auto before the "
        "session comes up)",
    )
    p.add_argument(
        "--decisions", action="store_true", dest="decisions_report",
        help="HOST --replan only: feed the decision ledger (ISSUE 15) "
        "the same timed rounds the A/B measures and append DECISIONS "
        "report lines per adaptation (kind, predicted, realized, "
        "verdict) — the ledger-measured realized gain must agree with "
        "the paired-A/B headline within 15%%",
    )
    p.add_argument(
        "--async", action="store_true", dest="async_ab",
        help="HOST only: paired same-process async-scheduler A/B — "
        "alternate the serial step loop (compute all, then one step-end "
        "group allreduce) with readiness-ordered submission to the "
        "background scheduler (KF_CONFIG_ASYNC=on, set before the "
        "session comes up), report both medians, the drift-free speedup "
        "and the OVERLAP line (flush-wait vs walk time)",
    )
    p.add_argument(
        "--scrape", action="store_true", dest="scrape_ab",
        help="standalone telemetry-plane A/B (ISSUE 18): flat per-peer "
        "scraping vs hierarchical digests + sampled link matrix against "
        "a simulated in-process fleet at k=64 and k=256; writes the "
        "sweep-time and /cluster/links payload trajectory to "
        "--scrape-out (no TPU, no kfrun needed)",
    )
    p.add_argument(
        "--scrape-out", default="BENCH_AGG_r15.json",
        help="output path for the --scrape trajectory JSON",
    )
    args = p.parse_args()
    if args.scrape_ab:
        # pure-host telemetry bench: dispatch before any accelerator
        # path (or HOST-flag validation) runs
        bench_scrape(args.scrape_out)
        return
    if args.method != "HOST" and (
        args.algo or args.wire or args.wire_ab or args.async_ab
        or args.zero_ab or args.steps_report or args.replan_ab
        or args.resources_report or args.memory_report
    ):
        # the default method is XLA: silently measuring the wrong plane
        # is worse than an error
        p.error("--algo/--wire/--wire-ab/--async/--zero/--replan/--steps/"
                "--resources/--memory only apply to --method HOST")
    if sum(1 for f in (args.wire_ab, args.async_ab, args.zero_ab,
                       args.replan_ab) if f) > 1:
        p.error("--wire-ab/--async/--zero/--replan are separate A/Bs — "
                "pick one")
    if args.decisions_report and not args.replan_ab:
        p.error("--decisions rides the --replan A/B (the adaptation it "
                "closes with an outcome is the re-plan adoption)")
    if args.method == "HOST":
        import os

        if args.algo:
            os.environ["KF_CONFIG_ALGO"] = args.algo
        if args.wire:
            os.environ["KF_CONFIG_WIRE"] = args.wire
        if args.async_ab:
            os.environ["KF_CONFIG_ASYNC"] = "on"
        if args.zero_ab:
            os.environ["KF_CONFIG_ASYNC"] = "on"
            os.environ["KF_CONFIG_ZERO"] = "on"
        if args.replan_ab:
            # the measured plan reorders the SEGMENTED ring; every
            # worker runs the same argv so the overrides stay
            # cluster-agreed like --algo
            os.environ["KF_CONFIG_ALGO"] = "segmented"
            os.environ["KF_CONFIG_REPLAN"] = "auto"
        if args.decisions_report:
            # size the ledger's windows to the A/B's round structure
            # (per-leg rounds are few); an operator-set env still wins
            os.environ.setdefault("KF_DECISION_WINDOW", "6")
            os.environ.setdefault("KF_DECISION_SETTLE", "1")
        # wire-byte accounting rides the metrics gate; the bench wants it
        # on regardless so the A/B always reports bytes per peer
        from kungfu_tpu.telemetry import config as tconfig

        tconfig.enable("metrics")
        if args.resources_report:
            # anchor the accounting window NOW so the report's closing
            # sweep attributes exactly the benched iterations
            from kungfu_tpu.telemetry import resource as _tres

            _tres.get_plane().maybe_sweep(force=True)
        if args.memory_report:
            # same anchor for the memory plane: the baseline sweep gives
            # the trend/leak windows a pre-bench starting point
            from kungfu_tpu.telemetry import memory as _tmem

            _tmem.get_plane().maybe_sweep(force=True)
    if args.method == "XLA":
        bench_xla(args.model, args.iters)
    elif args.method == "P2P":
        bench_p2p(args.model, args.iters)
    elif args.method == "GNS":
        bench_gns(args.iters)
    elif args.wire_ab:
        bench_host_wire_ab(args.model, args.iters)
    elif args.async_ab:
        bench_host_async_ab(args.model, args.iters, passes=args.passes)
    elif args.zero_ab:
        bench_host_zero_ab(args.model, args.iters)
    elif args.replan_ab:
        bench_host_replan_ab(args.model, args.iters,
                             decisions=args.decisions_report)
    else:
        bench_host(args.model, args.iters)
    if args.method == "HOST" and args.steps_report:
        report_steps(args.model)
    if args.method == "HOST" and args.resources_report:
        report_resources(args.model)
    if args.method == "HOST" and args.memory_report:
        report_memory(args.model)


if __name__ == "__main__":
    main()
