"""Policy engine: lifecycle hooks driving adaptation decisions.

Capability parity: srcs/python/kungfu/tensorflow/policy/{base_policy,
policy_hook}.py — a BasePolicy gets before/after train/epoch/step
callbacks; the runner tracks trained samples and a mutable batch size and
stops when this worker is detached (policy_hook.py:8-77). Framework-
agnostic here: drive it from any JAX training loop.
"""

from __future__ import annotations

from typing import List, Optional


class BasePolicy:
    def before_train(self, ctx: "PolicyContext") -> None: ...

    def after_train(self, ctx: "PolicyContext") -> None: ...

    def before_epoch(self, ctx: "PolicyContext") -> None: ...

    def after_epoch(self, ctx: "PolicyContext") -> None: ...

    def before_step(self, ctx: "PolicyContext") -> None: ...

    def after_step(self, ctx: "PolicyContext") -> None: ...


class PolicyContext:
    """Mutable training-run state shared with policies."""

    def __init__(self, batch_size: int, total_samples: Optional[int] = None):
        self.batch_size = batch_size
        self.total_samples = total_samples
        self.trained_samples = 0
        self.epoch = 0
        self.step = 0
        self.metrics: dict = {}
        self.stopped = False

    def request_stop(self) -> None:
        self.stopped = True


class PolicyRunner:
    """Drives policies through a training loop.

    with PolicyRunner([p1, p2], batch_size=64) as runner:
        for epoch in ...:
            with runner.epoch():
                for batch in ...:
                    with runner.step():
                        train(batch)
                    if runner.ctx.stopped: ...
    """

    def __init__(self, policies: List[BasePolicy], batch_size: int,
                 total_samples: Optional[int] = None):
        self.policies = policies
        self.ctx = PolicyContext(batch_size, total_samples)

    def __enter__(self):
        for p in self.policies:
            p.before_train(self.ctx)
        return self

    def __exit__(self, *exc):
        for p in self.policies:
            p.after_train(self.ctx)
        return False

    def epoch(self):
        return _Scope(
            enter=lambda: [p.before_epoch(self.ctx) for p in self.policies],
            exit=lambda: (
                [p.after_epoch(self.ctx) for p in self.policies],
                setattr(self.ctx, "epoch", self.ctx.epoch + 1),
            ),
        )

    def step(self):
        def after():
            self.ctx.trained_samples += self.ctx.batch_size
            self.ctx.step += 1
            for p in self.policies:
                p.after_step(self.ctx)
            try:
                from kungfu_tpu import api

                if api.detached():
                    self.ctx.request_stop()
            except Exception:
                pass
            if (
                self.ctx.total_samples is not None
                and self.ctx.trained_samples >= self.ctx.total_samples
            ):
                self.ctx.request_stop()

        return _Scope(
            enter=lambda: [p.before_step(self.ctx) for p in self.policies],
            exit=after,
        )


class _Scope:
    def __init__(self, enter, exit):
        self._enter = enter
        self._exit = exit

    def __enter__(self):
        self._enter()
        return self

    def __exit__(self, *exc):
        self._exit()
        return False
