"""Policy engine: lifecycle hooks driving adaptation decisions.

Capability parity: srcs/python/kungfu/tensorflow/policy/{base_policy,
policy_hook}.py — a BasePolicy gets before/after train/epoch/step
callbacks; the runner tracks trained samples and a mutable batch size and
stops when this worker is detached (policy_hook.py:8-77). Framework-
agnostic here: drive it from any JAX training loop.

The monitor→adapt loop (ISSUE 2): each step the runner publishes this
worker's step timing into the telemetry registry
(``kungfu_steps_total`` + ``kungfu_step_duration_seconds``) — the raw
series the cluster aggregator scrapes for straggler detection — and
pulls the aggregator's cluster-health signals back into
``PolicyContext.metrics`` (``cluster/stragglers``,
``cluster/step_skew``, ``cluster/self_straggler``, ...) so a
``BasePolicy`` can trigger a resize or strategy switch on cross-peer
skew. See :class:`StragglerPolicy` for the canonical consumer.

The link plane (ISSUE 6) adds ``links/min_bw`` + ``links/slowest_edge``
(cluster-wide when the runner aggregator is live, else this worker's
own outgoing row) and ``collective/efficiency`` +
``collective/wait_frac`` from the walk profiler — the measured inputs
for straggler-adaptive topology re-planning and the async collective
scheduler (ROADMAP items 2/5).

The step plane (ISSUE 13) adds ``step/critical_peer`` +
``step/critical_edge`` (cluster-wide only — electing a critical peer
needs every peer's timeline) and ``step/overlap_frac`` +
``step/queue_delay_frac`` (worker-local fallback from this worker's own
step timelines, overridden by the cluster merge) — per-step measured
attribution, the inputs ROADMAP items 2 (measured-topology re-planning)
and 5 (profile-fed submit priorities) consume.

The decision ledger (ISSUE 15) closes the adaptation loop on itself:
``PolicyRunner.step()`` feeds each step's wall-clock duration to the
ledger (the measurement substrate of every adaptation's realized gain)
and ``decision/last_kind`` + ``decision/last_realized_gain`` +
``decision/regressed`` surface the latest measured outcome — the trust
signals an unattended autoscaler (ROADMAP item 4) needs before it can
act without an operator.

The resource plane (ISSUE 16) adds ``resource/cpu_frac`` +
``resource/engine_frac`` + ``resource/saturated`` (worker-local
per-thread CPU attribution, overridden by the cluster merge) and
``resource/saturated_peers`` (cluster-wide only) — the measured
compute-side inputs that tell a policy whether a slow peer is
network-bound (re-plan around it) or compute-bound (shed it).

The memory plane (ISSUE 17) adds ``memory/headroom_frac`` +
``memory/pressure`` + ``memory/leak_suspect`` (worker-local byte
attribution and OOM-headroom forecast, overridden by the cluster
merge) and ``memory/min_headroom_peer`` + ``memory/min_headroom_frac``
(cluster-wide only) — the grow-gate inputs ROADMAP item 3's unattended
autoscaler consults before proposing a bigger cluster.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from kungfu_tpu import knobs
from kungfu_tpu.telemetry import log


class BasePolicy:
    def before_train(self, ctx: "PolicyContext") -> None: ...

    def after_train(self, ctx: "PolicyContext") -> None: ...

    def before_epoch(self, ctx: "PolicyContext") -> None: ...

    def after_epoch(self, ctx: "PolicyContext") -> None: ...

    def before_step(self, ctx: "PolicyContext") -> None: ...

    def after_step(self, ctx: "PolicyContext") -> None: ...


class PolicyContext:
    """Mutable training-run state shared with policies."""

    def __init__(self, batch_size: int, total_samples: Optional[int] = None):
        self.batch_size = batch_size
        self.total_samples = total_samples
        self.trained_samples = 0
        self.epoch = 0
        self.step = 0
        self.metrics: dict = {}
        self.stopped = False

    def request_stop(self) -> None:
        self.stopped = True


class PolicyRunner:
    """Drives policies through a training loop.

    with PolicyRunner([p1, p2], batch_size=64) as runner:
        for epoch in ...:
            with runner.epoch():
                for batch in ...:
                    with runner.step():
                        train(batch)
                    if runner.ctx.stopped: ...
    """

    # refresh cluster signals into ctx.metrics at most this often; the
    # underlying fetch is TTL-cached too, so a step costs a float compare
    CLUSTER_SIGNAL_PERIOD = 2.0

    def __init__(self, policies: List[BasePolicy], batch_size: int,
                 total_samples: Optional[int] = None):
        self.policies = policies
        self.ctx = PolicyContext(batch_size, total_samples)
        self._step_t0 = 0.0
        self._signals_at = 0.0
        # step-time publication: the per-worker series behind the cluster
        # plane's straggler detection; gated once, zero-cost when off
        self._m_steps = self._m_step_hist = None
        from kungfu_tpu.telemetry import config as _tcfg

        if _tcfg.metrics_enabled():
            from kungfu_tpu.telemetry import metrics as _tm

            self._m_steps = _tm.counter(
                "kungfu_steps_total",
                "Training steps completed by this worker",
            )
            self._m_step_hist = _tm.histogram(
                "kungfu_step_duration_seconds",
                "Wall-clock duration of each training step",
            )

    def _pull_cluster_signals(self) -> None:
        """Merge the link-plane/profiler signals and the aggregator's
        cluster-health signals into ctx.metrics (throttled; absent
        plane = no-op). Worker-local signals land first so the
        cluster-wide view — when a runner aggregator is live — wins on
        the shared ``links/*`` keys."""
        now = time.monotonic()
        if now - self._signals_at < self.CLUSTER_SIGNAL_PERIOD:
            return
        self._signals_at = now
        try:
            # this worker's own view: its outgoing-link row
            # (links/min_bw, links/slowest_edge) and the collective
            # critical-path profile (collective/efficiency, wait_frac).
            # Evict the previous refresh's values FIRST: a source that
            # went quiet (e.g. the only estimated peer departed and was
            # pruned) returns {} and must take its stale signals with it
            # — a frozen links/min_bw steering re-planning hours later
            # is the exact staleness LinkTable.prune exists to prevent
            from kungfu_tpu.collective.host_session import get_walk_profiler
            from kungfu_tpu.telemetry import decisions as _tdec
            from kungfu_tpu.telemetry import link as _link
            from kungfu_tpu.telemetry import memory as _tmem
            from kungfu_tpu.telemetry import resource as _tres
            from kungfu_tpu.telemetry import steptrace as _steptrace

            for key in ("links/min_bw", "links/slowest_edge",
                        "collective/efficiency", "collective/wait_frac",
                        "step/overlap_frac", "step/queue_delay_frac",
                        "step/critical_peer", "step/critical_edge",
                        "decision/last_kind", "decision/last_realized_gain",
                        "decision/regressed",
                        "resource/cpu_frac", "resource/engine_frac",
                        "resource/saturated", "resource/saturated_peers",
                        "memory/headroom_frac", "memory/pressure",
                        "memory/leak_suspect", "memory/min_headroom_peer",
                        "memory/min_headroom_frac"):
                self.ctx.metrics.pop(key, None)
            if _link.enabled():
                self.ctx.metrics.update(_link.get_table().signals())
            self.ctx.metrics.update(get_walk_profiler().signals())
            # step plane (ISSUE 13): this worker's own overlap/queue
            # fractions — the cluster-wide merge (which alone can name
            # step/critical_peer + step/critical_edge) overrides these
            # below when the runner aggregator is live
            self.ctx.metrics.update(_steptrace.get_store().local_signals())
            # decision ledger (ISSUE 15): the latest measured adaptation
            # outcome, worker-local (decisions fire on every peer)
            self.ctx.metrics.update(_tdec.get_ledger().signals())
            # resource plane (ISSUE 16): this worker's own CPU
            # attribution — the cluster merge overrides the shared
            # resource/* keys below when a runner aggregator is live
            self.ctx.metrics.update(_tres.get_plane().signals())
            # memory plane (ISSUE 17): this worker's own headroom and
            # leak verdicts — same cluster-override precedence
            self.ctx.metrics.update(_tmem.get_plane().signals())
        except Exception as e:  # noqa: BLE001 - telemetry must never kill training
            log.debug("policy: walk/link signal refresh failed: %s", e)
        try:
            from kungfu_tpu import monitor

            signals = monitor.cluster_health()
        except Exception as e:  # noqa: BLE001 - telemetry must never kill training
            log.debug("policy: cluster health fetch failed: %s", e)
            return
        if signals:
            self.ctx.metrics.update(signals)

    def __enter__(self):
        for p in self.policies:
            p.before_train(self.ctx)
        return self

    def __exit__(self, *exc):
        for p in self.policies:
            p.after_train(self.ctx)
        return False

    def epoch(self):
        return _Scope(
            enter=lambda: [p.before_epoch(self.ctx) for p in self.policies],
            exit=lambda: (
                [p.after_epoch(self.ctx) for p in self.policies],
                setattr(self.ctx, "epoch", self.ctx.epoch + 1),
            ),
        )

    def step(self):
        def before():
            self._step_t0 = time.perf_counter()
            for p in self.policies:
                p.before_step(self.ctx)

        def after():
            dt = time.perf_counter() - self._step_t0
            if self._m_steps is not None:
                self._m_steps.inc()
                self._m_step_hist.observe(dt)
            # decision ledger (ISSUE 15): the per-step durations are the
            # measurement substrate every adaptation's realized gain is
            # computed from — fire-and-forget, a deque append when no
            # decision is measuring
            from kungfu_tpu.telemetry import decisions as _tdec

            _tdec.note_step(dt)
            self._pull_cluster_signals()
            self.ctx.trained_samples += self.ctx.batch_size
            self.ctx.step += 1
            for p in self.policies:
                p.after_step(self.ctx)
            try:
                from kungfu_tpu import api

                if api.detached():
                    self.ctx.request_stop()
            except Exception as e:  # noqa: BLE001 - detach check is advisory
                log.debug("policy: detach check failed: %s", e)
            if (
                self.ctx.total_samples is not None
                and self.ctx.trained_samples >= self.ctx.total_samples
            ):
                self.ctx.request_stop()

        return _Scope(enter=before, exit=after)


class StragglerPolicy(BasePolicy):
    """Adaptation on cluster skew: when the cluster plane flags the same
    straggler for `patience` consecutive signal refreshes, invoke
    `on_straggler(ctx, peers)` — typically an `api.resize(size-1)` to
    shed the slow peer, or a strategy switch away from topologies rooted
    on it. The default action just records the decision in ctx.metrics
    (``cluster/straggler_action_pending``) so embedders can act in the
    training loop, where collective calls are safe.
    """

    def __init__(
        self,
        patience: int = 3,
        on_straggler: Optional[Callable[["PolicyContext", List[str]], None]] = None,
    ):
        self.patience = patience
        self.on_straggler = on_straggler
        self._seen: dict = {}  # peer -> consecutive flags
        self._last_update = None

    def after_step(self, ctx: "PolicyContext") -> None:
        flagged = ctx.metrics.get("cluster/stragglers")
        if flagged is None:
            return
        # count once per signal REFRESH, not once per step (steps are
        # orders of magnitude faster than scrapes). The refresh marker
        # is cluster/updated_at — a steady straggler produces identical
        # flag lists every refresh, so content can't mark freshness.
        update = ctx.metrics.get("cluster/updated_at")
        if update is not None and update == self._last_update:
            return
        self._last_update = update
        self._seen = {
            p: self._seen.get(p, 0) + 1 for p in flagged
        }
        persistent = sorted(
            p for p, n in self._seen.items() if n >= self.patience
        )
        if not persistent:
            return
        if self.on_straggler is not None:
            self.on_straggler(ctx, persistent)
        else:
            ctx.metrics["cluster/straggler_action_pending"] = persistent
        self._seen = {p: 0 for p in self._seen}


class ReplanPolicy(BasePolicy):
    """Measured-topology re-planning driver (ISSUE 14 / ROADMAP item 2):
    watches the measured network signals — ``links/slowest_edge`` /
    ``links/min_bw`` from the link plane and ``step/critical_edge`` from
    the step plane — and, when the SAME edge keeps being named for
    ``patience`` consecutive signal refreshes, votes to re-derive the
    ring from the measured matrix via ``HostSession.check_replan``.

    The check itself is a lockstep collective round, so it runs every
    ``interval_steps`` steps ON EVERY PEER regardless of this peer's
    local suspicion (peers that see nothing vote no; the majority
    decides — the same shape as the interference vote). Steps advance in
    lockstep under synchronous training, which is what makes the step
    counter a valid cross-peer gate. The switch lands at a step
    boundary: call it from ``after_step`` (this class) or anywhere no
    walk is in flight.

    ``KF_CONFIG_REPLAN`` (cluster-agreed) gates the whole machinery:
    with it ``off`` (the default) ``check_replan`` is a local no-op and
    this policy never runs a collective. On adoption the session emits a
    ``topology_replanned`` audit event naming old→new order and the
    predicted gain; ``ctx.metrics['replan/last_order']`` mirrors it for
    embedders.

    Under the sampled link matrix (ISSUE 18) a row may be several
    sweeps old; re-planning a ring off decayed measurements is worse
    than keeping the current one. When the cluster plane publishes
    ``links/oldest_row_age_s`` and it exceeds ``max_row_age_s``
    (default ``KF_AGG_LINK_MAX_AGE_S``; 0 disables the gate) this peer
    refuses to VOTE yes — the ``check_replan`` collective still runs
    in lockstep so peers with fresh data stay in sync.

    Adaptive demotion (ISSUE 19, ``KF_CONFIG_REPLAN=hier`` only): the
    planner's segment weights are bandwidth-only, so this policy feeds
    the OTHER measured planes in — ``step/critical_peer`` +
    ``step/critical_edge`` (who the cluster keeps waiting on),
    ``resource/saturated_peers`` and ``cluster/straggler_causes`` (WHY:
    network vs compute vs memory). A peer that stays elected critical
    for ``demote_patience`` consecutive closed ledger windows with a
    cause ≠ network (a slow LINK is the flat re-planner's job; demotion
    is for peers that are themselves the bottleneck) is proposed into
    the demoted role via ``HostSession.check_demote`` — a lockstep
    majority vote, run every ``interval_steps`` on every peer exactly
    like ``check_replan``. Demoted = zero-weight segments + excluded
    from the inter-host ring, still receiving results by broadcast.
    The adoption opens a ``peer_demoted`` decision record; the ledger
    grades it against measured step times, and if it lands in
    ``decision/regressed`` this policy votes the peer straight back
    (rollback). A demoted peer that stays un-flagged for
    ``demote_patience`` clean windows is promoted back on recovery."""

    # a straggler cause that re-planning/demotion treats as transient
    # network weather — routed around, never demoted for
    NETWORK_CAUSES = ("network", "unknown", None, "")

    def __init__(
        self,
        interval_steps: int = 32,
        patience: int = 3,
        min_gain: float = 1.05,
        session_supplier: Optional[Callable[[], object]] = None,
        max_row_age_s: Optional[float] = None,
        demote_patience: Optional[int] = None,
    ):
        if interval_steps < 1:
            raise ValueError("interval_steps must be >= 1")
        self.interval_steps = interval_steps
        self.patience = patience
        self.min_gain = min_gain
        if max_row_age_s is None:
            try:
                max_row_age_s = float(knobs.get("KF_AGG_LINK_MAX_AGE_S"))
            except (TypeError, ValueError):
                max_row_age_s = 60.0
        self.max_row_age_s = max_row_age_s
        if demote_patience is None:
            try:
                demote_patience = int(knobs.get("KF_REPLAN_DEMOTE_PATIENCE"))
            except (TypeError, ValueError):
                demote_patience = 3
        self.demote_patience = max(1, demote_patience)
        self._session_supplier = session_supplier
        self._edge = None  # the persistently-named edge being watched
        self._streak = 0
        self._last_update = None
        # demotion watch (ISSUE 19): per-peer counts of closed ledger
        # windows spent elected critical (with a demotable cause) /
        # spent clean while demoted — the patience substrate
        self._crit_windows: dict = {}   # peer label -> windows critical
        self._clean_windows: dict = {}  # rank -> windows un-flagged
        self._window_mark = 0           # ctx.step at last window close
        self._demote_update = None

    def _session(self):
        if self._session_supplier is not None:
            return self._session_supplier()
        try:
            from kungfu_tpu.peer import get_default_peer

            return get_default_peer().current_session()
        except Exception as e:  # noqa: BLE001 - no peer = nothing to re-plan
            log.debug("replan policy: no session: %s", e)
            return None

    def _observe(self, ctx: "PolicyContext") -> None:
        """Track how long the same measured edge has been the named
        bottleneck. Counted once per signal REFRESH when the cluster
        plane stamps one (cluster/updated_at — the StragglerPolicy
        discipline), else once per step off the worker-local signals."""
        edge = ctx.metrics.get("step/critical_edge")
        if edge is None:
            slowest = ctx.metrics.get("links/slowest_edge")
            edge = slowest[-1] if isinstance(slowest, (list, tuple)) and slowest else None
        if edge is None:
            return
        update = ctx.metrics.get("cluster/updated_at")
        if update is not None and update == self._last_update:
            return
        self._last_update = update
        edge = str(edge)
        if edge == self._edge:
            self._streak += 1
        else:
            self._edge, self._streak = edge, 1

    @staticmethod
    def _rank_of(sess, label) -> Optional[int]:
        peers = getattr(sess, "peers", None)
        if peers is None or label is None:
            return None
        try:
            from kungfu_tpu.plan.peer import PeerID

            return peers.rank(PeerID.parse(str(label)))
        except Exception as e:  # noqa: BLE001 - unparseable label = unknown peer
            log.debug("replan policy: unmappable peer label %r: %s", label, e)
            return None

    @staticmethod
    def _label_of(sess, rank: int) -> Optional[str]:
        peers = getattr(sess, "peers", None)
        try:
            return str(peers[rank]) if peers is not None else None
        except Exception as e:  # noqa: BLE001 - rank outside the peer list
            log.debug("replan policy: no label for rank %s: %s", rank, e)
            return None

    def _observe_demotion(self, ctx: "PolicyContext", sess) -> None:
        """Close a demotion-patience window: one ledger measurement
        window (``DecisionLedger.window`` steps) with a fresh cluster
        refresh. Inside each closed window, count whether the SAME
        peer stayed elected critical with a demotable cause — and, for
        already-demoted peers, whether they stayed clean (the recovery
        counter promotion keys off)."""
        from kungfu_tpu.telemetry import decisions as _tdec

        window = max(1, int(getattr(_tdec.get_ledger(), "window", 16)))
        if ctx.step - self._window_mark < window:
            return
        update = ctx.metrics.get("cluster/updated_at")
        if update is not None and update == self._demote_update:
            return  # no fresh cluster view: the window cannot close
        self._window_mark = ctx.step
        self._demote_update = update
        crit = ctx.metrics.get("step/critical_peer")
        causes = ctx.metrics.get("cluster/straggler_causes") or {}
        saturated = set(ctx.metrics.get("resource/saturated_peers") or ())
        demotable = crit is not None and (
            causes.get(crit) not in self.NETWORK_CAUSES
            or crit in saturated  # direct compute measurement
        )
        if demotable:
            self._crit_windows = {
                crit: self._crit_windows.get(crit, 0) + 1
            }
        else:
            # a clean window (or a network-caused one) breaks the streak
            self._crit_windows.clear()
        flagged = set(ctx.metrics.get("cluster/stragglers") or ())
        demoted = tuple(getattr(sess, "demoted_peers", tuple)())
        self._clean_windows = {
            r: (
                self._clean_windows.get(r, 0) + 1
                if (lbl := self._label_of(sess, r)) is not None
                and lbl not in flagged and lbl != crit
                else 0
            )
            for r in demoted
        }

    def _demote_proposal(self, ctx: "PolicyContext", sess):
        """(demote_rank, promote_rank) this peer votes for — either may
        be None; the lockstep majority decides."""
        demoted = set(getattr(sess, "demoted_peers", tuple)())
        promote = None
        regressed = ctx.metrics.get("decision/regressed") or []
        if "peer_demoted" in regressed and demoted:
            # the ledger measured the demotion throughput-hostile:
            # roll it back rather than wait out a recovery
            promote = min(demoted)
        else:
            clean = sorted(
                r for r, n in self._clean_windows.items()
                if n >= self.demote_patience and r in demoted
            )
            if clean:
                promote = clean[0]
        demote = None
        for label, n in sorted(self._crit_windows.items()):
            if n < self.demote_patience:
                continue
            rank = self._rank_of(sess, label)
            if rank is not None and rank not in demoted and rank != promote:
                demote = rank
                break
        return demote, promote

    def after_step(self, ctx: "PolicyContext") -> None:
        self._observe(ctx)
        if ctx.step == 0 or ctx.step % self.interval_steps:
            return
        sess = self._session()
        if sess is None or getattr(sess, "size", 1) < 2:
            return
        want = self._streak >= self.patience
        if want and self.max_row_age_s > 0:
            # sampled-matrix staleness gate (ISSUE 18): don't vote to
            # re-plan off link rows older than the knob — the collective
            # still runs so fresh peers stay in lockstep
            age = ctx.metrics.get("links/oldest_row_age_s")
            if isinstance(age, (int, float)) and age > self.max_row_age_s:
                want = False
                ctx.metrics["replan/vote_withheld_stale_links"] = age
        plan = sess.check_replan(want=want, min_gain=self.min_gain)
        if plan is not None:
            # adopted: restart the watch window against the new topology
            self._edge, self._streak = None, 0
            ctx.metrics["replan/last_order"] = list(plan.order)
            ctx.metrics["replan/predicted_gain"] = plan.gain
        # adaptive demotion (ISSUE 19): a second lockstep round, run on
        # every peer at the same step boundary exactly like the re-plan
        # vote (check_demote is a no-op collective-free return outside
        # KF_CONFIG_REPLAN=hier, which is cluster-agreed)
        if getattr(sess, "replan_mode", "") == "hier" \
                and hasattr(sess, "check_demote"):
            self._observe_demotion(ctx, sess)
            demote, promote = self._demote_proposal(ctx, sess)
            adopted = sess.check_demote(demote=demote, promote=promote)
            if adopted is not None:
                self._crit_windows.clear()
                self._clean_windows.clear()
                now_demoted = [
                    int(r) for r in getattr(sess, "demoted_peers", tuple)()
                ]
                ctx.metrics["replan/demoted"] = now_demoted
                ctx.metrics["replan/last_order"] = list(adopted.order)


class PrecisionPolicy(BasePolicy):
    """Adaptive wire-precision driver (ISSUE 20): chooses the collective
    codec — bf16 / int8 / int4 — from the measured gradient noise scale
    and votes flips through ``HostSession.check_precision``, a lockstep
    majority round run every ``interval_steps`` on EVERY peer exactly
    like the re-plan and interference votes (peers with no opinion vote
    to keep the current mode; the majority decides).

    The signal: ``kungfu_noise_scale`` (McCandlish B_noise, published by
    ``monitor.noise_scale.publish_noise_scale`` from an on-device psum —
    identical on every peer) relative to the actual batch size. When
    B_noise >> B the minibatch gradient is already dominated by sampling
    noise, so block-scaled quantization noise (bounded by half a scale
    step per element — docs/collectives.md) is negligible and the wire
    can drop to int8, then int4; when B_noise falls toward B the
    gradient is informative and the policy votes back up to bf16.
    ``monitor/noise_scale`` in ``ctx.metrics`` overrides the gauge when
    an embedder or the cluster plane supplies it.

    A target must persist for ``patience`` consecutive vote rounds
    before this peer proposes it — one noisy estimate never flips the
    cluster. Every adopted flip opens a ``precision_switch`` decision
    record; if the ledger closes it ``regressed`` (throughput- or
    accuracy-hostile: step times got worse), the policy votes straight
    back to the pre-flip mode and then holds ``cooldown_intervals``
    vote rounds before proposing another downshift — the rollback
    contract that makes an aggressive downshift safe to try."""

    def __init__(
        self,
        interval_steps: int = 32,
        patience: int = 3,
        int8_ratio: float = 8.0,
        int4_ratio: float = 64.0,
        cooldown_intervals: int = 8,
        session_supplier: Optional[Callable[[], object]] = None,
    ):
        if interval_steps < 1:
            raise ValueError("interval_steps must be >= 1")
        if not (int4_ratio >= int8_ratio > 0):
            raise ValueError("need int4_ratio >= int8_ratio > 0")
        self.interval_steps = interval_steps
        self.patience = max(1, patience)
        self.int8_ratio = float(int8_ratio)
        self.int4_ratio = float(int4_ratio)
        self.cooldown_intervals = max(0, cooldown_intervals)
        self._session_supplier = session_supplier
        self._want: Optional[str] = None  # the persistent target watched
        self._streak = 0
        self._flip_old: Optional[str] = None  # mode before our last flip
        self._cooldown = 0

    def _session(self):
        if self._session_supplier is not None:
            return self._session_supplier()
        try:
            from kungfu_tpu.peer import get_default_peer

            return get_default_peer().current_session()
        except Exception as e:  # noqa: BLE001 - no peer = nothing to vote on
            log.debug("precision policy: no session: %s", e)
            return None

    def _target(self, ctx: "PolicyContext", signals: dict) -> Optional[str]:
        """The mode this peer believes the measured noise justifies, or
        None when no (finite, positive) noise estimate is available."""
        noise = ctx.metrics.get("monitor/noise_scale")
        if noise is None:
            try:
                from kungfu_tpu.telemetry import metrics as _tm

                m = _tm.get_registry().get("kungfu_noise_scale")
                noise = m.value if m is not None else None
            except Exception as e:  # noqa: BLE001 - metrics plane optional
                log.debug("precision policy: no noise gauge: %s", e)
                noise = None
        batch = ctx.batch_size
        if not isinstance(noise, (int, float)) or not noise > 0 or batch <= 0:
            return None
        ratio = float(noise) / float(batch)
        signals["noise_scale"] = float(noise)
        signals["batch_size"] = int(batch)
        signals["noise_ratio"] = ratio
        if ratio >= self.int4_ratio:
            return "int4"
        if ratio >= self.int8_ratio:
            return "int8"
        return "bf16"

    def after_step(self, ctx: "PolicyContext") -> None:
        if ctx.step == 0 or ctx.step % self.interval_steps:
            return
        sess = self._session()
        if (
            sess is None
            or getattr(sess, "size", 1) < 2
            or not hasattr(sess, "check_precision")
        ):
            return
        current = sess.active_wire_mode()
        if self._cooldown > 0:
            self._cooldown -= 1
        proposal: Optional[str] = None
        trigger = "noise_scale"
        signals: dict = {}
        regressed = ctx.metrics.get("decision/regressed") or []
        if "precision_switch" in regressed and self._flip_old is not None \
                and self._flip_old != current:
            # the ledger measured our flip hostile: vote straight back
            proposal = self._flip_old
            trigger = "regression_rollback"
        else:
            target = self._target(ctx, signals)
            if target is not None and target == self._want:
                self._streak += 1
            else:
                self._want = target
                self._streak = 1 if target is not None else 0
            wants_flip = (
                target is not None
                and target != current
                and self._streak >= self.patience
            )
            if wants_flip and self._cooldown > 0:
                ctx.metrics["precision/vote_withheld_cooldown"] = \
                    self._cooldown
                wants_flip = False
            if wants_flip:
                proposal = target
        # the vote is a lockstep collective: run it EVERY interval on
        # every peer, opinion or not — a silent peer would hang the rest
        new = sess.check_precision(
            proposal, trigger=trigger, signals=signals or None
        )
        if new is not None:
            if trigger == "regression_rollback":
                # rolled back: don't re-roll the rollback, and hold off
                # further downshift proposals for the cooldown window
                self._flip_old = None
                self._cooldown = self.cooldown_intervals
            else:
                self._flip_old = current
            self._streak = 0
            ctx.metrics["precision/active"] = new


class _Scope:
    def __init__(self, enter, exit):
        self._enter = enter
        self._exit = exit

    def __enter__(self):
        self._enter()
        return self

    def __exit__(self, *exc):
        self._exit()
        return False
