"""Ring attention: exact causal self-attention over a sequence-sharded
mesh axis.

Long-context parity goal (SURVEY §7 / BASELINE north star): the reference
has no sequence parallelism at all; TPU-native long-context training needs
attention over sequences larger than one chip's memory. This is the ring
algorithm (Liu et al., "Ring Attention with Blockwise Transformers"): each
device holds one sequence block of Q/K/V; K/V blocks rotate around the
ring via `ppermute` while each device accumulates its queries' attention
over every block with an online (flash-style) softmax, streaming each
held block through in blk_k-sized sub-tiles — peak memory is
O(S_local x blk_k) scores instead of O(S^2), and the ring rides the ICI
bidirectionally.

Runs INSIDE a `shard_map` over the sequence axis. Accumulation is f32
regardless of input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # finite: exp(NEG_INF - NEG_INF) must be well-defined


def ring_self_attention(q, k, v, axis_name: str, axis_size: int,
                        causal: bool = True, blk_k: int = 1024):
    """Exact attention for sequence-sharded q, k, v of shape
    (B, H, S_local, head_dim); the global sequence is axis_size * S_local
    with device i (by `lax.axis_index`) holding block i. Returns the
    (B, H, S_local, head_dim) context in q's dtype.

    Within each ring step the held K/V block streams through in
    `blk_k`-sized sub-blocks (an inner online-softmax scan), so the score
    tensor is (S_local, blk_k) instead of (S_local, S_local) — the
    "blockwise transformers" half of the ring-attention paper. At
    S_local=8192, B=1, H=8 that is a 2 GiB dense f32 score buffer vs
    256 MiB tiled at blk_k=1024; for S_local <= blk_k the loop has one
    iteration and this is exactly the r4 formulation. A ragged
    S_local % blk_k shrinks blk_k to the largest divisor so streaming is
    never silently abandoned."""
    B, H, Sl, hd = q.shape
    out_dtype = q.dtype
    idx = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    qpos = idx * Sl + jnp.arange(Sl)[:, None]  # (Sl, 1) global query pos

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    blk_k = min(blk_k, Sl)
    while Sl % blk_k:
        blk_k -= 1  # largest divisor of Sl <= requested blk_k
    n_sub = Sl // blk_k

    def sub_accumulate(k_sub, v_sub, kpos, m, l, o):
        """One (Sl, blk_k) score tile of the online softmax."""
        scores = (
            jnp.einsum("bhqd,bhkd->bhqk", qf, k_sub.astype(jnp.float32))
            * scale
        )
        if causal:
            mask = kpos <= qpos  # (Sl, blk_k)
            scores = jnp.where(mask, scores, NEG_INF)
            maskf = mask.astype(jnp.float32)
        else:
            maskf = jnp.ones(scores.shape[-2:], jnp.float32)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        # p is explicitly zeroed on masked entries: when a tile is fully
        # masked m_new stays NEG_INF and exp(scores - m_new) would be 1
        p = jnp.exp(scores - m_new) * maskf
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v_sub.astype(jnp.float32))
        return m_new, l, o

    def accumulate(k_blk, v_blk, blk, m, l, o):
        if n_sub == 1:
            kpos = blk * Sl + jnp.arange(Sl)[None, :]
            return sub_accumulate(k_blk, v_blk, kpos, m, l, o)
        k_subs = k_blk.reshape(B, H, n_sub, blk_k, hd).transpose(2, 0, 1, 3, 4)
        v_subs = v_blk.reshape(B, H, n_sub, blk_k, hd).transpose(2, 0, 1, 3, 4)

        def body(carry, inp):
            m, l, o = carry
            k_sub, v_sub, j = inp
            kpos = blk * Sl + j * blk_k + jnp.arange(blk_k)[None, :]
            return sub_accumulate(k_sub, v_sub, kpos, m, l, o), None

        (m, l, o), _ = lax.scan(
            body, (m, l, o), (k_subs, v_subs, jnp.arange(n_sub))
        )
        return m, l, o

    def body(step, carry):
        # rotate FIRST (permute-before-compute): steps 1..n-1 do exactly
        # n-1 ring rotations total — a rotate-after-compute loop would do
        # one extra ppermute whose result is discarded, and XLA does not
        # DCE collectives inside a while-loop body
        k_blk, v_blk, m, l, o = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        # after `step` rotations we hold the block that STARTED at
        # (idx - step); its global positions follow from that block id
        blk = (idx - step) % axis_size
        m, l, o = accumulate(k_blk, v_blk, blk, m, l, o)
        return k_blk, v_blk, m, l, o

    m0 = jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl, 1), jnp.float32)
    o0 = jnp.zeros((B, H, Sl, hd), jnp.float32)
    m, l, o = accumulate(k, v, idx, m0, l0, o0)  # step 0: own block
    _, _, _, l, o = lax.fori_loop(1, axis_size, body, (k, v, m, l, o))
    # causal attention always has >= 1 unmasked key (the diagonal), so l>0
    return (o / l).astype(out_dtype)
