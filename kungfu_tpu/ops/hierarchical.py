"""Hierarchical allreduce: ICI psum within a slice x host-plane allreduce
across slices — the multi-slice data path.

Capability parity: the reference's bridged hierarchical collective
(srcs/cpp/src/tensorflow/ops/gpu/collective.cpp:108-162 — local NCCL
reduce, CPU cross-host allreduce, local NCCL bcast; cross strategies
srcs/go/kungfu/session/strategy.go:188-210). TPU mapping: each kfrun
worker owns one jax world (a slice / ICI domain); gradient sync composes

  1. ``lax.pmean`` over the in-world mesh axis (XLA collective on ICI),
  2. a host-plane allreduce across worlds (DCN), entered from INSIDE the
     jitted step via ``jax.experimental.io_callback`` so the training step
     stays one compiled program per world.

Semantics: hierarchical mean — mean over worlds of the in-world mean.
With equal-sized worlds this equals the global mean over all replicas
(exactly, when the addends are exactly representable; to rounding
otherwise, like any reassociated float sum).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.experimental import io_callback
from jax.sharding import PartitionSpec as P

from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.workspace import Workspace


class CrossSliceReducer:
    """Host-side cross-world gradient averaging, callable from io_callback.

    Keeps a per-instance step counter so every collective round gets fresh
    wire names (all worlds advance in lockstep — the host collective
    itself is the synchronizer). Leaves are fused per dtype into one
    workspace each, reduced concurrently via the session group op, and a
    single division by the world count lands after the wire SUM (the
    reference's reduce-then-scale order)."""

    def __init__(self, peer=None, name: str = "hier", compress: str = ""):
        """compress="bf16": f32/f64 leaves cross the DCN wire as bfloat16
        (half/quarter the bytes; the in-slice ICI psum stays full
        precision, so only the CROSS-slice term is rounded — the standard
        gradient-compression trade for bandwidth-bound DCN links).
        Integer and already-half-precision leaves pass through."""
        self._peer = peer
        self.name = name
        self.step = 0
        if compress not in ("", "bf16"):
            raise ValueError(f"unknown compression {compress!r}")
        self.compress = compress

    def _session(self):
        if self._peer is None:
            from kungfu_tpu.peer import get_default_peer

            self._peer = get_default_peer()
        return self._peer.current_session()

    def __call__(self, *leaves: np.ndarray) -> List[np.ndarray]:
        sess = self._session()
        step = self.step
        self.step += 1
        n = sess.size
        if n <= 1:
            return [np.asarray(l) for l in leaves]
        arrs = [np.ascontiguousarray(l) for l in leaves]
        orig_dtypes = [a.dtype for a in arrs]
        if self.compress == "bf16":
            import ml_dtypes

            arrs = [
                a.astype(ml_dtypes.bfloat16)
                if np.issubdtype(a.dtype, np.floating) and a.dtype.itemsize > 2
                else a
                for a in arrs
            ]
        outs = [np.empty_like(a) for a in arrs]
        ws = [
            Workspace(
                send=a.reshape(-1),
                recv=o.reshape(-1),
                op=ReduceOp.SUM,
                name=f"kungfu::hier:{self.name}:{step}:{i}",
            )
            for i, (a, o) in enumerate(zip(arrs, outs))
        ]
        sess.group_all_reduce(ws)
        return [self._mean(o, n, dt) for o, dt in zip(outs, orig_dtypes)]

    @staticmethod
    def _mean(o: np.ndarray, n: int, out_dtype=None) -> np.ndarray:
        """sum/n, cast ONCE to out_dtype (default: o's dtype) — the
        compressed path divides the bf16 wire sum at f32 precision and
        lands directly in the original f32/f64 without an intermediate
        bf16 rounding. NOTE the branch check must be issubdtype(...,
        integer), not floating: ml_dtypes bfloat16 has numpy kind 'V', so
        a floating-check would send bf16 down the integer floor-division
        branch and zero out sub-1.0 gradient sums."""
        if out_dtype is None:
            out_dtype = o.dtype
        if np.issubdtype(o.dtype, np.integer):
            return (o // n).astype(out_dtype, copy=False)
        if o.dtype.itemsize < 4:
            # bf16/f16/f8 wire sums: divide at f32 precision
            return (o.astype(np.float32) / np.float32(n)).astype(
                out_dtype, copy=False
            )
        return (o / o.dtype.type(n)).astype(out_dtype, copy=False)


def cross_slice_mean(tree, reducer: CrossSliceReducer):
    """Average a pytree across worlds on the host plane, from inside jit.

    Call OUTSIDE any shard_map region (on replicated values) so the
    callback fires once per world per step, not once per device. The
    callback is pinned to device 0 (XLA's SPMD partitioner refuses a
    REPLICATED side-effecting custom-call); XLA inserts the gather/
    broadcast around the pinned call."""
    from jax.sharding import SingleDeviceSharding

    leaves, treedef = jax.tree.flatten(tree)
    shapes = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    # ordered=False: the ordered variant threads a replicated token that
    # XLA's partitioner rejects next to a device-pinned custom-call. One
    # callback per step + a data dependency on its results gives the
    # needed sequencing anyway (steps are serialized by the param chain).
    out = io_callback(
        reducer,
        shapes,
        *leaves,
        ordered=False,
        sharding=SingleDeviceSharding(jax.devices()[0]),
    )
    return jax.tree.unflatten(treedef, out)


def make_hier_train_step(
    loss_fn: Callable,
    opt: optax.GradientTransformation,
    mesh,
    axis_name: str = "dp",
    peer=None,
    name: str = "hier",
    batch_spec: Optional[P] = None,
    donate: bool = False,
    compress: str = "",
):
    """One jitted S-SGD step with hierarchical gradient sync.

    loss_fn(params, batch) -> scalar loss, evaluated per-shard inside a
    shard_map over `axis_name`; gradients are pmean'd over the in-world
    mesh (ICI), then averaged across worlds on the host plane, then the
    optax update applies identically in every world.
    """
    from kungfu_tpu.parallel._compat import shard_map

    reducer = CrossSliceReducer(peer=peer, name=name, compress=compress)
    bspec = batch_spec if batch_spec is not None else P(axis_name)

    def local_grads(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = jax.tree.map(lambda g: lax.pmean(g, axis_name), grads)
        return lax.pmean(loss, axis_name), grads

    sharded_grads = shard_map(
        local_grads,
        mesh=mesh,
        in_specs=(P(), bspec),
        out_specs=(P(), P()),
        check_vma=False,
    )

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = sharded_grads(params, batch)
        grads = cross_slice_mean(grads, reducer)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if donate:
        step = jax.jit(step.__wrapped__, donate_argnums=(0, 1))
    return step


def synchronous_sgd_hierarchical(
    base: optax.GradientTransformation,
    axis_name: str = "dp",
    peer=None,
    name: str = "hier-ssgd",
) -> optax.GradientTransformation:
    """S-SGD whose gradient averaging is hierarchical (in-world pmean +
    cross-world host allreduce). Use inside shard_map ONLY via
    make_hier_train_step; as a bare optax transformation it must run on
    replicated values (the cross-world callback fires per call site)."""
    reducer = CrossSliceReducer(peer=peer, name=name)

    def init(params):
        return base.init(params)

    def update(grads, state, params=None, **extra):
        grads = jax.tree.map(lambda g: lax.pmean(g, axis_name), grads)
        grads = cross_slice_mean(grads, reducer)
        return base.update(grads, state, params, **extra)

    return optax.GradientTransformation(init, update)
