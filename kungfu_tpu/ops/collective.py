"""Device collectives: XLA ops over ICI mesh axes.

Capability parity: the reference's collective op kernels
(srcs/cpp/src/tensorflow/ops/cpu/collective.cpp, gpu/collective.cpp and the
python wrappers srcs/python/kungfu/tensorflow/ops/collective.py). On TPU
these are not graph-walks over TCP nor NCCL calls: each op lowers to an XLA
collective (AllReduce / AllGather / CollectivePermute) that rides the ICI
torus inside a compiled program. XLA's static schedule subsumes the
reference's NCCL scheduler (srcs/cpp/src/nccl/scheduler.cpp) — cross-worker
op order is fixed at compile time, so no runtime order negotiation exists.

All functions here must be called inside a `shard_map`/`pmap` context where
`axis_name` is bound. The fuse/defuse helpers mirror the reference's tensor
packing (ops/__init__.py:29-46) and are pure reshapes that XLA fuses away.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kungfu_tpu.base.ops import ReduceOp

_PSUM_OPS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MIN: lax.pmin,
    ReduceOp.MAX: lax.pmax,
}


def all_reduce(x: jax.Array, axis_name: str = "dp", op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """AllReduce one array over a mesh axis. SUM/MIN/MAX lower to a single
    XLA AllReduce; PROD via exp/log is intentionally unsupported — the
    reference only uses SUM/MIN/MAX on device."""
    try:
        fn = _PSUM_OPS[op]
    except KeyError:
        raise ValueError(f"unsupported device reduce op: {op!r}") from None
    return fn(x, axis_name)


def all_average(x: jax.Array, axis_name: str = "dp") -> jax.Array:
    return lax.pmean(x, axis_name)


def group_all_reduce(xs, axis_name: str = "dp", op: ReduceOp = ReduceOp.SUM):
    """AllReduce a pytree of arrays (one logical call; XLA may combine the
    AllReduces — the analogue of the reference's group_all_reduce)."""
    return jax.tree.map(lambda x: all_reduce(x, axis_name, op), xs)


def group_all_average(xs, axis_name: str = "dp"):
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), xs)


def all_gather(x: jax.Array, axis_name: str = "dp", axis: int = 0, tiled: bool = False) -> jax.Array:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def broadcast(x: jax.Array, axis_name: str = "dp", root: int = 0) -> jax.Array:
    """Broadcast root's value to all ranks on the axis.

    Lowered as a masked psum (one XLA AllReduce) — the standard XLA idiom;
    replaces the reference's broadcast graph walk.
    """
    idx = lax.axis_index(axis_name)
    zero = jnp.zeros_like(x)
    return lax.psum(jnp.where(idx == root, x, zero), axis_name)


def group_broadcast(xs, axis_name: str = "dp", root: int = 0):
    return jax.tree.map(lambda x: broadcast(x, axis_name, root), xs)


def subset_all_reduce(
    x: jax.Array,
    mask: jax.Array,
    axis_name: str = "dp",
) -> jax.Array:
    """AllReduce over a subset of ranks (capability parity with
    KungfuSubsetAllReduce, ops/cpu/collective.cpp:105-147).

    mask: bool/int array indexed by rank on the axis; ranks with mask==0
    contribute zero and receive the subset sum. On TPU a static subset is
    better expressed as a smaller mesh axis; this dynamic-mask form supports
    elastic subsets without recompilation.
    """
    idx = lax.axis_index(axis_name)
    m = mask[idx].astype(x.dtype)
    return lax.psum(x * m, axis_name)


# ---------------------------------------------------------------------------
# fuse / defuse: pack a list of tensors into one flat buffer and back.
# ---------------------------------------------------------------------------

def fuse(xs: Sequence[jax.Array]) -> jax.Array:
    """Concatenate raveled tensors (reference fuse, ops/__init__.py:29-34)."""
    return jnp.concatenate([jnp.ravel(x) for x in xs])


def defuse(fused: jax.Array, shapes: Sequence[Tuple[int, ...]]) -> List[jax.Array]:
    """Split a fused buffer back into tensors of the given shapes."""
    out = []
    off = 0
    for shape in shapes:
        size = int(np.prod(shape)) if shape else 1
        out.append(jnp.reshape(fused[off:off + size], shape))
        off += size
    return out


def fuse_pytree(tree):
    """Pack a pytree into (flat_vector, unflatten_fn)."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    fused = fuse(leaves)

    def unflatten(vec):
        parts = defuse(vec, shapes)
        parts = [p.astype(dt) for p, dt in zip(parts, dtypes)]
        return jax.tree.unflatten(treedef, parts)

    return fused, unflatten
