from kungfu_tpu.ops.collective import (
    all_gather,
    all_reduce,
    broadcast,
    defuse,
    fuse,
    group_all_reduce,
    subset_all_reduce,
)
from kungfu_tpu.ops.hierarchical import (
    CrossSliceReducer,
    cross_slice_mean,
    make_hier_train_step,
    synchronous_sgd_hierarchical,
)
from kungfu_tpu.ops.flash_attention import flash_attention
from kungfu_tpu.ops.moe import moe_ffn, switch_moe
from kungfu_tpu.ops.ring_attention import ring_self_attention

__all__ = [
    "all_gather",
    "all_reduce",
    "broadcast",
    "defuse",
    "fuse",
    "group_all_reduce",
    "subset_all_reduce",
    "CrossSliceReducer",
    "cross_slice_mean",
    "make_hier_train_step",
    "synchronous_sgd_hierarchical",
    "ring_self_attention",
    "moe_ffn",
    "switch_moe",
    "flash_attention",
]
