from kungfu_tpu.ops.collective import (
    all_gather,
    all_reduce,
    broadcast,
    defuse,
    fuse,
    group_all_reduce,
    subset_all_reduce,
)

__all__ = [
    "all_gather",
    "all_reduce",
    "broadcast",
    "defuse",
    "fuse",
    "group_all_reduce",
    "subset_all_reduce",
]
