"""Pallas flash attention: fused causal self-attention for the MXU.

The hot op done as a TPU kernel (pallas_guide.md playbook): per (batch x
head, q-block) grid program, the q tile stays in VMEM while K/V stream
through block by block with an online (flash) softmax — the (S, S) score
matrix never materializes in HBM, so peak memory is O(BLK_Q x S_block)
instead of O(S^2). Causal programs stop at their diagonal block (the
upper-triangular half is never computed at all).

Differentiable via custom_vjp: the forward runs the kernel; the backward
recomputes attention with the dense formulation under jax.vjp (correct
everywhere; a fused flash backward kernel is a further optimization, not
a semantic difference).

Off-TPU the kernel runs in interpret mode so the same code path is
testable on the CPU meshes used by this repo's test suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _dense_reference(q, k, v, causal: bool, sm_scale: float):
    S = q.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _chunked_reference(q, k, v, causal: bool, sm_scale: float,
                       blk_k: int = 512):
    """Differentiable online-softmax attention as a lax.scan over K/V
    blocks, each scan step rematerialized (jax.checkpoint): identical
    math to the dense formulation, but the (S, S) score tensor never
    exists in either the forward OR the saved-residual set — the flash
    backward runs through jax.vjp of THIS, keeping training memory
    O(S x BLK_K) per head."""
    B, H, S, hd = q.shape
    blk_k = min(blk_k, S)
    if S % blk_k:
        return _dense_reference(q, k, v, causal, sm_scale)
    qf = q.astype(jnp.float32)
    n_kb = S // blk_k
    kb_ = k.reshape(B, H, n_kb, blk_k, hd).transpose(2, 0, 1, 3, 4)
    vb_ = v.reshape(B, H, n_kb, blk_k, hd).transpose(2, 0, 1, 3, 4)
    qpos = lax.broadcasted_iota(jnp.int32, (S, blk_k), 0)

    @jax.checkpoint
    def body(carry, inp):
        m, l, acc = carry
        kb, vb, kb_idx = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32)) * sm_scale
        if causal:
            kpos = kb_idx * blk_k + lax.broadcasted_iota(
                jnp.int32, (S, blk_k), 1
            )
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)
            maskf = mask.astype(jnp.float32)
        else:
            maskf = 1.0
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * maskf
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhqk,bhkd->bhqd", p,
                                      vb.astype(jnp.float32))
        return (m_new, l, acc), None

    m0 = jnp.full((B, H, S, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, S, hd), jnp.float32)
    (m, l, acc), _ = lax.scan(
        body, (m0, l0, acc0), (kb_, vb_, jnp.arange(n_kb))
    )
    return (acc / l).astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, blk_k: int, causal: bool,
            sm_scale: float):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (BLK_Q, hd)
    blk_q, hd = q.shape
    S = k_ref.shape[1]
    qi = pl.program_id(1)
    q_off = qi * blk_q

    n_kb = S // blk_k
    if causal:
        # stop at the diagonal block: keys beyond q_off + blk_q - 1 are
        # always masked
        n_kb_eff = lax.min(n_kb, (q_off + blk_q + blk_k - 1) // blk_k)
    else:
        n_kb_eff = n_kb

    qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            kpos = kb * blk_k + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1
            )
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)
            maskf = mask.astype(jnp.float32)
        else:
            maskf = 1.0
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * maskf
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((blk_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    acc0 = jnp.zeros((blk_q, hd), jnp.float32)
    _, l, acc = lax.fori_loop(0, n_kb_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _forward(q, k, v, causal: bool, sm_scale: float, blk_q: int,
             blk_k: int, interpret) -> jnp.ndarray:
    from jax.experimental import pallas as pl

    B, H, S, hd = q.shape
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        # degenerate shapes: correctness beats fusion
        return _dense_reference(q, k, v, causal, sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qf = q.reshape(B * H, S, hd)
    kf = k.reshape(B * H, S, hd)
    vf = v.reshape(B * H, S, hd)
    out = pl.pallas_call(
        functools.partial(_kernel, blk_k=blk_k, causal=causal,
                          sm_scale=sm_scale),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        grid=(B * H, S // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, hd), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, hd), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, S, hd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float = None,
                    blk_q: int = 512, blk_k: int = 512, interpret=None):
    """Fused causal attention for (B, H, S, hd) q/k/v; drop-in for the
    transformer's pluggable attention core:

        _block(x, layer, cfg, core=lambda q, k, v: flash_attention(q, k, v))

    Measured on a v5e chip (bf16, B=2 H=8 hd=64, defaults): beats XLA's
    fused dense attention from S ~= 2048 (1.1x) to S = 4096 (1.4x), and
    its O(BLK_Q x S) working set keeps growing sequences off the HBM
    cliff that the dense (S, S) score tensor hits. Below ~2k sequence
    length XLA dense wins — use the default dense core there.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _forward(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    out = _forward(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)
    return out, (q, k, v)


def _bwd(causal, sm_scale, blk_q, blk_k, interpret, res, g):
    q, k, v = res
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    # memory-efficient backward: vjp through the remat-chunked formulation
    # (identical math; no (S, S) tensor in residuals or recompute)
    _, vjp = jax.vjp(
        lambda q, k, v: _chunked_reference(q, k, v, causal, sm_scale, blk_k),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
