"""Pallas flash attention: fused causal self-attention for the MXU.

The hot op done as a TPU kernel (pallas_guide.md playbook): per (batch x
head, q-block) grid program, the q tile stays in VMEM while K/V stream
through block by block with an online (flash) softmax — the (S, S) score
matrix never materializes in HBM, so peak memory is O(BLK_Q x S_block)
instead of O(S^2). Causal programs stop at their diagonal block (the
upper-triangular half is never computed at all).

Differentiable via custom_vjp: the forward kernel also emits the per-row
log-sum-exp, and the backward runs two fused Pallas kernels (dq over
k-blocks; dk/dv over q-blocks) that recompute exact block probabilities
from it — the standard two-pass flash backward. Neither direction ever
materializes an (S, S) tensor. Shapes the grid can't tile fall back to
a q-chunk-rematerialized formulation (`_chunked_reference`) under
jax.vjp — identical math, same memory bound.

Off-TPU the kernel runs in interpret mode so the same code path is
testable on the CPU meshes used by this repo's test suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _dense_reference(q, k, v, causal: bool, sm_scale: float):
    S = q.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _chunked_reference(q, k, v, causal: bool, sm_scale: float,
                       blk_q: int = 512, blk_k: int = 512):
    """Differentiable online-softmax attention with bounded memory:
    `lax.map` over Q-CHUNKS, each chunk wrapped in `jax.checkpoint`.

    Per chunk, an inner k-block scan runs the flash recurrence; the
    checkpoint boundary means the outer map's saved residuals are just
    the chunk inputs (O(S x hd) total), and the inner scan's per-step
    carries exist only transiently during that chunk's backward
    (O(S/blk_k x blk_q x hd)). Scanning k-blocks at FULL q (the naive
    layout) would be wrong: scan's VJP saves the (S, hd) acc carry per
    k-step — Theta(S^2 hd / blk_k), a quadratic bill hidden in
    residuals. The flash backward runs through jax.vjp of this."""
    B, H, S, hd = q.shape
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        return _dense_reference(q, k, v, causal, sm_scale)
    n_qb, n_kb = S // blk_q, S // blk_k
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    kb_ = kf.reshape(B, H, n_kb, blk_k, hd).transpose(2, 0, 1, 3, 4)
    vb_ = vf.reshape(B, H, n_kb, blk_k, hd).transpose(2, 0, 1, 3, 4)

    @jax.checkpoint
    def one_chunk(args):
        qc, q_off = args  # (B, H, blk_q, hd), scalar block offset
        qcf = qc.astype(jnp.float32)
        qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)

        def body(carry, inp):
            m, l, acc = carry
            kb, vb, kb_idx = inp
            s = jnp.einsum("bhqd,bhkd->bhqk", qcf, kb) * sm_scale
            if causal:
                kpos = kb_idx * blk_k + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1
                )
                mask = kpos <= qpos
                s = jnp.where(mask, s, NEG_INF)
                maskf = mask.astype(jnp.float32)
            else:
                maskf = 1.0
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new) * maskf
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * corr + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
            return (m_new, l, acc), None

        m0 = jnp.full((B, H, blk_q, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, blk_q, 1), jnp.float32)
        acc0 = jnp.zeros((B, H, blk_q, hd), jnp.float32)
        (_, l, acc), _ = lax.scan(
            body, (m0, l0, acc0), (kb_, vb_, jnp.arange(n_kb))
        )
        return acc / l

    q_chunks = q.reshape(B, H, n_qb, blk_q, hd).transpose(2, 0, 1, 3, 4)
    offsets = jnp.arange(n_qb) * blk_q
    out = lax.map(one_chunk, (q_chunks, offsets))  # (n_qb, B, H, blk_q, hd)
    out = out.transpose(1, 2, 0, 3, 4).reshape(B, H, S, hd)
    return out.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
            blk_q: int, blk_k: int, causal: bool, sm_scale: float):
    """One (bh, q-block, k-block) grid program. The TPU grid runs the
    LAST dimension sequentially on one core, so the (m, l, acc) flash
    accumulators live in VMEM scratch across the k-block sweep; K/V
    arrive one block at a time via BlockSpec streaming — VMEM holds
    O(blk) state regardless of S."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    qi = pl.program_id(1)
    n_kb = pl.num_programs(2)
    q_off = qi * blk_q
    k_off = kb * blk_k

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    # causal: blocks fully above the diagonal contribute nothing
    live = (k_off <= q_off + blk_q - 1) if causal else (kb >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)
            maskf = mask.astype(jnp.float32)
        else:
            maskf = 1.0
        m = m_scr[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * maskf
        corr = jnp.exp(m - m_new)
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:, :1] = m_new

    @pl.when(kb == n_kb - 1)
    def _finalize():
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)
        # log-sum-exp per row: the backward recomputes exact block probs
        # as exp(s - lse) without re-running the online max/sum recurrence.
        # Stored 8-lane-replicated: Mosaic wants the last block dim ==
        # the array dim (8) and the stats are sublane-oriented anyway,
        # so this layout round-trips with zero relayouts.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(l_scr[:, :1]), lse_ref[0].shape
        )


def _kv_index(blk_q, blk_k, causal, b, i, j):
    if not causal:
        return (b, j, 0)
    diag = (i * blk_q + blk_q - 1) // blk_k  # last live k-block for q-block i
    return (b, jnp.minimum(j, diag), 0)


def _forward(q, k, v, causal: bool, sm_scale: float, blk_q: int,
             blk_k: int, interpret, with_lse: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        # degenerate shapes: correctness beats fusion
        out = _dense_reference(q, k, v, causal, sm_scale)
        return (out, None) if with_lse else out
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qf = q.reshape(B * H, S, hd)
    kf = k.reshape(B * H, S, hd)
    vf = v.reshape(B * H, S, hd)
    out, lse = pl.pallas_call(
        functools.partial(_kernel, blk_q=blk_q, blk_k=blk_k, causal=causal,
                          sm_scale=sm_scale),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 8), jnp.float32),
        ],
        grid=(B * H, S // blk_q, S // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, hd), lambda b, i, j: (b, i, 0)),
            # causal: clamp the K/V block index at the q-block's diagonal
            # so dead above-diagonal blocks repeat the previous index and
            # Pallas skips their HBM fetch entirely (pl.when already
            # skips their compute)
            pl.BlockSpec((1, blk_k, hd), functools.partial(_kv_index, blk_q, blk_k, causal)),
            pl.BlockSpec((1, blk_k, hd), functools.partial(_kv_index, blk_q, blk_k, causal)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, hd), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, 8), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 128), jnp.float32),  # m (lane-replicated col 0)
            pltpu.VMEM((blk_q, 128), jnp.float32),  # l
            pltpu.VMEM((blk_q, hd), jnp.float32),  # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(B, H, S, hd)
    if with_lse:
        return out, lse  # (B*H, S, 8), lane-replicated
    return out


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref,
               dq_scr, *, blk_q: int, blk_k: int, causal: bool,
               sm_scale: float):
    """dQ: per (bh, q-block) program, k-blocks stream sequentially.
    Block probs are recomputed exactly from the saved row LSE (standard
    two-pass flash backward), so no (S, S) tensor exists anywhere:
        p  = exp(q k^T * scale - lse)
        ds = p * (dO v^T - delta)
        dq += ds @ k * scale
    """
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    qi = pl.program_id(1)
    n_kb = pl.num_programs(2)
    q_off = qi * blk_q
    k_off = kb * blk_k

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr[...])

    live = (k_off <= q_off + blk_q - 1) if causal else (kb >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = dl_ref[0][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            p = jnp.where(kpos <= qpos, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32) * sm_scale

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref,
                dv_ref, dk_scr, dv_scr, *, blk_q: int, blk_k: int,
                causal: bool, sm_scale: float):
    """dK/dV: per (bh, k-block) program, q-blocks stream sequentially:
        p   = exp(q k^T * scale - lse)
        dv += p^T @ dO
        ds  = p * (dO v^T - delta)
        dk += ds^T @ q * scale
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    kj = pl.program_id(1)
    n_qb = pl.num_programs(2)
    q_off = qi * blk_q
    k_off = kj * blk_k

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    live = (q_off + blk_q - 1 >= k_off) if causal else (qi >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = dl_ref[0][:, :1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            p = jnp.where(kpos <= qpos, p, 0.0)
        dv_scr[...] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _q_index(blk_q, blk_k, causal, b, j, i):
    """dK/dV grid: clamp dead above-diagonal q-block fetches at the
    k-block's first live q-block (mirror of _kv_index)."""
    if not causal:
        return (b, i, 0)
    lo = (j * blk_k) // blk_q
    return (b, jnp.maximum(i, lo), 0)


def _q_index2(blk_q, blk_k, causal, b, j, i):
    if not causal:
        return (b, i, 0)
    lo = (j * blk_k) // blk_q
    return (b, jnp.maximum(i, lo), 0)


def _backward_kernels(q, k, v, o, lse, g, causal, sm_scale, blk_q, blk_k,
                      interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # delta = rowsum(dO * O): one fused elementwise+reduce pass, XLA's
    # job; 8-lane-replicated to match the LSE layout (see _finalize)
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (B, H, S)
    qf = q.reshape(B * H, S, hd)
    kf = k.reshape(B * H, S, hd)
    vf = v.reshape(B * H, S, hd)
    gf = g.reshape(B * H, S, hd)
    lsef = lse  # (B*H, S, 8) straight from the forward kernel
    deltaf = jnp.broadcast_to(
        delta.reshape(B * H, S)[:, :, None], (B * H, S, 8)
    )

    q_spec = pl.BlockSpec((1, blk_q, hd), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec(
        (1, blk_k, hd), functools.partial(_kv_index, blk_q, blk_k, causal)
    )
    row_spec = pl.BlockSpec((1, blk_q, 8), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, blk_q=blk_q, blk_k=blk_k,
                          causal=causal, sm_scale=sm_scale),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        grid=(B * H, S // blk_q, S // blk_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((blk_q, hd), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, deltaf)

    qi_spec = pl.BlockSpec(
        (1, blk_q, hd), functools.partial(_q_index, blk_q, blk_k, causal)
    )
    row_i_spec = pl.BlockSpec(
        (1, blk_q, 8), functools.partial(_q_index2, blk_q, blk_k, causal)
    )
    kj_spec = pl.BlockSpec((1, blk_k, hd), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, blk_q=blk_q, blk_k=blk_k,
                          causal=causal, sm_scale=sm_scale),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, hd), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, hd), v.dtype),
        ],
        grid=(B * H, S // blk_k, S // blk_q),
        in_specs=[qi_spec, kj_spec, kj_spec, qi_spec, row_i_spec, row_i_spec],
        out_specs=[kj_spec, kj_spec],
        scratch_shapes=[
            pltpu.VMEM((blk_k, hd), jnp.float32),
            pltpu.VMEM((blk_k, hd), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, deltaf)

    shape = (B, H, S, hd)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float = None,
                    blk_q: int = 512, blk_k: int = 512, interpret=None):
    """Fused causal attention for (B, H, S, hd) q/k/v; drop-in for the
    transformer's pluggable attention core:

        _block(x, layer, cfg, core=lambda q, k, v: flash_attention(q, k, v))

    Forward AND backward are Pallas kernels (two-pass flash backward:
    dq streams k-blocks, dk/dv stream q-blocks, block probs recomputed
    from the forward's saved row log-sum-exp). Measured fwd+bwd on a
    v5e chip (bf16, B=2 H=8 hd=64, defaults — BENCH_FLASH_r05.json):
    1.25x XLA dense at S=1024, ~parity at 2048, 1.3x at 4096, 2.1x at
    8192; at 16384 dense OOMs on the (S, S) score tensor while this
    kernel's working set stays O(BLK x S).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _forward(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    S = q.shape[2]
    if S % min(blk_q, S) or S % min(blk_k, S):
        # degenerate shapes: dense forward, remat-chunked vjp backward
        out = _forward(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)
        return out, (q, k, v, None, None)
    out, lse = _forward(
        q, k, v, causal, sm_scale, blk_q, blk_k, interpret, with_lse=True
    )
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, blk_q, blk_k, interpret, res, g):
    q, k, v, o, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if lse is None:
        # fallback (shapes the kernel grid can't tile): vjp through the
        # remat-chunked formulation — identical math, no (S, S) tensor
        _, vjp = jax.vjp(
            lambda q, k, v: _chunked_reference(q, k, v, causal, sm_scale, blk_k),
            q, k, v,
        )
        return vjp(g)
    # fused two-pass flash backward kernels (dq, then dk/dv)
    S = q.shape[2]
    return _backward_kernels(
        q, k, v, o, lse, g, causal, sm_scale,
        min(blk_q, S), min(blk_k, S), interpret,
    )


flash_attention.defvjp(_fwd, _bwd)
