"""Pallas flash attention: fused causal self-attention for the MXU.

The hot op done as a TPU kernel (pallas_guide.md playbook): per (batch x
head, q-block) grid program, the q tile stays in VMEM while K/V stream
through block by block with an online (flash) softmax — the (S, S) score
matrix never materializes in HBM, so peak memory is O(BLK_Q x S_block)
instead of O(S^2). Causal programs stop at their diagonal block (the
upper-triangular half is never computed at all).

Differentiable via custom_vjp: the forward runs the kernel; the backward
differentiates a q-chunk-mapped, per-chunk-rematerialized formulation
(`_chunked_reference`) — identical math, and neither the forward nor the
backward ever holds an (S, S) tensor or a quadratic residual set.

Off-TPU the kernel runs in interpret mode so the same code path is
testable on the CPU meshes used by this repo's test suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _dense_reference(q, k, v, causal: bool, sm_scale: float):
    S = q.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _chunked_reference(q, k, v, causal: bool, sm_scale: float,
                       blk_q: int = 512, blk_k: int = 512):
    """Differentiable online-softmax attention with bounded memory:
    `lax.map` over Q-CHUNKS, each chunk wrapped in `jax.checkpoint`.

    Per chunk, an inner k-block scan runs the flash recurrence; the
    checkpoint boundary means the outer map's saved residuals are just
    the chunk inputs (O(S x hd) total), and the inner scan's per-step
    carries exist only transiently during that chunk's backward
    (O(S/blk_k x blk_q x hd)). Scanning k-blocks at FULL q (the naive
    layout) would be wrong: scan's VJP saves the (S, hd) acc carry per
    k-step — Theta(S^2 hd / blk_k), a quadratic bill hidden in
    residuals. The flash backward runs through jax.vjp of this."""
    B, H, S, hd = q.shape
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        return _dense_reference(q, k, v, causal, sm_scale)
    n_qb, n_kb = S // blk_q, S // blk_k
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    kb_ = kf.reshape(B, H, n_kb, blk_k, hd).transpose(2, 0, 1, 3, 4)
    vb_ = vf.reshape(B, H, n_kb, blk_k, hd).transpose(2, 0, 1, 3, 4)

    @jax.checkpoint
    def one_chunk(args):
        qc, q_off = args  # (B, H, blk_q, hd), scalar block offset
        qcf = qc.astype(jnp.float32)
        qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)

        def body(carry, inp):
            m, l, acc = carry
            kb, vb, kb_idx = inp
            s = jnp.einsum("bhqd,bhkd->bhqk", qcf, kb) * sm_scale
            if causal:
                kpos = kb_idx * blk_k + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1
                )
                mask = kpos <= qpos
                s = jnp.where(mask, s, NEG_INF)
                maskf = mask.astype(jnp.float32)
            else:
                maskf = 1.0
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new) * maskf
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * corr + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
            return (m_new, l, acc), None

        m0 = jnp.full((B, H, blk_q, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, blk_q, 1), jnp.float32)
        acc0 = jnp.zeros((B, H, blk_q, hd), jnp.float32)
        (_, l, acc), _ = lax.scan(
            body, (m0, l0, acc0), (kb_, vb_, jnp.arange(n_kb))
        )
        return acc / l

    q_chunks = q.reshape(B, H, n_qb, blk_q, hd).transpose(2, 0, 1, 3, 4)
    offsets = jnp.arange(n_qb) * blk_q
    out = lax.map(one_chunk, (q_chunks, offsets))  # (n_qb, B, H, blk_q, hd)
    out = out.transpose(1, 2, 0, 3, 4).reshape(B, H, S, hd)
    return out.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
            blk_q: int, blk_k: int, causal: bool, sm_scale: float):
    """One (bh, q-block, k-block) grid program. The TPU grid runs the
    LAST dimension sequentially on one core, so the (m, l, acc) flash
    accumulators live in VMEM scratch across the k-block sweep; K/V
    arrive one block at a time via BlockSpec streaming — VMEM holds
    O(blk) state regardless of S."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    qi = pl.program_id(1)
    n_kb = pl.num_programs(2)
    q_off = qi * blk_q
    k_off = kb * blk_k

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    # causal: blocks fully above the diagonal contribute nothing
    live = (k_off <= q_off + blk_q - 1) if causal else (kb >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)
            maskf = mask.astype(jnp.float32)
        else:
            maskf = 1.0
        m = m_scr[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * maskf
        corr = jnp.exp(m - m_new)
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:, :1] = m_new

    @pl.when(kb == n_kb - 1)
    def _finalize():
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


def _kv_index(blk_q, blk_k, causal, b, i, j):
    if not causal:
        return (b, j, 0)
    diag = (i * blk_q + blk_q - 1) // blk_k  # last live k-block for q-block i
    return (b, jnp.minimum(j, diag), 0)


def _forward(q, k, v, causal: bool, sm_scale: float, blk_q: int,
             blk_k: int, interpret) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        # degenerate shapes: correctness beats fusion
        return _dense_reference(q, k, v, causal, sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qf = q.reshape(B * H, S, hd)
    kf = k.reshape(B * H, S, hd)
    vf = v.reshape(B * H, S, hd)
    out = pl.pallas_call(
        functools.partial(_kernel, blk_q=blk_q, blk_k=blk_k, causal=causal,
                          sm_scale=sm_scale),
        out_shape=jax.ShapeDtypeStruct((B * H, S, hd), q.dtype),
        grid=(B * H, S // blk_q, S // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, hd), lambda b, i, j: (b, i, 0)),
            # causal: clamp the K/V block index at the q-block's diagonal
            # so dead above-diagonal blocks repeat the previous index and
            # Pallas skips their HBM fetch entirely (pl.when already
            # skips their compute)
            pl.BlockSpec((1, blk_k, hd), functools.partial(_kv_index, blk_q, blk_k, causal)),
            pl.BlockSpec((1, blk_k, hd), functools.partial(_kv_index, blk_q, blk_k, causal)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, hd), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((blk_q, 128), jnp.float32),  # m (lane-replicated col 0)
            pltpu.VMEM((blk_q, 128), jnp.float32),  # l
            pltpu.VMEM((blk_q, hd), jnp.float32),  # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, S, hd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float = None,
                    blk_q: int = 512, blk_k: int = 512, interpret=None):
    """Fused causal attention for (B, H, S, hd) q/k/v; drop-in for the
    transformer's pluggable attention core:

        _block(x, layer, cfg, core=lambda q, k, v: flash_attention(q, k, v))

    Measured on a v5e chip (bf16, B=2 H=8 hd=64, defaults): beats XLA's
    fused dense attention from S ~= 2048 (1.1x) to S = 4096 (1.4x), and
    its O(BLK_Q x S) working set keeps growing sequences off the HBM
    cliff that the dense (S, S) score tensor hits. Below ~2k sequence
    length XLA dense wins — use the default dense core there.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _forward(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    out = _forward(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)
    return out, (q, k, v)


def _bwd(causal, sm_scale, blk_q, blk_k, interpret, res, g):
    q, k, v = res
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    # memory-efficient backward: vjp through the remat-chunked formulation
    # (identical math; no (S, S) tensor in residuals or recompute)
    _, vjp = jax.vjp(
        lambda q, k, v: _chunked_reference(q, k, v, causal, sm_scale, blk_k),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
