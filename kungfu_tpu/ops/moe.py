"""Expert parallelism: top-k routed MoE FFN with all_to_all dispatch.

Beyond-reference capability (the reference is data-parallel only,
SURVEY §2.4); on TPU the expert dimension is a mesh axis and token
dispatch is `lax.all_to_all` over ICI — the canonical TPU MoE layout
(per-device expert groups, capacity-bounded buckets).

`moe_ffn` is the general form: E = axis_size * experts_per_device global
experts, top_k ∈ {1, 2} routing with renormalized gates, capacity
dropping per (source shard, choice). Each shard packs its tokens into
per-expert capacity buckets (choices side by side on the bucket axis so
ONE all_to_all carries both), exchanges buckets with every peer, applies
its local expert stack as one batched einsum, and sends results back the
way they came. Dropped tokens (over capacity) pass through on the
residual path (combine weight 0), the standard switch behavior; a top-2
token keeps whichever of its choices fit.

`switch_moe` (top-1, one expert per device) is the round-4 surface,
preserved as a thin special case.

Runs INSIDE a shard_map over the expert axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(x, router_w, w_in, w_out, axis_name: str, axis_size: int,
            top_k: int = 1, capacity_factor: float = 1.25):
    """x (T, D) tokens on this shard; router_w (D, E).

    w_in (epd, D, F), w_out (epd, F, D) are THIS device's expert stack
    (leading dim = experts per device); E = axis_size * epd. Returns
    (out (T, D), aux_loss) — out is zero for dropped tokens (caller adds
    the residual), aux_loss is the switch load-balancing loss on the
    primary choice."""
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    T, D = x.shape
    epd = w_in.shape[0]
    E = axis_size * epd
    if router_w.shape[-1] != E:
        raise ValueError(
            f"router width {router_w.shape[-1]} != axis_size*epd = {E}"
        )
    C = max(1, int(capacity_factor * T / E))  # per (shard, choice) capacity
    K = top_k * C  # bucket slots per expert on the wire

    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
    top_probs, top_idx = lax.top_k(probs, top_k)  # (T, top_k)
    # top-1 keeps the RAW router prob as its gate (switch semantics);
    # top-2 renormalizes over the chosen pair (GShard/Mixtral combine)
    gates = (
        top_probs
        if top_k == 1
        else top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
    )

    send = jnp.zeros((E, K, D), x.dtype)
    scat = []
    for j in range(top_k):
        expert_j = top_idx[:, j]  # (T,)
        onehot = jax.nn.one_hot(expert_j, E, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot
        slot = jnp.sum(pos, axis=-1) - 1  # 0-based within (expert, choice)
        kept = slot < C
        se = jnp.where(kept, expert_j, 0)
        sc = jnp.where(kept, j * C + slot, 0)
        send = send.at[se, sc].add(jnp.where(kept[:, None], x, 0),
                                   mode="drop")
        scat.append((se, sc, kept))

    # exchange: group bucket rows by destination DEVICE (expert e lives on
    # device e // epd at local index e % epd)
    send = send.reshape(axis_size, epd, K, D)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # (axis_size, epd, K, D)
    # local expert stack as one batched einsum over the epd dim
    h = jax.nn.gelu(
        jnp.einsum("sjkd,jdf->sjkf", recv, w_in.astype(recv.dtype))
    )
    y = jnp.einsum("sjkf,jfd->sjkd", h, w_out.astype(recv.dtype))
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    back = back.reshape(E, K, D)  # my tokens' results, per (expert, slot)

    out = jnp.zeros((T, D), x.dtype)
    for j, (se, sc, kept) in enumerate(scat):
        got = back[se, sc]  # (T, D)
        got = jnp.where(kept[:, None], got, 0)
        out = out + got.astype(x.dtype) * gates[:, j, None].astype(x.dtype)

    # switch aux loss on the primary choice: E * sum_e frac_e * mean_prob_e
    onehot1 = jax.nn.one_hot(top_idx[:, 0], E, dtype=jnp.float32)
    frac = jnp.mean(onehot1, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    aux = lax.pmean(aux, axis_name)
    return out, aux


def switch_moe(x, router_w, w_in, w_out, axis_name: str, axis_size: int,
               capacity_factor: float = 1.25):
    """Top-1 switch MoE with one expert per device (the round-4 surface):
    w_in (D, F), w_out (F, D). See `moe_ffn` for the general form."""
    return moe_ffn(
        x, router_w, w_in[None], w_out[None], axis_name, axis_size,
        top_k=1, capacity_factor=capacity_factor,
    )
