"""Expert parallelism: switch-routed MoE FFN with all_to_all dispatch.

Beyond-reference capability (the reference is data-parallel only,
SURVEY §2.4); on TPU the expert dimension is a mesh axis and token
dispatch is `lax.all_to_all` over ICI — the canonical TPU MoE layout
(one expert group per device, capacity-bounded buckets).

Top-1 (switch) routing with capacity dropping: each shard routes its
tokens, packs them into per-expert capacity buckets, exchanges buckets
with every peer via all_to_all, applies its local expert, and sends the
results back the way they came. Dropped tokens (over capacity) pass
through on the residual path (combine weight 0), the standard switch
behavior.

Runs INSIDE a shard_map over the expert axis. Experts = axis size (one
expert per device); generalizing to k experts/device stacks an extra
leading dim on the expert weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def switch_moe(x, router_w, w_in, w_out, axis_name: str, axis_size: int,
               capacity_factor: float = 1.25):
    """x (T, D) tokens on this shard; router_w (D, E); w_in (D, F),
    w_out (F, D) are THIS device's expert. E == axis_size. Returns
    (out (T, D), aux_loss) — out is zero for dropped tokens (caller adds
    the residual), aux_loss is the switch load-balancing loss."""
    T, D = x.shape
    E = axis_size
    C = max(1, int(capacity_factor * T / E))  # per (src, expert) capacity

    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]  # (T,)

    # position of each token within its expert's capacity bucket
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based slot per token
    slot = jnp.sum(pos, axis=-1) - 1  # (T,) 0-based; may exceed C-1
    kept = slot < C

    # pack: send[e, c] = the c-th kept token routed to expert e
    send = jnp.zeros((E, C, D), x.dtype)
    scat_e = jnp.where(kept, expert, 0)
    scat_c = jnp.where(kept, slot, 0)
    send = send.at[scat_e, scat_c].add(
        jnp.where(kept[:, None], x, 0), mode="drop"
    )

    # exchange: recv[s, c] = bucket sent BY shard s TO my expert
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    # expert FFN on every received token: (E, C, D) -> (E, C, D)
    h = jax.nn.gelu(recv @ w_in.astype(recv.dtype))
    y = h @ w_out.astype(recv.dtype)
    # return to senders
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # (E, C, D): my tokens, per expert

    # unpack: token t's result lives at back[expert[t], slot[t]]
    out = back[scat_e, scat_c]  # (T, D)
    out = jnp.where(kept[:, None], out, 0).astype(x.dtype)
    out = out * gate[:, None].astype(x.dtype)

    # switch aux loss: E * sum_e fraction_tokens_e * mean_prob_e, averaged
    # over shards (identical formula on every shard after the pmean)
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    aux = lax.pmean(aux, axis_name)
    return out, aux
