"""State broadcast at (re)initialization.

Capability parity: srcs/python/kungfu/tensorflow/initializer/__init__.py —
broadcast_variables makes every worker start from rank-0's weights (also
used after elastic resizes to bring joiners in sync).

TPU-native mapping:
- Within one mesh (single controller), replication via `jax.device_put` IS
  the broadcast — there is exactly one logical value.
- Across processes (multi-host pod, or workers rejoining after an elastic
  resize), host-level values can diverge; `broadcast_variables` forces
  process-0's values everywhere (XLA AllReduce under the hood via
  multihost_utils), mirroring BroadcastGlobalVariablesOp.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def broadcast_variables(tree, mesh: Mesh = None):
    """Force every process to process-0's values, then replicate on-mesh.

    Single-process: pure replication (no communication).
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tree = multihost_utils.broadcast_one_to_all(tree)
    if mesh is not None:
        tree = jax.device_put(tree, NamedSharding(mesh, P()))
    return tree
