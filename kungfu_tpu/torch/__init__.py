"""PyTorch frontend: distributed data parallelism over the host plane.

Capability parity: srcs/python/kungfu/torch/__init__.py +
srcs/cpp/src/torch/module_cpu.cpp — the reference serves TensorFlow AND
PyTorch from one runtime. Here the same host collective engine (graph-walk
allreduce over the kfrun cluster) backs torch tensors: gradients cross the
numpy bridge zero-copy (torch CPU tensors share memory with numpy views).

JAX remains the TPU compute path; this frontend covers the reference's
second-framework contract for CPU torch and torch/XLA hosts:

    from kungfu_tpu import torch as kf_torch
    kf_torch.broadcast_parameters(model)
    opt = kf_torch.SynchronousSGDOptimizer(torch.optim.SGD(model.parameters(), lr=0.1))
    ...
    loss.backward(); opt.step()
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from kungfu_tpu import api
from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.serialize import pack_leaves, unpack_leaves
from kungfu_tpu.base.workspace import Workspace


def _params_of(module_or_params) -> List:
    if hasattr(module_or_params, "parameters"):
        return list(module_or_params.parameters())
    return list(module_or_params)


def _flat_view(t) -> np.ndarray:
    """Flat numpy view of a tensor: zero-copy for contiguous CPU tensors
    (.cpu() is a no-op there); a host copy for XLA/CUDA tensors, whose
    callers write the result back explicitly. bfloat16 crosses the bridge
    by bit-reinterpretation (torch refuses .numpy() on bf16) and comes out
    as an ml_dtypes.bfloat16 array, which the host engine reduces
    natively."""
    import torch

    t = t.detach().cpu().contiguous().view(-1)
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _to_torch(arr: np.ndarray):
    """numpy -> torch, inverting _flat_view's bf16 reinterpretation."""
    import torch

    arr = np.ascontiguousarray(arr)
    if arr.dtype.kind == "V" and arr.dtype.itemsize == 2:  # ml_dtypes bf16
        return torch.from_numpy(arr.view(np.int16)).view(torch.bfloat16)
    return torch.from_numpy(arr)


_sync_round = [0]


def sync_gradients(module_or_params, name: str = "torch-grad",
                   _force_sync_engine: bool = False) -> None:
    """Average .grad across the cluster in-place (parity:
    _synchronize_grads, kungfu/torch/optimizers.py). One windowed group
    allreduce over the host plane; no-op for a cluster of one. Wire names
    carry a per-process round counter: a peer that finishes round k and
    immediately starts k+1 must not have its sends consumed by a slower
    peer still waiting on round k.

    With the async scheduler enabled (``KF_CONFIG_ASYNC``) the group is
    routed through it instead (submit-all + flush — grads are already
    ready here, so there is no backprop overlap; the hook path in
    SynchronousSGDOptimizer is the overlapped one). Scheduler tensor
    names must be STABLE across steps, so the trailing ``:<suffix>`` of
    `name` (the sync path's round counter) is stripped — the scheduler
    stamps its own round counter into wire names."""
    size = api.cluster_size()
    if size <= 1:
        return
    params = [p for p in _params_of(module_or_params) if p.grad is not None]
    if not params:
        return
    rnd = _sync_round[0]
    _sync_round[0] += 1
    views = [_flat_view(p.grad) for p in params]
    sess = api.get_default_peer().current_session()
    if sess.async_enabled() and not _force_sync_engine:
        # async scheduler path (ISSUE 10): stable per-tensor names (the
        # scheduler stamps its own round counter into wire names, which
        # is what the :{rnd}: component below exists for on the sync
        # path), submitted in parameter order, one flush per step
        sched = sess.scheduler()
        for i, v in enumerate(views):
            sched.submit(Workspace(
                send=v, recv=v, op=ReduceOp.SUM,
                name=f"kungfu::torch:{name.rsplit(':', 1)[0]}:{i}",
            ))
        sched.flush()
    else:
        ws = [
            Workspace(send=v, recv=v, op=ReduceOp.SUM,
                      name=f"kungfu::torch:{name}:{rnd}:{i}")
            for i, v in enumerate(views)
        ]
        sess.group_all_reduce(ws)
    inv = 1.0 / size
    for p, v in zip(params, views):
        v *= v.dtype.type(inv)
        # v aliases p.grad's storage for CPU tensors; if torch had to
        # copy (non-CPU / non-contiguous), write the result back
        if p.grad.device.type != "cpu" or not p.grad.is_contiguous():
            p.grad.copy_(_to_torch(v).view_as(p.grad))


def broadcast_parameters(module_or_params, root: int = 0,
                         name: str = "torch-init") -> None:
    """Replace every param with root's values (parity:
    broadcast_parameters, kungfu/torch/__init__.py)."""
    import torch

    if api.cluster_size() <= 1:
        return
    params = _params_of(module_or_params)
    sess = api.get_default_peer().current_session()
    blob = pack_leaves([_flat_view(p) for p in params])
    out = sess.broadcast_bytes(blob, f"kungfu::torch:{name}", root=root)
    if sess.rank == root:
        return
    leaves = unpack_leaves(out, len(params))
    with torch.no_grad():
        for p, l in zip(params, leaves):
            p.copy_(_to_torch(l).view_as(p))


def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, name: str = "torch-ar"):
    """AllReduce a single tensor, returning a new tensor on the input's
    device (parity: all_reduce_fn). all_reduce_array never mutates its
    input and returns a fresh buffer, so no defensive copy is needed."""
    out = api.all_reduce_array(_flat_view(tensor), op=op, name=name)
    return _to_torch(out).view_as(tensor).to(tensor.device)


class SynchronousSGDOptimizer:
    """S-SGD wrapper over any torch optimizer (parity:
    SynchronousSGDOptimizer, kungfu/torch/optimizers.py): averages
    gradients across the cluster, then applies the base step.

    With the async collective scheduler enabled (``KF_CONFIG_ASYNC``,
    ISSUE 10) each parameter's gradient is SUBMITTED the moment autograd
    finishes accumulating it (post-accumulate-grad hooks), so buckets
    pack and walk while backward is still producing later gradients;
    ``step()`` then only flushes the tail. Falls back to the step-end
    group op when the scheduler is off, the cluster is size 1, or torch
    predates the hook API (<2.1). Results are bit-identical either way
    (same buckets, same engine — only launch time moves).

    Hook-path contract: exactly ONE backward per ``step()``. Gradient
    accumulation (several ``backward()`` calls before a step) would
    submit partially-accumulated gradients, so pass
    ``async_hooks=False`` to keep the step-end path for such loops (a
    second backward otherwise fails fast with the scheduler's
    "submitted twice in round" error rather than reducing partial
    data)."""

    def __init__(self, base, name: str = "ssgd",
                 async_hooks: Optional[bool] = None):
        self.base = base
        self.name = name
        self._step = 0
        self._async_grads: dict = {}  # param index -> (param, flat view)
        # None: follow the session's KF_CONFIG_ASYNC; False: never hook
        # (gradient-accumulation loops); True: require hooks or fall
        # back silently like None
        self._async_opt_in = async_hooks
        self._hooks_installed: Optional[bool] = None  # None: undecided

    def _params_list(self) -> List:
        return [
            p for group in self.base.param_groups for p in group["params"]
        ]

    def _install_hooks(self) -> bool:
        """Register per-param submission hooks when the async scheduler
        can take them; decided once, at the first step (the session
        exists by then). Hook firing order is autograd order — identical
        across data-parallel replicas of the same model, which is what
        the scheduler's registration consensus verifies."""
        if self._async_opt_in is False:
            return False
        if api.cluster_size() <= 1:
            return False
        sess = api.get_default_peer().current_session()
        if not sess.async_enabled():
            return False
        params = self._params_list()
        if not all(
            hasattr(p, "register_post_accumulate_grad_hook") for p in params
        ):
            return False

        def make_hook(i):
            def hook(param):
                s = api.get_default_peer().current_session()
                if not s.async_enabled():
                    # an elastic resize landed on an async-off session
                    # (e.g. KF_CONFIG_ASYNC=auto shrunk to 1 peer):
                    # hooks must go dormant, NOT buffer into a scheduler
                    # nobody will ever flush — step() falls back to the
                    # step-end path when _async_grads stays empty
                    return
                v = _flat_view(param.grad)
                self._async_grads[i] = (param, v)
                s.scheduler().submit(Workspace(
                    send=v, recv=v, op=ReduceOp.SUM,
                    name=f"kungfu::torch:{self.name}:{i}",
                ))
            return hook

        for i, p in enumerate(params):
            if p.requires_grad:
                p.register_post_accumulate_grad_hook(make_hook(i))
        return True

    def step(self, closure=None):
        if self._hooks_installed is None:
            # decided AFTER the first backward: grads of step 0 already
            # exist, so step 0 always takes the sync path below and the
            # hooks start feeding the scheduler from step 1
            self._hooks_installed = self._install_hooks()
        if self._async_grads:
            sess = api.get_default_peer().current_session()
            if not sess.async_enabled():
                # a resize landed BETWEEN backward and step: this
                # step's submissions died with the old epoch and some
                # in-place gradient views may already be partially
                # reduced — scaling them would corrupt silently, and
                # re-reducing could double-sum completed buckets. Fail
                # loudly; the elastic loop re-runs the step.
                self._async_grads.clear()
                raise RuntimeError(
                    "cluster resized mid-step onto an async-off "
                    "session; gradients of this step are indeterminate "
                    "— zero_grad() and re-run the backward"
                )
            api.flush_async()
            inv = 1.0 / api.cluster_size()
            for _, (p, v) in sorted(self._async_grads.items()):
                v *= v.dtype.type(inv)
                # v aliases p.grad's storage for CPU tensors; if torch
                # had to copy (non-CPU / non-contiguous), write back
                if p.grad.device.type != "cpu" or not p.grad.is_contiguous():
                    p.grad.copy_(_to_torch(v).view_as(p.grad))
            self._async_grads.clear()
        else:
            # step-end path (step 0, hooks unavailable, or opted out):
            # force the classic group engine even when the scheduler is
            # on — routing THIS call through the scheduler would
            # register grad-filtered indices while the hooks submit
            # full-param-list indices, desynchronizing the registered
            # identity set for any model with frozen params
            sync_gradients(self._params_list(),
                           name=f"{self.name}:{self._step}",
                           _force_sync_engine=True)
        self._step += 1
        return self.base.step(closure)

    def __getattr__(self, item):
        return getattr(self.base, item)


class ZeroSGDOptimizer:
    """ZeRO-1 sharded S-SGD for torch over the host plane (ISSUE 11):
    gradients are reduce-scattered around the ring, ``step()`` runs SGD
    on — and holds momentum state plus f32 master weights for — ONLY
    this rank's 1/k shard, and an all-gather of updated weights (bf16 on
    the wire when ``KF_CONFIG_WIRE`` is active) lands the result back in
    the param tensors in place. Optimizer state and update FLOPs drop
    k-fold vs :class:`SynchronousSGDOptimizer`.

    This optimizer OWNS the SGD math (``lr``/``momentum``, the torch-SGD
    formula ``buf = m·buf + g; p -= lr·buf``) rather than wrapping a
    base ``torch.optim`` instance — a base optimizer would allocate
    full-size state, which is exactly what sharding removes.

    With ``KF_CONFIG_ZERO`` resolving off — or a cluster of one — it
    falls back to the replicated path (``sync_gradients`` + the same
    formula on full params, full-size state), so ``zero`` A/Bs by knob;
    for plain SGD on exact payloads the two paths are bit-identical.
    With the async scheduler on, gradients are submitted per tensor and
    the weight all-gathers pipeline across buckets; ``step()`` returns
    with params fully updated (the forward that follows needs them).

    Elastic resize: shard ownership is a function of k — call
    ``export_state()`` BEFORE the resize and ``rebuild(blob)`` after
    (see ShardedUpdateSession). CPU-tensor first like the rest of the
    frontend: param/grad views cross the numpy bridge zero-copy there;
    non-CPU params are copied back after each step."""

    def __init__(self, module_or_params, lr: float, momentum: float = 0.0,
                 name: str = "zsgd"):
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.name = name
        self._params = [
            p for p in _params_of(module_or_params) if p.requires_grad
        ]
        if not self._params:
            raise ValueError("ZeroSGDOptimizer needs at least one param")
        self._mode: Optional[str] = None  # decided at first step
        self._views: List[np.ndarray] = []
        self._zs = None  # ShardedUpdateSession (sharded mode)
        self._repl_opt = None  # ShardedSGD over FULL params (fallback)
        self._repl_state: List[dict] = []
        self._step = 0

    def _build(self) -> None:
        self.rebuild(None)

    def state_bytes(self) -> int:
        """Optimizer-held bytes on this peer: ~1/k of the replicated
        path in sharded mode (the `kungfu_sharded_update_state_bytes`
        story the bench reports)."""
        if self._mode is None:
            self._build()
        if self._zs is not None:
            return self._zs.state_bytes()
        return sum(
            a.nbytes for st in self._repl_state for a in st.values()
        )

    def _bucket_layout(self, sess):
        from kungfu_tpu.collective.zero import bucket_layout

        return bucket_layout(
            [v.size for v in self._views], sess.GROUP_BUCKET_BYTES
        )

    def export_state(self) -> bytes:
        """Full optimizer state as one exact blob (every peer gets the
        identical bytes) — run BEFORE a resize, then `rebuild(blob)` on
        the new epoch. BOTH modes serialize the same canonical
        bucket-shaped layout (per bucket: full f32 masters, then each
        state leaf — the `bucket_layout` of the param sizes under the
        cluster-agreed byte cap), so a resize that flips the resolved
        KF_CONFIG_ZERO mode (e.g. `auto` shrinking to one peer) can
        still restore the other mode's blob."""
        if self._mode is None:
            self._build()
        if self._zs is not None:
            return self._zs.export_state()
        from kungfu_tpu.base.serialize import pack_leaves

        sess = api.get_default_peer().current_session()
        names = self._repl_opt.state_names()
        leaves = []
        for idxs in self._bucket_layout(sess):
            # replicated mode's masters ARE the current params
            leaves.append(np.concatenate([self._views[i] for i in idxs]))
            for k in names:
                leaves.append(np.concatenate(
                    [self._repl_state[i][k] for i in idxs]
                ))
        return pack_leaves(leaves)

    def rebuild(self, restore_state: Optional[bytes] = None) -> None:
        """(Re-)bind to the CURRENT session epoch — called lazily at the
        first step, and explicitly after an elastic resize with an
        `export_state` blob from before it, re-sharding (or
        de-sharding: the resolved mode may flip across the resize)
        optimizer state so zero-step-loss resizes hold."""
        from kungfu_tpu.collective.zero import ShardedSGD, ShardedUpdateSession

        sess = api.get_default_peer().current_session()
        self._views = [_flat_view(p) for p in self._params]
        if sess.zero_enabled():
            self._mode = "sharded"
            self._zs = ShardedUpdateSession(
                self._views, ShardedSGD(self.lr, self.momentum),
                name=self.name, session=sess, restore_state=restore_state,
            )
            self._repl_opt = None
            self._repl_state = []
            self._writeback()
            return
        self._mode = "replicated"
        self._zs = None
        self._repl_opt = ShardedSGD(self.lr, self.momentum)
        self._repl_state = [self._repl_opt.init(v.size) for v in self._views]
        if restore_state is not None:
            from kungfu_tpu.base.serialize import unpack_leaves

            names = self._repl_opt.state_names()
            layout = self._bucket_layout(sess)
            leaves = unpack_leaves(restore_state, (1 + len(names)) * len(layout))
            it = iter(leaves)
            for idxs in layout:
                # canonical layout (see export_state): masters refresh
                # the params, state leaves split back per param
                master = np.asarray(next(it), np.float32).reshape(-1)
                off = 0
                for i in idxs:
                    np.copyto(self._views[i], master[off:off + self._views[i].size])
                    off += self._views[i].size
                for k in names:
                    full = np.asarray(next(it), np.float32).reshape(-1)
                    off = 0
                    for i in idxs:
                        np.copyto(self._repl_state[i][k],
                                  full[off:off + self._views[i].size])
                        off += self._views[i].size
            self._writeback()

    def _writeback(self) -> None:
        """Non-CPU / non-contiguous params: the numpy views are copies,
        push the updated values back into the tensors."""
        for p, v in zip(self._params, self._views):
            if p.device.type != "cpu" or not p.data.is_contiguous():
                with_no_grad_copy(p, v)

    def zero_grad(self) -> None:
        for p in self._params:
            if p.grad is not None:
                p.grad.detach_()
                p.grad.zero_()

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        if self._mode is None:
            self._build()
        grads = []
        for i, p in enumerate(self._params):
            if p.grad is None:
                raise RuntimeError(
                    f"param {i} has no gradient — ZeroSGDOptimizer "
                    "requires every registered param to receive a grad "
                    "each step (the sharded bucket layout is fixed)"
                )
            grads.append(_flat_view(p.grad))
        if self._zs is not None:
            sess = api.get_default_peer().current_session()
            if sess.async_enabled():
                for i, g in enumerate(grads):
                    self._zs.submit_grad(i, g)
                self._zs.flush()
                # params feed the forward right after step() returns:
                # wait for the tail all-gathers here (the pipelining
                # already overlapped them with later buckets' updates)
                self._zs.wait_params()
            else:
                self._zs.step(grads)
        else:
            # replicated fallback: averaged grads (in place), then the
            # identical SGD formula on full params with full-size state
            if api.cluster_size() > 1:
                sync_gradients(self._params, name=f"{self.name}:{self._step}",
                               _force_sync_engine=True)
                # non-CPU grads: sync_gradients wrote the averages back
                # into p.grad, so the pre-sync copies above are stale
                grads = [_flat_view(p.grad) for p in self._params]
            for v, g, st in zip(self._views, grads, self._repl_state):
                self._repl_opt.apply(v, g, st, 1.0)
        self._writeback()
        self._step += 1
        return loss


def with_no_grad_copy(p, arr: np.ndarray) -> None:
    """p.copy_(arr) under no_grad, inverting the bf16 bridge."""
    import torch

    with torch.no_grad():
        p.copy_(_to_torch(arr).view_as(p))


class PairAveragingOptimizer:
    """AD-PSGD for torch (parity: PairAveragingOptimizer): apply the local
    step, then average parameters 0.5/0.5 with a random peer's published
    model via the versioned p2p store."""

    def __init__(self, base, name: str = "torch-pair", rng=None):
        import random

        self.base = base
        self.blob = f"pair-avg-torch:{name}"
        self.rng = rng or random.Random(api.current_rank() * 6007 + 13)
        self._version = 0
        self._published = False

    def _params(self) -> List:
        return [p for g in self.base.param_groups for p in g["params"]]

    def _publish(self) -> None:
        p2p = api.get_default_peer().p2p
        blob = pack_leaves([_flat_view(p) for p in self._params()])
        p2p.save_version(self._version, self.blob, blob)
        self._version += 1

    def _random_peer(self) -> Optional[int]:
        size = api.cluster_size()
        if size <= 1:
            return None
        r = self.rng.randrange(size - 1)
        me = api.current_rank()
        return r + 1 if r >= me else r

    def step(self, closure=None):
        import torch

        if not self._published:
            # first step: publish + fence so every peer has a model to serve
            self._publish()
            api.run_barrier()
            self._published = True
        out = self.base.step(closure)
        target = self._random_peer()
        if target is not None:
            sess = api.get_default_peer().current_session()
            p2p = api.get_default_peer().p2p
            try:
                data = p2p.request(
                    sess.peers[target], self.blob, timeout=30, version="latest"
                )
            except (ConnectionError, TimeoutError, OSError):
                data = None
            params = self._params()
            if data is not None:
                try:
                    leaves = unpack_leaves(bytes(data), len(params))
                except (ValueError, KeyError):
                    leaves = None
                if leaves is not None:
                    with torch.no_grad():
                        for p, l in zip(params, leaves):
                            p.mul_(0.5).add_(_to_torch(l).view_as(p), alpha=0.5)
        self._publish()
        return out

    def __getattr__(self, item):
        return getattr(self.base, item)
