"""Embedded runner API: launch a single-machine multi-process cluster
from Python, plus the failure-monitor signal helpers.

Capability parity: srcs/python/kungfu/cmd/__init__.py —
``launch_multiprocess(f, np)`` (cmd/__init__.py:45-49) and the
``monitor_batch_begin/end`` / ``monitor_epoch_end`` / ``monitor_train_end``
signal functions (:18-31) that feed the -auto-recover heartbeat monitor.
"""

from __future__ import annotations

import os
import socket
from typing import Callable, List

from kungfu_tpu.runner.monitored import send_heartbeat


def _reserve_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _run_worker(f: Callable[[int], None], rank: int, env: dict) -> None:
    os.environ.update(env)
    f(rank)
    # deterministic teardown before the process exits (atexit also covers
    # it, but multiprocessing's exit path is less forgiving)
    from kungfu_tpu.peer import finalize_default_peer

    finalize_default_peer()


def launch_multiprocess(f: Callable[[int], None], np_: int) -> None:
    """Run ``f(rank)`` in ``np_`` local worker processes wired into one
    host-plane cluster (parity: launch_multiprocess). Inside ``f`` the
    normal API works: ``kungfu_tpu.api.current_rank()``, collectives,
    optimizers. Raises RuntimeError if any worker exits nonzero."""
    import multiprocessing as mp

    from kungfu_tpu.plan.peer import PeerID, PeerList
    from kungfu_tpu.runner import env as kfenv

    peers = PeerList(
        [PeerID("127.0.0.1", p) for p in _reserve_ports(np_)]
    )
    envs = [
        kfenv.worker_env(
            self_id=peers[r],
            peers=peers,
            runners=PeerList(),
            parent=None,
        )
        for r in range(np_)
    ]
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(target=_run_worker, args=(f, r, envs[r]), daemon=False)
        for r in range(np_)
    ]
    for p in procs:
        p.start()
    for p in procs:
        # kfcheck: disable=KF302 — the workers ARE the foreground job; the
        # launcher's contract is to block for their whole (unbounded)
        # training run, and Ctrl-C interrupts the join
        p.join()
    bad = [(i, p.exitcode) for i, p in enumerate(procs) if p.exitcode != 0]
    if bad:
        raise RuntimeError(f"launch_multiprocess: workers failed: {bad}")


def monitor_batch_begin(rank: int = -1) -> None:
    """Heartbeat: a batch started (parity: monitor_batch_begin)."""
    send_heartbeat("begin", _rank(rank))


def monitor_batch_end(rank: int = -1) -> None:
    send_heartbeat("end", _rank(rank))


def monitor_epoch_end(rank: int = -1) -> None:
    send_heartbeat("epoch", _rank(rank))


def monitor_train_end(rank: int = -1) -> None:
    send_heartbeat("trainend", _rank(rank))


def _rank(rank: int) -> int:
    if rank >= 0:
        return rank
    try:
        from kungfu_tpu import api

        return api.current_rank()
    # kfcheck: disable=KF400 — heartbeats are best-effort: outside a
    # cluster api.current_rank() has no peer and rank 0 is the contract
    except Exception:  # noqa: BLE001
        return 0
