"""Peer latency probes: RTT vector over the host plane.

Capability parity: GetPeerLatencies (srcs/go/kungfu/session/monitoring.go:38-64
+ ops/cpu/topology.cpp:84-116) — each peer pings every other peer and
reports a round-trip-time vector (self = 0). Feeds the MST topology
optimization (kungfu_tpu.plan.mst) and interference diagnostics.
"""

from __future__ import annotations

import time
from typing import List

import numpy as np


def probe_peer_latencies(client, peers, self_rank: int, samples: int = 3) -> np.ndarray:
    """RTT seconds per peer, aligned to rank order; self = 0.0, unreachable
    peers = +inf. Takes the best of `samples` probes (min filters out
    scheduler noise, the standard RTT-probe practice)."""
    from kungfu_tpu.telemetry import config as _tcfg
    from kungfu_tpu.telemetry import metrics as _tm

    rtt_gauge = (
        _tm.gauge(
            "kungfu_peer_rtt_seconds",
            "Best probed RTT per peer (+inf peers omitted)",
            ("peer",),
        )
        if _tcfg.metrics_enabled()
        else None
    )
    if rtt_gauge is not None:
        # each probe covers the CURRENT cluster: dropping the old children
        # first stops departed peers from reporting stale RTTs forever and
        # bounds label cardinality across elastic resizes
        rtt_gauge.clear_children()
    out = np.zeros(len(peers), np.float64)
    for r, peer in enumerate(peers):
        if r == self_rank:
            continue
        best = np.inf
        for _ in range(samples):
            t0 = time.perf_counter()
            if client.ping(peer, timeout=2.0):
                best = min(best, time.perf_counter() - t0)
        out[r] = best
        if rtt_gauge is not None and np.isfinite(best):
            rtt_gauge.labels(str(peer)).set(best)
    return out


def latency_matrix_from_rows(rows: List[np.ndarray]) -> np.ndarray:
    """Symmetrize allgathered RTT rows into a dense cost matrix (average of
    the two directions; peers measure slightly different RTTs)."""
    m = np.stack(rows).astype(np.float64)
    return (m + m.T) / 2.0
