"""Gradient-noise-scale (GNS) monitoring, fully on-device.

Capability parity: the reference's NoiseScale op
(srcs/cpp/src/tensorflow/ops/cpu/collective.cpp:256-304) +
MonitorGradientNoiseScaleOptimizer (optimizers/grad_noise_scale.py:11-88)
and global_noise_scale (ops/monitor.py), implementing the estimator from
"An Empirical Model of Large-Batch Training" (McCandlish et al.):

With B_small = per-worker batch, B_big = global batch, g_small = local
gradient, g_big = cluster-averaged gradient:
    |G|^2 est:  g2 = (B_big*|g_big|^2 - B_small*|g_small|^2) / (B_big - B_small)
    tr(S) est:  s  = (|g_small|^2 - |g_big|^2) / (1/B_small - 1/B_big)
GNS = EMA(s) / EMA(g2)  — the batch size at which noise ~ signal.

TPU-first: everything (norms, pmean, EMAs, ratio) is traced into the same
compiled step as backprop — no extra pass and no host trip, vs. the
reference's separate CPU op on fused gradients.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax


class GNSState(NamedTuple):
    g2_ema: jnp.ndarray  # EMA of |G|^2 estimate
    s_ema: jnp.ndarray  # EMA of tr(S) estimate
    count: jnp.ndarray


def gns_init() -> GNSState:
    return GNSState(
        g2_ema=jnp.zeros((), jnp.float32),
        s_ema=jnp.zeros((), jnp.float32),
        count=jnp.zeros((), jnp.int32),
    )


def _sq_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def gns_update_norms(
    state: GNSState,
    gs: jnp.ndarray,
    gb: jnp.ndarray,
    batch_small: jnp.ndarray,
    batch_big: jnp.ndarray,
    alpha: float = 0.6,
) -> GNSState:
    """One EMA update from squared norms gs = E|g_small|^2, gb = |g_big|^2.

    alpha mirrors the reference's EMA decay for the noise-scale op.
    """
    bs = jnp.asarray(batch_small, jnp.float32)
    bb = jnp.asarray(batch_big, jnp.float32)
    g2 = (bb * gb - bs * gs) / (bb - bs)
    s = (gs - gb) / (1.0 / bs - 1.0 / bb)
    # first sample initializes the EMAs (parity: EMA warm start)
    first = state.count == 0
    g2_ema = jnp.where(first, g2, alpha * g2 + (1 - alpha) * state.g2_ema)
    s_ema = jnp.where(first, s, alpha * s + (1 - alpha) * state.s_ema)
    return GNSState(g2_ema=g2_ema, s_ema=s_ema, count=state.count + 1)


def gns_update(
    state: GNSState,
    local_grads,
    avg_grads,
    batch_small,
    batch_big,
    alpha: float = 0.6,
) -> GNSState:
    """Tree-input form of gns_update_norms (single-process estimate)."""
    return gns_update_norms(
        state, _sq_norm(local_grads), _sq_norm(avg_grads), batch_small, batch_big, alpha
    )


def noise_scale(state: GNSState) -> jnp.ndarray:
    """Current GNS estimate (0 while unseeded)."""
    return jnp.where(
        state.g2_ema != 0, state.s_ema / jnp.maximum(state.g2_ema, 1e-30), 0.0
    )


def publish_noise_scale(state: GNSState) -> float:
    """Pull the GNS estimate to the host and publish it as the
    ``kungfu_noise_scale`` gauge (plus the raw EMAs); returns the value.

    The estimate itself stays on-device in the optimizer state — call
    this at a logging cadence, not per step (it is an explicit device ->
    host transfer, the thing the compiled estimator avoids)."""
    from kungfu_tpu.telemetry import metrics as _tm

    val = float(noise_scale(state))
    _tm.gauge(
        "kungfu_noise_scale",
        "Gradient noise scale (McCandlish critical batch estimate)",
    ).set(val)
    _tm.gauge(
        "kungfu_noise_scale_g2_ema", "EMA of the |G|^2 estimate"
    ).set(float(state.g2_ema))
    _tm.gauge(
        "kungfu_noise_scale_s_ema", "EMA of the tr(S) estimate"
    ).set(float(state.s_ema))
    return val


class _MonitorState(NamedTuple):
    base: optax.OptState
    gns: GNSState


def monitor_gradient_noise_scale(
    base: optax.GradientTransformation,
    batch_small: int,
    axis_name: str = "dp",
    interval: int = 1,
    alpha: float = 0.6,
) -> optax.GradientTransformation:
    """S-SGD + on-device GNS (parity: MonitorGradientNoiseScaleOptimizer).

    Must run inside shard_map over `axis_name`. The GNS estimate lives in
    the optimizer state (read it with `noise_scale(state.gns)`); `interval`
    thins the EMA updates like the reference's `interval` arg.
    """

    def init(params):
        return _MonitorState(base=base.init(params), gns=gns_init())

    def update(grads, state, params=None, **extra):
        np_ = lax.psum(jnp.ones((), jnp.float32), axis_name)
        avg = jax.tree.map(lambda g: lax.pmean(g, axis_name), grads)
        do_update = (state.gns.count * 0 + 1) if interval == 1 else (
            jnp.mod(state.gns.count, interval) == 0
        )
        # E|g_small|^2 averaged over workers: keeps the GNS state replicated
        # across the axis (every device holds the same EMA)
        gs = lax.pmean(_sq_norm(grads), axis_name)
        gb = _sq_norm(avg)
        new_gns = gns_update_norms(
            state.gns, gs, gb, batch_small, batch_small * np_, alpha
        )
        # thin only the EMAs; count advances every step so interval works
        gns = GNSState(
            g2_ema=jnp.where(do_update, new_gns.g2_ema, state.gns.g2_ema),
            s_ema=jnp.where(do_update, new_gns.s_ema, state.gns.s_ema),
            count=state.gns.count + 1,
        )
        updates, base_state = base.update(avg, state.base, params, **extra)
        return updates, _MonitorState(base=base_state, gns=gns)

    return optax.GradientTransformation(init, update)
