"""Network monitor: per-peer egress/ingress byte counters + rate windows.

Capability parity: srcs/go/monitor/{monitor,counters,server}.go — totals
and windowed rates per peer, Prometheus-style text endpoint, enabled by
KF_CONFIG_ENABLE_MONITORING; surfaced to training as egress_rates()
(parity: ops/cpu/monitoring.cpp:5-22 + session monitoring).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.plan.peer import PeerID

DEFAULT_WINDOW = 1.0  # seconds


def enabled() -> bool:
    return os.environ.get("KF_CONFIG_ENABLE_MONITORING", "") in ("1", "true")


class RateCounter:
    """Monotonic byte counter with a sliding-window rate estimate."""

    def __init__(self, window: float = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._total = 0
        self._window = window
        self._samples: deque = deque()  # (t, total)

    def add(self, n: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._total += n
            self._samples.append((now, self._total))
            cutoff = now - self._window
            while len(self._samples) > 1 and self._samples[0][0] < cutoff:
                self._samples.popleft()

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def rate(self) -> float:
        """Bytes/sec over the window."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            (t0, b0), (t1, b1) = self._samples[0], self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (b1 - b0) / (t1 - t0)


class NetMonitor:
    def __init__(self):
        self._egress: Dict[PeerID, RateCounter] = defaultdict(RateCounter)
        self._ingress: Dict[PeerID, RateCounter] = defaultdict(RateCounter)

    def sent(self, peer: PeerID, n: int) -> None:
        self._egress[peer].add(n)

    def received(self, peer: PeerID, n: int) -> None:
        self._ingress[peer].add(n)

    def egress_totals(self) -> Dict[PeerID, int]:
        return {p: c.total for p, c in self._egress.items()}

    def egress_rates(self, peers: List[PeerID]) -> List[float]:
        """Rates aligned to a rank order (parity: GetEgressRates)."""
        return [self._egress[p].rate() if p in self._egress else 0.0 for p in peers]

    def ingress_rates(self, peers: List[PeerID]) -> List[float]:
        return [self._ingress[p].rate() if p in self._ingress else 0.0 for p in peers]

    def render_metrics(self) -> str:
        """Prometheus-style exposition (parity: monitor/server.go)."""
        lines = []
        for name, table in (("egress", self._egress), ("ingress", self._ingress)):
            lines.append(f"# TYPE kungfu_{name}_bytes counter")
            for p, c in sorted(table.items(), key=lambda kv: str(kv[0])):
                lines.append(
                    f'kungfu_{name}_bytes{{peer="{p}"}} {c.total}'
                )
            lines.append(f"# TYPE kungfu_{name}_rate gauge")
            for p, c in sorted(table.items(), key=lambda kv: str(kv[0])):
                lines.append(
                    f'kungfu_{name}_rate{{peer="{p}"}} {c.rate():.1f}'
                )
        return "\n".join(lines) + "\n"


_global_monitor: Optional[NetMonitor] = None
_monitor_lock = threading.Lock()


def get_monitor() -> NetMonitor:
    global _global_monitor
    with _monitor_lock:
        if _global_monitor is None:
            _global_monitor = NetMonitor()
        return _global_monitor


class MetricsServer:
    """/metrics HTTP endpoint (parity: peer's port+10000 server)."""

    def __init__(self, monitor: NetMonitor, port: int):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(inner):
                if inner.path.rstrip("/") != "/metrics":
                    inner.send_response(404)
                    inner.end_headers()
                    return
                body = monitor.render_metrics().encode()
                inner.send_response(200)
                inner.send_header("Content-Type", "text/plain")
                inner.send_header("Content-Length", str(len(body)))
                inner.end_headers()
                inner.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self.httpd.server_address[1]

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
