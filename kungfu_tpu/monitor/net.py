"""Network monitor: per-peer egress/ingress byte counters + rate windows.

Capability parity: srcs/go/monitor/{monitor,counters,server}.go — totals
and windowed rates per peer, surfaced to training as egress_rates()
(parity: ops/cpu/monitoring.cpp:5-22 + session monitoring).

Refactored onto the shared telemetry subsystem (ISSUE 1): the singleton
monitor mirrors every count into the process metrics registry
(``kungfu_egress_bytes_total``/``kungfu_ingress_bytes_total`` and
message counters, labelled by peer), and the Prometheus endpoint is the
per-worker TelemetryServer (``/metrics`` + ``/trace`` + ``/audit``) —
the bespoke /metrics-only server this module used to own survives as a
thin back-compat wrapper. Enabled by ``KF_CONFIG_ENABLE_MONITORING``
(any truthy spelling: 1/true/yes/on) or ``KF_TELEMETRY=metrics``.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional

from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.telemetry import config as _tconfig
from kungfu_tpu.telemetry import metrics as _metrics

DEFAULT_WINDOW = 1.0  # seconds


def enabled() -> bool:
    """Truthy parsing is shared (telemetry.config.truthy): "yes"/"on"
    variants used to be silently rejected here."""
    return _tconfig.metrics_enabled()


class RateCounter:
    """Monotonic byte counter with a sliding-window rate estimate."""

    def __init__(self, window: float = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._total = 0
        self._window = window
        self._samples: deque = deque()  # (t, total)

    def add(self, n: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._total += n
            self._samples.append((now, self._total))
            cutoff = now - self._window
            while len(self._samples) > 1 and self._samples[0][0] < cutoff:
                self._samples.popleft()

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def rate(self) -> float:
        """Bytes/sec over the window."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            (t0, b0), (t1, b1) = self._samples[0], self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (b1 - b0) / (t1 - t0)


class NetMonitor:
    def __init__(self, registry: Optional[_metrics.Registry] = None):
        # guards the peer->counter TABLES (key inserts vs. scrape
        # iteration); each RateCounter still has its own lock for adds
        self._tables_lock = threading.Lock()
        self._egress: Dict[PeerID, RateCounter] = defaultdict(RateCounter)
        self._ingress: Dict[PeerID, RateCounter] = defaultdict(RateCounter)
        # registry mirroring: only the process singleton (get_monitor)
        # publishes into the shared registry; standalone instances (tests)
        # stay self-contained. Per-peer label children are cached beside
        # the rate counters (_children) — sent()/received() run per
        # MESSAGE, so the steady path must be cached-object .inc() calls,
        # not str(peer) + family-lock label lookups
        self._registry = registry
        self._reg_children: Dict[PeerID, tuple] = {}
        if registry is not None:
            self._reg_families = tuple(
                registry.counter(name, help, ("peer",))
                for name, help in (
                    ("kungfu_egress_bytes_total",
                     "Bytes sent per peer over the host transport"),
                    ("kungfu_ingress_bytes_total",
                     "Bytes received per peer over the host transport"),
                    ("kungfu_egress_messages_total",
                     "Messages sent per peer over the host transport"),
                    ("kungfu_ingress_messages_total",
                     "Messages received per peer over the host transport"),
                )
            )
            registry.add_renderer(self.render_rates)
        else:
            self._reg_families = None

    def _counter(self, table: Dict[PeerID, RateCounter], peer: PeerID) -> RateCounter:
        # insert under the tables lock so a concurrent scrape's snapshot
        # never races a rehash (first message from a new peer mid-resize)
        with self._tables_lock:
            return table[peer]

    def _children(self, peer: PeerID) -> tuple:
        kids = self._reg_children.get(peer)
        if kids is None:
            label = str(peer)
            kids = tuple(f.labels(label) for f in self._reg_families)
            with self._tables_lock:
                kids = self._reg_children.setdefault(peer, kids)
        return kids

    def _snapshot(self, table):
        with self._tables_lock:
            return sorted(table.items(), key=lambda kv: str(kv[0]))

    def sent(self, peer: PeerID, n: int) -> None:
        self._counter(self._egress, peer).add(n)
        if self._reg_families is not None:
            ebytes, _, emsgs, _ = self._children(peer)
            ebytes.inc(n)
            emsgs.inc()

    def received(self, peer: PeerID, n: int) -> None:
        self._counter(self._ingress, peer).add(n)
        if self._reg_families is not None:
            _, ibytes, _, imsgs = self._children(peer)
            ibytes.inc(n)
            imsgs.inc()

    def egress_totals(self) -> Dict[PeerID, int]:
        return {p: c.total for p, c in self._snapshot(self._egress)}

    def egress_rates(self, peers: List[PeerID]) -> List[float]:
        """Rates aligned to a rank order (parity: GetEgressRates)."""
        with self._tables_lock:
            table = dict(self._egress)
        return [table[p].rate() if p in table else 0.0 for p in peers]

    def ingress_rates(self, peers: List[PeerID]) -> List[float]:
        with self._tables_lock:
            table = dict(self._ingress)
        return [table[p].rate() if p in table else 0.0 for p in peers]

    def render_rates(self) -> str:
        """Windowed-rate gauges (not plain registry samples: the window is
        computed at scrape time)."""
        lines = []
        for name, table in (("egress", self._egress), ("ingress", self._ingress)):
            lines.append(f"# TYPE kungfu_{name}_rate gauge")
            for p, c in self._snapshot(table):
                lines.append(f'kungfu_{name}_rate{{peer="{p}"}} {c.rate():.1f}')
        return "\n".join(lines) + "\n"

    def render_metrics(self) -> str:
        """Prometheus-style exposition (parity: monitor/server.go):
        byte totals plus the rate block shared with render_rates()."""
        lines = []
        for name, table in (("egress", self._egress), ("ingress", self._ingress)):
            lines.append(f"# TYPE kungfu_{name}_bytes counter")
            for p, c in self._snapshot(table):
                lines.append(
                    f'kungfu_{name}_bytes{{peer="{p}"}} {c.total}'
                )
        return "\n".join(lines) + "\n" + self.render_rates()


_global_monitor: Optional[NetMonitor] = None
_monitor_lock = threading.Lock()


def get_monitor() -> NetMonitor:
    global _global_monitor
    with _monitor_lock:
        if _global_monitor is None:
            _global_monitor = NetMonitor(registry=_metrics.get_registry())
        return _global_monitor


class MetricsServer:
    """Back-compat /metrics endpoint for a standalone NetMonitor.

    Workers under a Peer get the full TelemetryServer (/metrics + /trace
    + /audit) instead; this wrapper keeps the old ``MetricsServer(mon,
    port)`` contract for embedders and serves the monitor's own
    exposition alongside the process registry.
    """

    def __init__(self, monitor: NetMonitor, port: int):
        from kungfu_tpu.telemetry.http import TelemetryServer

        reg = _metrics.get_registry()
        self._srv = TelemetryServer(
            port,
            extra_routes={
                # include_extras=False: render_metrics() already carries
                # this monitor's rate gauges, and when `monitor` is the
                # process singleton its renderer is ALSO attached to the
                # registry — emitting a family twice is invalid exposition
                "/metrics": lambda: (
                    monitor.render_metrics() + reg.render(include_extras=False),
                    "text/plain; version=0.0.4",
                )
            },
        )
        self.port = self._srv.port
        self.httpd = self._srv.httpd

    def start(self):
        self._srv.start()

    def stop(self):
        self._srv.stop()
