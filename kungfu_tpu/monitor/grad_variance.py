"""Cross-worker gradient-variance monitoring, fully on-device.

Capability parity: MonitorGradientVarianceOptimizer
(srcs/python/kungfu/tensorflow/optimizers/grad_variance.py) — synchronous
SGD plus a periodic estimate of the gradient variance across workers:

    Var[g] = E_workers[g^2] - (E_workers[g])^2        (per tensor)
    variance = sum over tensors of ||Var[g]||_F

TPU-first: the two cross-worker moments ride the SAME compiled step as the
gradient pmean (two extra psums fused by XLA), vs. the reference's second
group_all_reduce of squared gradients through separate CPU op kernels.
The estimate lives in the optimizer state (read with
`gradient_variance(opt_state)`); no host trip, no printing side effects.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax


class GradVarState(NamedTuple):
    variance: jnp.ndarray  # latest summed-Frobenius-norm estimate
    count: jnp.ndarray


class _MonitorState(NamedTuple):
    base: optax.OptState
    grad_var: GradVarState


def _variance_estimate(grads, avg_grads, axis_name: str) -> jnp.ndarray:
    """sum_t || E[g_t^2] - avg_t^2 ||_F across the worker axis."""
    total = jnp.zeros((), jnp.float32)
    for g, a in zip(jax.tree.leaves(grads), jax.tree.leaves(avg_grads)):
        g32 = g.astype(jnp.float32)
        a32 = a.astype(jnp.float32)
        mean_sq = lax.pmean(jnp.square(g32), axis_name)
        var = mean_sq - jnp.square(a32)
        total = total + jnp.sqrt(jnp.maximum(jnp.sum(jnp.square(var)), 0.0))
    return total


def monitor_gradient_variance(
    base: optax.GradientTransformation,
    axis_name: str = "dp",
    interval: int = 1,
) -> optax.GradientTransformation:
    """S-SGD + cross-worker gradient variance (parity:
    MonitorGradientVarianceOptimizer). Must run inside shard_map over
    `axis_name`; `interval` thins the estimate like the reference's
    monitor_interval."""

    def init(params):
        return _MonitorState(
            base=base.init(params),
            grad_var=GradVarState(
                variance=jnp.zeros((), jnp.float32), count=jnp.zeros((), jnp.int32)
            ),
        )

    def update(grads, state, params=None, **extra):
        avg = jax.tree.map(lambda g: lax.pmean(g, axis_name), grads)
        if interval == 1:
            est = _variance_estimate(grads, avg, axis_name)
        else:
            # lax.cond (not where): the estimate costs a second
            # gradient-sized cross-worker pmean per leaf, so off-interval
            # steps must SKIP the collectives, not discard their result.
            # The predicate is replicated (derived from the replicated
            # count), so every worker takes the same branch.
            est = lax.cond(
                jnp.mod(state.grad_var.count, interval) == 0,
                lambda: _variance_estimate(grads, avg, axis_name),
                lambda: state.grad_var.variance,
            )
        gv = GradVarState(variance=est, count=state.grad_var.count + 1)
        updates, base_state = base.update(avg, state.base, params, **extra)
        return updates, _MonitorState(base=base_state, grad_var=gv)

    return optax.GradientTransformation(init, update)


def gradient_variance(opt_state) -> jnp.ndarray:
    """Read the latest variance estimate out of a monitored optimizer
    state."""
    return opt_state.grad_var.variance


def publish_gradient_variance(opt_state) -> float:
    """Pull the variance estimate to the host and publish it as the
    ``kungfu_gradient_variance`` gauge; returns the value. Call at a
    logging cadence — this is an explicit device -> host transfer."""
    from kungfu_tpu.telemetry import metrics as _tm

    val = float(gradient_variance(opt_state))
    _tm.gauge(
        "kungfu_gradient_variance",
        "Cross-worker gradient variance (summed Frobenius norm)",
    ).set(val)
    return val
