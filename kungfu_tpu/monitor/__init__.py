"""Monitors: gradient noise scale (device plane) + network rates (host).

Lazy re-exports (PEP 562): `noise_scale` drags in jax.numpy machinery
(~330 ms even with jax itself already imported), and the TRANSPORT
imports this package for `monitor.net` on every Peer construction — an
eager import here put a third of a second inside every elastic joiner's
critical path (measured round 5, bench_resize).
"""

__all__ = ["GNSState", "gns_init", "gns_update", "monitor_gradient_noise_scale"]


def __getattr__(name):
    if name in __all__:
        from kungfu_tpu.monitor import noise_scale

        return getattr(noise_scale, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
