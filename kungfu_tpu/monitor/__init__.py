"""Monitors: gradient noise scale / variance (device plane) + network
rates (host plane), publishing into the shared telemetry registry.

Lazy re-exports (PEP 562): `noise_scale`/`grad_variance` drag in
jax.numpy machinery (~330 ms even with jax itself already imported),
and the TRANSPORT imports this package for `monitor.net` on every Peer
construction — an eager import here put a third of a second inside
every elastic joiner's critical path (measured round 5, bench_resize).
"""

import importlib

# "noise_scale" (the function) is deliberately NOT re-exported: the name
# would shadow the submodule of the same name — import it from
# kungfu_tpu.monitor.noise_scale directly
_NOISE = ("GNSState", "gns_init", "gns_update", "monitor_gradient_noise_scale",
          "publish_noise_scale")
_VARIANCE = ("monitor_gradient_variance", "gradient_variance",
             "publish_gradient_variance")

__all__ = list(_NOISE + _VARIANCE) + ["cluster_health"]


def cluster_health(max_age: float = 5.0) -> dict:
    """Cluster-level health signals for the adaptation layer (ISSUE 2).

    Returns the flattened ``cluster/*`` signal dict derived from the
    runner-side TelemetryAggregator's snapshot: straggler list, per-peer
    straggler scores, step-time skew, RTT outliers, and whether THIS
    worker is flagged. In the runner process it reads the in-process
    aggregator; in a worker it polls the watcher's ``/cluster/health``
    endpoint (``KF_CLUSTER_HEALTH_URL``, injected at spawn) with an
    ``max_age``-second cache. Empty dict when no cluster plane is up.
    """
    mod = importlib.import_module("kungfu_tpu.telemetry.cluster")
    return mod.health_signals(max_age)


def __getattr__(name):
    # importlib (NOT `from ... import`): "noise_scale" names both the
    # submodule and a lazy attribute, and a from-import would re-enter
    # this __getattr__ for it — infinite recursion
    if name in _NOISE:
        mod = importlib.import_module("kungfu_tpu.monitor.noise_scale")
        return getattr(mod, name)
    if name in _VARIANCE:
        mod = importlib.import_module("kungfu_tpu.monitor.grad_variance")
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
