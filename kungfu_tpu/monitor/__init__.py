from kungfu_tpu.monitor.noise_scale import (
    GNSState,
    gns_init,
    gns_update,
    monitor_gradient_noise_scale,
)

__all__ = ["GNSState", "gns_init", "gns_update", "monitor_gradient_noise_scale"]
