"""Developer tooling: project-specific static analysis (kfcheck) and
runtime debug instrumentation (lockwatch).

Nothing here is imported by the training path unless the operator asks
for it: `python -m kungfu_tpu.devtools.kfcheck` is the analyzer's entry
point, and `kungfu_tpu/__init__` imports lockwatch only under a truthy
`KF_DEBUG_LOCKS`. See docs/devtools.md.
"""
